"""Master server: heartbeat ingest, client vid-map push, assign/lookup.

Reference: weed/server/master_server.go:83, master_grpc_server.go:62
(SendHeartbeat), :253 (KeepConnected), master_grpc_server_assign.go:38
(Assign), master_grpc_server_volume.go:186 (LookupEcVolume). Single-leader
for now (the raft seam is `is_leader`; a lease/raft backend plugs in there —
reference runs seaweedfs/raft or hashicorp/raft).
"""

from __future__ import annotations

import queue
import random
import threading
import time

from ..pb import master_pb2 as pb
from ..storage.types import TTL, ReplicaPlacement, file_id
from ..utils import failpoints
from ..utils.log import logger
from ..utils.rpc import MASTER_SERVICE, RpcService, Stub, VOLUME_SERVICE, serve
from .sequencer import MemorySequencer, SnowflakeSequencer
from .topology import EcShardInfo, Topology, VolumeInfo
from .volume_growth import GrowRequest, VolumeGrowth
from .volume_layout import LayoutRegistry

log = logger("master")


class MasterServer:
    def __init__(self, ip: str = "127.0.0.1", port: int = 9333,
                 volume_size_limit_mb: int = 30_000,
                 default_replication: str = "000",
                 sequencer: str = "memory",
                 pulse_seconds: float = 5.0,
                 garbage_threshold: float = 0.3,
                 guard=None, http_port: int | None = None,
                 peers: list[str] | None = None,
                 raft_state_path: str | None = None,
                 maintenance_scripts: "list[str] | None" = None,
                 maintenance_interval_s: float | None = None,
                 maintenance_initial_delay_s: float | None = None,
                 maintenance_health_driven: bool = True,
                 metrics_gateway: str = "", metrics_interval_s: int = 15,
                 ec_parity_shards: int | None = None,
                 lifecycle_policy: str = "",
                 slo_policy: str = "",
                 link_costs: str = "",
                 telemetry_interval_s: float | None = None):
        self.ip = ip
        self.port = port
        self.address = f"{ip}:{port}"
        self.topo = Topology(volume_size_limit_mb * 1024 * 1024)
        self.layouts = LayoutRegistry(self.topo)
        self.growth = VolumeGrowth(self.topo, allocate_fn=self._allocate_volume,
                                   costs_fn=lambda: self.link_costs)
        # per-layout cooldown after a failed writableVolumeCount grow
        # (monotonic deadline); without it every assign on a full
        # cluster re-runs a doomed topology-wide allocation sweep
        self._want_growth_backoff: dict[tuple, float] = {}
        self.sequencer = (SnowflakeSequencer() if sequencer == "snowflake"
                          else MemorySequencer())
        self.default_replication = default_replication
        self.pulse_seconds = pulse_seconds
        self.garbage_threshold = garbage_threshold
        # Multi-master: a raft quorum elects the leader and replicates
        # MaxVolumeId (reference raft_server.go FSM); single master runs
        # leaderless-raft-free with is_leader pinned True.
        self.peers = [p for p in (peers or []) if p] or [self.address]
        self.raft = None
        self._follower = None   # FollowerVidCache when raft is on
        self._raft_state_path = raft_state_path
        # Optional security.Guard: when its signing_key is set, Assign
        # responses carry a single-fid JWT the volume server will demand
        # (reference master_grpc_server_assign.go JWT minting).
        self.guard = guard
        self._subscribers: dict[int, tuple[str, queue.Queue]] = {}
        # sid -> (address, client_type, version, created_at_ns,
        # grpc_port): the cluster membership ListClusterNodes reports
        # (reference cluster.go:104 tracks filers/brokers the same way)
        self._sub_meta: dict[int, tuple[str, str, str, int, int]] = {}
        self._sub_seq = 0
        self._sub_lock = threading.Lock()
        self._admin_locks: dict[str, tuple[int, int, str]] = {}  # name -> (token, ts, client)
        # HTTP status/metrics API (reference master_server_handlers*.go);
        # 0/None disables. gRPC stays on `port`, HTTP on its own port.
        self.http_port = http_port
        # (leader_grpc, leader_http) advertised through the raft FSM by
        # each new leader: followers serve it in 421 bodies so HTTP
        # clients (shell -url fetches) can follow to the leader without
        # guessing its HTTP port from a gRPC hint
        self._leader_http_hint: tuple[str, str] = ("", "")
        self._grpc = None
        self._http = None
        self._http_stop = None
        # profiling plane: loop-lag probe on the fastweb HTTP loop +
        # the process-shared continuous sampler (start()/stop())
        from ..profiling import LoopLagMonitor
        self._loop_lag = LoopLagMonitor("master")
        self._sampler = None
        self._stop = threading.Event()
        # optional push-gateway loop; started in start(), joined in stop()
        self.metrics_gateway = metrics_gateway
        self.metrics_interval_s = metrics_interval_s
        self._metrics_push = None
        # Self-driving maintenance (reference startAdminScripts
        # master_server.go:269): [] disables, None -> repair/balance defaults.
        # DisableVacuum/EnableVacuum RPC toggle: suppresses the cron's
        # vacuum line only (reference command_volume_vacuum_disable.go:
        # "volume.vacuum still works"). In-memory per-master, NOT raft-
        # replicated or persisted — matching the reference, whose flag is
        # a plain topology bool (topology.go:42 isDisableVacuum); operators
        # re-disable after a failover.
        self.vacuum_disabled = False
        # Health plane (master/health.py): scores the topology into
        # severity buckets every janitor tick and on /cluster/health.
        # Heartbeats don't carry RS(k,m), so the engine derives k from
        # each volume's observed stripe width minus the configured
        # parity count (fork default RS(14,2)).
        # Fid-range leases (batched ingest): Assign(count=N) is a lease —
        # the registry tracks outstanding grants for the
        # SeaweedFS_fid_leases_active gauge and supplies the TTL the
        # HTTP assign response advertises / the range JWT expires at.
        from .lease import FidLeaseRegistry
        self.fid_leases = FidLeaseRegistry()
        from .health import DEFAULT_PARITY_SHARDS, HealthEngine
        self.health = HealthEngine(
            self.topo,
            parity=(ec_parity_shards if ec_parity_shards is not None
                    else DEFAULT_PARITY_SHARDS),
            # stale = several missed pulses; stream death already
            # unregisters dead nodes, this catches wedged-but-connected
            stale_after_s=max(4 * pulse_seconds, 5.0))
        from .admin_cron import DEFAULT_INTERVAL_S, AdminCron
        # Tiered-storage lifecycle (lifecycle/): a policy FILE path
        # wires `lifecycle.apply` into the maintenance cron, so cooling
        # collections EC-encode, offload to the remote tier and promote
        # back on heat with zero operator commands. Served (with recent
        # transitions) at /debug/lifecycle.
        self.lifecycle_policy = lifecycle_policy
        if lifecycle_policy:
            import shlex as _shlex
            from .admin_cron import DEFAULT_SCRIPTS
            maintenance_scripts = list(
                DEFAULT_SCRIPTS if maintenance_scripts is None
                else maintenance_scripts)
            if not any(s.split()[:1] == ["lifecycle.apply"]
                       for s in maintenance_scripts):
                maintenance_scripts.append(
                    "lifecycle.apply -policy "
                    + _shlex.quote(lifecycle_policy))
        # health-driven: each sweep consumes the in-process engine's
        # report and runs planner->executor (maintenance/) in place of
        # the blind ec.rebuild / volume.fix.replication lines, falling
        # back to them if the scan itself fails
        self.admin_cron = AdminCron(
            self.address, scripts=maintenance_scripts,
            interval_s=maintenance_interval_s or DEFAULT_INTERVAL_S,
            initial_delay_s=maintenance_initial_delay_s,
            is_leader=lambda: self.is_leader,
            vacuum_enabled=lambda: not self.vacuum_disabled,
            health_fetch=(self.health.scan if maintenance_health_driven
                          else None),
            costs_fn=lambda: self.link_costs)
        # Fleet telemetry & SLO plane (telemetry/): a leader-resident
        # collector scrapes every node's exposition into a ring TSDB,
        # merges histograms into cluster percentiles, tracks heavy
        # hitters and evaluates burn-rate alerts. Follows raft
        # leadership exactly like the admin cron. `slo_policy` is a
        # JSON file path or inline JSON doc of objectives.
        self.slo_policy_source = slo_policy
        from ..telemetry import TelemetryCollector, parse_slo_policy
        policy = None
        if slo_policy:
            doc = slo_policy
            if not slo_policy.lstrip().startswith("{"):
                with open(slo_policy, encoding="utf-8") as f:
                    doc = f.read()
            policy = parse_slo_policy(doc)
        # Geo plane (geo/): the per-link cost model prices replica
        # growth, EC spread, repair fetches and balance moves in
        # cost-weighted bytes (intra_rack < cross_rack < cross_dc).
        # Same inline-JSON-or-file convention as -sloPolicy; the parsed
        # model feeds the placement engine, the raw doc is served at
        # /cluster/linkcosts so shell planners price moves identically.
        self.link_costs_source = link_costs
        from ..geo.policy import LinkCostModel, load_link_costs
        self.link_costs = (load_link_costs(link_costs) if link_costs
                           else LinkCostModel())
        self.telemetry = TelemetryCollector(
            node_id=f"master@{self.address}",
            targets_fn=self._telemetry_targets,
            is_leader=lambda: self.is_leader,
            interval_s=telemetry_interval_s,
            slo_policy=policy,
            local_scrape=self._local_scrape,
            health_stale_fn=self._telemetry_stale_nodes)
        # burning SLOs become health items: the cluster verdict reflects
        # user-facing objectives, not just structural integrity
        self.health.extra_items = self.telemetry.health_items

    @property
    def is_leader(self) -> bool:
        return self.raft.is_leader if self.raft is not None else True

    @property
    def leader_address(self) -> str:
        """Current known leader; empty during elections (clients treat an
        empty hint as 'retry elsewhere' rather than pinning a follower)."""
        if self.raft is None:
            return self.address
        if self.raft.is_leader:
            return self.address
        return self.raft.leader_address or ""

    # -- telemetry wiring ---------------------------------------------------
    def _telemetry_targets(self) -> list[dict]:
        """Scrape targets from live cluster membership: volume servers
        come from the heartbeat-fed topology, filers from the
        KeepConnected subscriber metadata (their metrics live under
        /__metrics__ because / is the filesystem namespace)."""
        targets = []
        for n in self.topo.all_nodes():
            targets.append({"node": f"volume@{n.id}",
                            "url": f"http://{n.url}/metrics",
                            "dc": n.rack.dc.id if n.rack else "",
                            "rack": n.rack.id if n.rack else ""})
        with self._sub_lock:
            metas = list(self._sub_meta.values())
        for address, client_type, _ver, _ts, _grpc in metas:
            if client_type == "filer" and address:
                targets.append({"node": f"filer@{address}",
                                "url": f"http://{address}/__metrics__"})
        return targets

    def _local_scrape(self) -> str:
        from ..stats import scrape_payload
        body, _ctype = scrape_payload()
        return body

    def _telemetry_stale_nodes(self) -> list[str]:
        """Health-plane staleness (missed heartbeats) -> telemetry node
        ids, so dead volume servers drop out of cluster merges even
        before their scrapes start failing."""
        report = self.health.last_report()
        return [f"volume@{nd['id']}" for nd in report.get("nodes", ())
                if nd.get("stale")]

    def _raft_apply(self, command: dict) -> None:
        """FSM apply (reference raft_server.go:53 StateMachine.Apply).
        Runs on every master as entries commit (the leader included), so
        all replicated control state lives here:

        - max_volume_id: vid allocation stays monotonic across leader
          changes (the reference FSM's only state).
        - seq_hwm: the sequencer high-water mark. The leader commits
          `key + count` BEFORE handing out [key, key+count), so a new
          leader's sequencer always starts past every range ever acked —
          zero duplicate fids across failovers, even when the granting
          leader died mid-lease-window.
        - lease: fid-range grant bookkeeping, so the leases-active gauge
          is correct on whichever master is scraped / becomes leader.
        - volume_new: layout mutations from growth, so a new leader's
          layout registry is warm before the first heartbeats arrive
          (locations still come from heartbeats; register is idempotent
          and the janitor drops locationless vids from writables).

        Lock order here is raft._lock -> {topo.lock, sequencer._lock,
        fid_leases._lock}; no path takes them in reverse."""
        mvid = command.get("max_volume_id")
        if mvid:
            with self.topo.lock:
                self.topo.max_volume_id = max(self.topo.max_volume_id, mvid)
        hwm = command.get("seq_hwm")
        if hwm:
            # set_max(seen) bumps past `seen`: next_id() returns >= hwm
            self.sequencer.set_max(hwm - 1)
        lease = command.get("lease")
        if lease:
            self.fid_leases.grant_replicated(int(lease.get("count", 1)),
                                             lease.get("ttl_s"))
        lh = command.get("leader_http")
        if lh:
            self._leader_http_hint = (lh.get("grpc", ""), lh.get("http", ""))
        vol = command.get("volume_new")
        if vol:
            v = VolumeInfo(
                id=int(vol["id"]), collection=vol.get("collection", ""),
                replica_placement=ReplicaPlacement.parse(
                    vol.get("replication", "")),
                ttl=TTL.parse(vol.get("ttl", "")),
                disk_type=vol.get("disk_type", "hdd") or "hdd")
            self.layouts.register_volume(v)

    def _on_raft_state(self, role: str, term: int,
                       leader: "str | None") -> None:
        """Published from the raft _run loop (outside the raft lock)
        whenever (role, term, leader) changes: step the maintenance
        plane up/down and point the follower read cache at the new
        leader promptly instead of on its next poll."""
        lead = role == "leader"
        log.info("%s: raft %s (term %d, leader %s)", self.address, role,
                 term, leader or "?")
        if lead:
            # stale growth backoffs from a previous leadership stint
            # must not delay this leader's first growth
            self._want_growth_backoff.clear()
            if self.http_port:
                # advertise this leader's HTTP address through the FSM
                # (propose blocks on commit, so not on the raft loop)
                threading.Thread(
                    target=self._advertise_leader_http, daemon=True,
                    name="leader-http-advertise").start()
        self.admin_cron.notify_leadership(lead)
        self.telemetry.notify_leadership(lead)
        if self._follower is not None:
            self._follower.poke()

    def _advertise_leader_http(self) -> None:
        if self.raft is None:
            return
        try:
            self.raft.propose({"leader_http": {
                "grpc": self.address,
                "http": f"{self.ip}:{self.http_port}"}})
        except Exception as e:  # noqa: BLE001 — best-effort hint
            log.warning("leader http advertise failed: %s", e)

    def lookup_locations(self, vid: int) -> "tuple[list[dict] | None, str]":
        """(locations, source) for a vid. The leader answers from its
        heartbeat-fed topology (`topo`); a follower answers from the
        replicated read cache (`follower`, bounded staleness). (None,
        "redirect") means the caller must send the client to the leader —
        a follower never serves an authoritative miss (write barrier);
        (None, "miss") is the leader's authoritative not-found."""
        if self.is_leader or self.raft is None:
            nodes = self.topo.lookup(vid)
            if nodes:
                return ([{"url": n.url, "public_url": n.public_url,
                          "grpc_port": n.grpc_port} for n in nodes], "topo")
            return (None, "miss")
        # a deposed leader's topology is stale until its heartbeat
        # streams die; only the replicated cache is staleness-bounded
        if self._follower is not None:
            locs = self._follower.lookup(vid)
            if locs:
                return (locs, "follower")
        return (None, "redirect")

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        from ..profiling import acquire_sampler
        self._sampler = acquire_sampler()
        svc = self._build_service()
        services = [svc]
        if len(self.peers) > 1:
            from .follower import FollowerVidCache
            from .raft import RaftNode
            self.raft = RaftNode(self.address, self.peers,
                                 self._raft_apply,
                                 state_path=self._raft_state_path)
            self.raft.on_state_change = self._on_raft_state
            # while we are NOT the leader, mirror the leader's vid map so
            # /dir/lookup can be served here (bounded staleness)
            self._follower = FollowerVidCache(
                self.address,
                leader_of=lambda: (None if self.raft.is_leader
                                   else self.raft.leader_address))
            services.append(self.raft.build_service())
        key = self.guard.signing_key if self.guard is not None else ""
        if key:
            from ..utils.rpc import set_cluster_key
            set_cluster_key(key)
        self._grpc = serve(f"{self.ip}:{self.port}", services, auth_key=key)
        if self.raft is not None:
            self.raft.start()
            self._follower.start()
        if self.http_port:
            self._start_http()
        threading.Thread(target=self._janitor, daemon=True,
                         name="master-janitor").start()
        self.admin_cron.start()
        self.telemetry.start()
        if self.metrics_gateway:
            from ..stats import start_push_loop
            self._metrics_push = start_push_loop(
                self.metrics_gateway, f"master-{self.address}",
                self.metrics_interval_s)
        log.info("master up at %s (leader)", self.address)

    def stop(self) -> None:
        self._stop.set()
        self.admin_cron.stop()
        self.telemetry.stop()
        if self._metrics_push is not None:
            self._metrics_push.stop()
        if self._follower is not None:
            self._follower.stop()
        if self.raft is not None:
            self.raft.stop()
        if self._grpc:
            self._grpc.stop(grace=0.5)
        if self._http_stop is not None:
            self._http_stop.set()
        self._loop_lag.close()
        if getattr(self, "_sampler", None) is not None:
            from ..profiling import release_sampler
            release_sampler()
            self._sampler = None

    def _start_http(self) -> None:
        """Status/metrics HTTP API (reference master_server_handlers.go:
        /dir/status topology dump, /dir/assign, /dir/lookup, /metrics).

        Served by utils/fastweb so keep-alive /dir/assign costs ~100 us
        round-trip — high-rate small-file writers assign here instead of
        paying Python-grpcio's ~300 us unary overhead."""
        import urllib.parse as _up

        from google.protobuf.json_format import MessageToDict

        from ..utils import fastweb
        from ..utils.fastweb import json_response

        ms = self

        def params_of(req: fastweb.Request) -> dict:
            # form-encoded bodies merge into the query params (the
            # reference Go master reads both via r.FormValue)
            q = req.query
            ctype = req.headers.get("Content-Type", "")
            if req.body and "application/x-www-form-urlencoded" in ctype:
                q = dict(q)
                q.update(_up.parse_qsl(req.body.decode(errors="replace")))
            return q

        def guarded(path: str, handler):
            # The reference wraps master HTTP handlers in guard.WhiteList
            # only; JWT gating applies just to the mutating /dir/assign.
            # /metrics stays open for scrapers. Params are parsed once and
            # handed to the handler (the assign hot path budget is ~100us).
            def h(req: fastweb.Request):
                q = params_of(req)
                if ms.guard is not None:
                    if path == "/dir/assign":
                        ok, why = ms.guard.check_write(req.remote, q,
                                                       req.headers)
                    else:
                        ok, why = ms.guard.check_ip(req.remote)
                    if not ok:
                        return json_response({"error": why}, status=401)
                return handler(req, q)
            return h

        # Handler policy on the single-loop fastweb server: the hot/cheap
        # handlers (assign, lookup, metrics, cluster status) run inline —
        # they are microseconds and an executor hop would double the
        # /dir/assign fast path's cost. Anything that can take visible
        # time (profiling, full-topology dumps, the HTML UI) is offloaded
        # to a thread so it cannot head-of-line-block assigns.
        def offloaded(handler):
            import asyncio
            import contextvars

            async def h(req):
                # carry the active trace span across the executor hop
                ctx = contextvars.copy_context()
                return await asyncio.get_running_loop().run_in_executor(
                    None, ctx.run, handler, req)
            return h

        def metrics(req):
            from ..stats import scrape_payload
            body, ctype = scrape_payload(req.headers.get("Accept", ""))
            return fastweb.Response(body.encode(), content_type=ctype)

        def debug_traces(req, q):
            from .. import tracing
            return json_response(tracing.debug_traces_payload(q))

        def debug_events(req, q):
            from ..ops import events
            return json_response(events.debug_events_payload(q))

        def cluster_health(req, q):
            # a fresh scan per request: the operator asking "is data at
            # risk NOW" must not get a stale janitor-tick answer
            return json_response(ms.health.scan())

        def cluster_telemetry(req, q):
            # leader-resident: only the leader scrapes the fleet, so a
            # follower redirects (421 + hint) like the write paths
            if not ms.is_leader:
                return not_leader_response()
            if q.get("trigger"):
                # force one scrape/evaluate cycle now (tests, bench and
                # `cluster.top -watch` first paint all need fresh data
                # without waiting out the jittered interval)
                ms.telemetry.trigger()
            try:
                top = int(q.get("top", "10") or 10)
            except ValueError:
                top = 10
            # ?profile=1 folds the fleet-merged flamegraph into the
            # snapshot (cluster.profile's fetch); off by default — the
            # folded stacks dwarf the rest of the payload
            return json_response(ms.telemetry.snapshot(
                top_limit=top, include_profile=bool(q.get("profile"))))

        def dir_status(req, q):
            # leader_address, not ms.address: a follower answering here
            # must hint at the real leader (empty mid-election)
            return json_response({"Topology": MessageToDict(ms.topology_info()),
                                  "Leader": ms.leader_address,
                                  "IsLeader": ms.is_leader})

        def not_leader_response():
            # typed redirect: 421 Misdirected Request + the leader hint
            # in the body (the hint is a gRPC address, so no Location
            # header — master_client follows the `leader` field)
            hint = ms.leader_address
            # FSM-advertised HTTP address, served only while it matches
            # the CURRENT leader (a hint from a deposed leader would
            # bounce the client to another follower at best)
            lh_grpc, lh_http = ms._leader_http_hint
            return json_response(
                {"error": (f"not leader; leader is {hint}" if hint
                           else "not leader; leader unknown"),
                 "leader": hint,
                 "leader_http": (lh_http if hint and lh_grpc == hint
                                 else "")}, status=421)

        def dir_lookup(req, q):
            from .. import tracing
            from ..stats import MASTER_LOOKUP_COUNTER
            with tracing.start_span(
                    "master.lookup", component="master",
                    child_of=tracing.extract(req.headers),
                    attrs={"vid": q.get("volumeId", "")}):
                vid = q.get("volumeId", "").split(",")[0]
                try:
                    locs, source = ms.lookup_locations(int(vid))
                except ValueError:
                    locs, source = None, "miss"
                MASTER_LOOKUP_COUNTER.inc(source)
                if locs:
                    body = {"volumeId": vid,
                            "locations": [{"url": l["url"],
                                           "publicUrl": l["public_url"]}
                                          for l in locs]}
                    if source == "follower":
                        # bounded-staleness answer from a non-leader:
                        # advertise where authority lives
                        body["leader"] = ms.leader_address
                    return json_response(body)
                if source == "redirect":
                    # write barrier: a follower never 404s a vid — the
                    # assign may simply not have replicated here yet
                    return not_leader_response()
                return json_response(
                    {"error": f"volume {vid} not found"}, status=404)

        async def dir_assign(req, q):
            from .. import tracing
            with tracing.start_span(
                    "master.assign", component="master",
                    child_of=tracing.extract(req.headers),
                    attrs={"collection": q.get("collection", "")}) as sp:
                try:
                    areq = pb.AssignRequest(
                        count=int(q.get("count", 1)),
                        collection=q.get("collection", ""),
                        replication=q.get("replication", ""),
                        ttl=q.get("ttl", ""),
                        disk_type=q.get("disk_type", ""),
                        # placement preferences (reference
                        # /dir/assign?dataCenter=&rack=): honored by
                        # VolumeGrowth when the assign has to grow
                        data_center=q.get("dataCenter", ""),
                        rack=q.get("rack", ""),
                        writable_volume_count=int(
                            q.get("writableVolumeCount", 0)))
                except ValueError as e:
                    # malformed numerics are a deterministic client
                    # error, not a retryable 500
                    return json_response({"error": f"bad assign: {e}"},
                                         status=400)
                # executor dispatches carry the contextvars context so
                # the growth path's AllocateVolume RPCs inherit this
                # span's trace instead of starting orphan roots
                # (run_in_executor, unlike asyncio.to_thread, does not
                # copy the context)
                import contextvars

                if ms.raft is not None or ms.needs_growth(areq):
                    # growth does AllocateVolume RPCs + a raft commit —
                    # seconds, not microseconds: run it off-loop so other
                    # assigns/lookups/scrapes aren't head-of-line blocked.
                    # With raft on, EVERY assign commits its fid range
                    # through the log (quorum RPCs that can block for the
                    # propose timeout during an election) — so the whole
                    # raft-mode assign path runs off-loop too; follower
                    # lookups stay responsive through election storms.
                    import asyncio
                    if ms.raft is None:
                        sp.add_event("volume_growth")
                    resp = await asyncio.get_running_loop().run_in_executor(
                        None, contextvars.copy_context().run,
                        ms.do_assign, areq)
                else:
                    # inline fast path NEVER grows: a concurrent assign may
                    # have filled the last writable between the check above
                    # and here (TOCTOU) — the sentinel re-dispatches that
                    # loser to the executor instead of blocking the loop
                    resp = ms.do_assign(areq, allow_growth=False)
                    if resp.error == ms.NEEDS_GROWTH:
                        import asyncio
                        sp.add_event("volume_growth")
                        resp = await asyncio.get_running_loop(
                            ).run_in_executor(
                                None, contextvars.copy_context().run,
                                ms.do_assign, areq)
                if resp.error:
                    sp.set_error(resp.error)
                    if resp.error.startswith("not leader"):
                        return not_leader_response()
                    return json_response({"error": resp.error}, status=406)
                sp.set_attr("fid", resp.fid)
                body = {
                    "fid": resp.fid, "count": resp.count,
                    "url": resp.location.url,
                    "publicUrl": resp.location.public_url,
                    "auth": resp.auth}
                if resp.count > 1:
                    # fid-range lease: spell the range out so clients
                    # need no fid arithmetic of their own — first key as
                    # hex (snowflake keys overflow JSON float precision),
                    # the shared cookie, the advertised TTL, and the
                    # replica set the lease's volume lives on
                    from ..storage.types import parse_file_id
                    vid, key, cookie = parse_file_id(resp.fid)
                    body.update({
                        "keyHex": f"{key:x}", "cookie": cookie,
                        "leaseTtlS": ms.fid_leases.ttl_s,
                        "replicas": [{"url": r.url,
                                      "publicUrl": r.public_url}
                                     for r in resp.replicas]})
                return json_response(body)

        def cluster_status(req, q):
            # `leader` (lowercase) is the stable client-facing hint the
            # redirect protocol uses; `Leader` stays for the reference-
            # compatible status shape
            return json_response({
                "IsLeader": ms.is_leader,
                "Leader": ms.leader_address,
                "leader": ms.leader_address,
                "Peers": [p for p in ms.peers if p != ms.address]})

        def ui(req, q):
            # human status UI (reference weed/server/master_ui)
            from ..utils.ui import render_page
            rows = []
            with ms.topo.lock:  # heartbeats mutate per-disk dicts
                nodes = list(ms.topo.all_nodes())
                for node in nodes:
                    vols = list(node.all_volumes())
                    ecs = list(node.all_ec_shards())
                    rack = getattr(node.rack, "id", "-") or "-"
                    rows.append([
                        node.id, rack, len(vols), len(ecs),
                        f"{sum(v.size for v in vols) >> 20} MB"])
            page = render_page(
                f"swtpu master {ms.address}",
                {"Leader": ms.leader_address or "(electing)",
                 "IsLeader": ms.is_leader,
                 "Peers": ", ".join(p for p in ms.peers
                                    if p != ms.address) or "-",
                 "Volume servers": len(nodes),
                 "Max volume id": ms.topo.max_volume_id,
                 "Vacuum automation":
                     "disabled" if ms.vacuum_disabled else "on"},
                [("Volume servers",
                  ["node", "rack", "volumes", "ec volumes", "bytes"], rows)])
            return fastweb.html_response(page)

        def debug_profile(req, q):
            # pprof-style CPU profile trigger (reference exposes
            # net/http/pprof on -debug.port, command/imports.go:4);
            # shared implementation (profiling.handle_profile_query):
            # seconds validation/clamp, continuous/summary modes, hz
            # retune — all four daemons serve the identical contract
            from .. import profiling as prof
            code, ctype, body = prof.handle_profile_query(q)
            return fastweb.Response(body.encode(), status=code,
                                    content_type=ctype)

        def debug_flight(req, q):
            # slowest/errored request ring (profiling/flight.py) —
            # mostly volume-server entries in real deployments, but the
            # endpoint exists on every daemon so the operator never
            # guesses which port carries it
            from .. import profiling as prof
            code, payload = prof.debug_flight_payload(q)
            return json_response(payload, status=code)

        def debug_locks(req, q):
            # lock-order cycles + long holds from the SWTPU_LOCKCHECK=1
            # runtime detector (utils/locktrack.py); cheap no-op payload
            # when the detector is off
            from ..utils import locktrack
            return json_response(locktrack.debug_locks_payload(q))

        def debug_lifecycle(req, q):
            """Lifecycle plane status: the configured policy (parsed
            fresh so edits to the file show without a restart) and the
            recent lifecycle.* journal events — the cron's transitions
            run in THIS process, so its plan/transition/skip history is
            one filter away."""
            from ..ops import events
            policy = None
            err = ""
            if ms.lifecycle_policy:
                try:
                    from ..lifecycle import parse_policy
                    policy = parse_policy(ms.lifecycle_policy).to_doc()
                except Exception as e:  # noqa: BLE001 — show, don't 500
                    err = str(e)
            qq = dict(q)
            qq["type"] = "lifecycle."
            return json_response({
                "policy": policy, "source": ms.lifecycle_policy,
                "policy_error": err,
                "recent": events.debug_events_payload(qq)})

        def cluster_linkcosts(req, q):
            """The master's parsed link-cost model, as a policy doc —
            shell balance planners fetch it so their cost-weighted plans
            match what the master's own cron would produce."""
            return json_response(ms.link_costs.to_doc())

        app = fastweb.FastApp()
        app.route("/metrics", metrics)
        app.route("/dir/status", offloaded(guarded("/dir/status", dir_status)))
        app.route("/dir/lookup", guarded("/dir/lookup", dir_lookup))
        app.route("/dir/assign", guarded("/dir/assign", dir_assign))
        app.route("/cluster/status", guarded("/cluster/status", cluster_status))
        app.route("/", offloaded(guarded("/", ui)))
        app.route("/debug/profile",
                  offloaded(guarded("/debug/profile", debug_profile)))
        # guarded like /debug/profile (flight entries carry fids, paths
        # and admit-time queue state)
        app.route("/debug/flight",
                  offloaded(guarded("/debug/flight", debug_flight)))
        # guarded like /debug/profile (spans carry fids and peer
        # addresses) and offloaded: snapshotting + serializing thousands
        # of spans must not head-of-line-block inline assigns
        app.route("/debug/traces",
                  offloaded(guarded("/debug/traces", debug_traces)))
        # same policy: events carry node addresses and volume ids, and a
        # full-topology health scan is milliseconds, not microseconds
        app.route("/debug/events",
                  offloaded(guarded("/debug/events", debug_events)))
        # guarded like the other /debug routes (stacks leak paths)
        app.route("/debug/locks",
                  offloaded(guarded("/debug/locks", debug_locks)))
        app.route("/cluster/health",
                  offloaded(guarded("/cluster/health", cluster_health)))
        app.route("/cluster/telemetry",
                  offloaded(guarded("/cluster/telemetry", cluster_telemetry)))
        app.route("/cluster/linkcosts",
                  guarded("/cluster/linkcosts", cluster_linkcosts))
        # guarded+offloaded like the other /debug routes (the journal
        # filter walks the whole ring)
        app.route("/debug/lifecycle",
                  offloaded(guarded("/debug/lifecycle", debug_lifecycle)))

        self._http_stop = threading.Event()
        threading.Thread(
            target=fastweb.serve_fast_app,
            args=(app, self.ip, self.http_port, self._http_stop),
            kwargs={"logger": log, "on_loop": self._loop_lag.attach},
            daemon=True,
            name="master-http").start()
        log.info("master http api on %s:%d", self.ip, self.http_port)

    # -- volume allocation RPC out to volume servers ------------------------
    def _allocate_volume(self, node, vid: int, req: GrowRequest) -> None:
        stub = Stub(node.grpc_address, VOLUME_SERVICE)
        from ..pb import volume_server_pb2 as vpb
        stub.call("AllocateVolume", vpb.AllocateVolumeRequest(
            volume_id=vid, collection=req.collection,
            replication=req.replication, ttl=req.ttl,
            disk_type=req.disk_type), vpb.AllocateVolumeResponse)
        # optimistic local registration; the next heartbeat confirms
        v = VolumeInfo(id=vid, collection=req.collection,
                       replica_placement=ReplicaPlacement.parse(req.replication),
                       ttl=TTL.parse(req.ttl), disk_type=req.disk_type)
        self.topo.incremental_volumes(node, [v], [])
        self.layouts.register_volume(v)
        if self.raft is not None and self.raft.is_leader:
            # replicate the layout mutation (and the vid watermark) so a
            # new leader knows this volume before its first heartbeat;
            # a failed commit is non-fatal — the volume exists on the
            # server and heartbeats will resync it
            if not self.raft.propose(
                    {"max_volume_id": self.topo.max_volume_id,
                     "volume_new": {"id": vid, "collection": req.collection,
                                    "replication": req.replication,
                                    "ttl": req.ttl,
                                    "disk_type": req.disk_type}},
                    timeout=2.0):
                log.warning("volume_new vid=%d not committed (no quorum); "
                            "heartbeats will resync", vid)
        from ..ops import events
        events.emit("volume.grow", vid=vid, collection=req.collection,
                    replication=req.replication, node=node.id)
        self._broadcast_location(node, new_vids=[vid])

    # -- broadcast to KeepConnected subscribers ------------------------------
    def _broadcast(self, msg: pb.KeepConnectedResponse) -> None:
        with self._sub_lock:
            for _, q in self._subscribers.values():
                try:
                    q.put_nowait(msg)
                except queue.Full:
                    pass

    def _broadcast_location(self, node, new_vids=(), deleted_vids=(),
                            new_ec=(), deleted_ec=()) -> None:
        self._broadcast(pb.KeepConnectedResponse(volume_location=pb.VolumeLocation(
            url=node.url, public_url=node.public_url, grpc_port=node.grpc_port,
            data_center=node.rack.dc.id if node.rack else "",
            new_vids=list(new_vids), deleted_vids=list(deleted_vids),
            new_ec_vids=list(new_ec), deleted_ec_vids=list(deleted_ec))))

    # -- gRPC service --------------------------------------------------------
    def _build_service(self) -> RpcService:
        svc = RpcService(MASTER_SERVICE)
        ms = self

        @svc.stream_stream("SendHeartbeat", pb.Heartbeat, pb.HeartbeatResponse)
        def send_heartbeat(request_iter, context):
            node = None
            try:
                for hb in request_iter:
                    from ..stats import MASTER_RECEIVED_HEARTBEATS
                    MASTER_RECEIVED_HEARTBEATS.inc()
                    node = ms._handle_heartbeat(hb, node)
                    yield pb.HeartbeatResponse(
                        volume_size_limit=ms.topo.volume_size_limit,
                        leader=ms.leader_address)
            finally:
                if node is not None:
                    vids, ec_vids = ms.topo.unregister_node(node)
                    log.info("node %s disconnected; dropped %d vols %d ec",
                             node.id, len(vids), len(ec_vids))
                    from ..ops import events
                    events.emit("node.leave", severity=events.WARN,
                                node=node.id, volumes=len(vids),
                                ec_volumes=len(ec_vids))
                    ms._broadcast_location(node, deleted_vids=vids,
                                           deleted_ec=ec_vids)

        @svc.stream_stream("KeepConnected", pb.KeepConnectedRequest,
                           pb.KeepConnectedResponse)
        def keep_connected(request_iter, context):
            first = next(iter(request_iter))
            q: queue.Queue = queue.Queue(maxsize=1024)
            with ms._sub_lock:
                ms._sub_seq += 1
                sid = ms._sub_seq
                ms._subscribers[sid] = (first.client_address, q)
                ms._sub_meta[sid] = (first.client_address,
                                     first.client_type or "client",
                                     first.version, time.time_ns(),
                                     first.grpc_port)
            log.info("client %s (%s) subscribed", first.client_address,
                     first.client_type)
            try:
                # leader hint first — a client that landed on a follower
                # must re-dial the leader for live vid-map updates
                hint = ms.leader_address
                if hint and hint != ms.address:
                    yield pb.KeepConnectedResponse(
                        volume_location=pb.VolumeLocation(leader=hint))
                # initial full vid map
                for node in ms.topo.all_nodes():
                    vids = sorted({v.id for v in node.all_volumes()})
                    ec_vids = sorted({s.volume_id for s in node.all_ec_shards()})
                    if vids or ec_vids:
                        yield pb.KeepConnectedResponse(
                            volume_location=pb.VolumeLocation(
                                url=node.url, public_url=node.public_url,
                                grpc_port=node.grpc_port,
                                new_vids=vids, new_ec_vids=ec_vids,
                                leader=ms.leader_address))
                while not ms._stop.is_set() and context.is_active():
                    try:
                        yield q.get(timeout=1.0)
                    except queue.Empty:
                        # idle keepalive carrying the current leader
                        # hint: follower read caches use it as their
                        # bounded-staleness liveness signal, and any
                        # subscriber learns of a leadership move without
                        # waiting for the next data event
                        yield pb.KeepConnectedResponse(
                            volume_location=pb.VolumeLocation(
                                leader=ms.leader_address))
            finally:
                with ms._sub_lock:
                    ms._subscribers.pop(sid, None)
                    ms._sub_meta.pop(sid, None)

        @svc.unary("Assign", pb.AssignRequest, pb.AssignResponse)
        def assign(req, context):
            return ms.do_assign(req)

        @svc.unary("LookupVolume", pb.LookupVolumeRequest, pb.LookupVolumeResponse)
        def lookup(req, context):
            failpoints.check("master.lookup")
            resp = pb.LookupVolumeResponse()
            for vf in req.volume_or_file_ids:
                entry = resp.volume_id_locations.add(volume_or_file_id=vf)
                # Full file-id lookups get a write-key token so clients can
                # delete/update (reference master_grpc_server_volume.go:102:
                # auth only when the lookup string is a file id).
                if ("," in vf and ms.guard is not None
                        and ms.guard.signing_key):
                    from ..security import gen_jwt_for_volume_server
                    entry.auth = gen_jwt_for_volume_server(
                        ms.guard.signing_key, ms.guard.expires_after_sec, vf)
                try:
                    vid = int(vf.split(",")[0])
                except ValueError:
                    entry.error = f"bad volume id {vf!r}"
                    continue
                if not ms.is_leader and ms.raft is not None:
                    # follower-served lookup from the replicated cache;
                    # miss/stale -> typed redirect (write barrier: never
                    # an authoritative not-found from a non-leader)
                    locs, source = ms.lookup_locations(vid)
                    from ..stats import MASTER_LOOKUP_COUNTER
                    MASTER_LOOKUP_COUNTER.inc(source)
                    if locs:
                        for l in locs:
                            entry.locations.add(url=l["url"],
                                                public_url=l["public_url"],
                                                grpc_port=l["grpc_port"])
                    else:
                        hint = ms.leader_address
                        entry.error = (f"not leader; leader is {hint}"
                                       if hint else
                                       "not leader; leader unknown")
                    continue
                nodes = ms.topo.lookup(vid)
                if not nodes and vid in ms.topo.ec_locations:
                    seen = set()
                    for sid_nodes in ms.topo.lookup_ec(vid).values():
                        for n in sid_nodes:
                            if n.id not in seen:
                                seen.add(n.id)
                                entry.locations.add(url=n.url,
                                                    public_url=n.public_url,
                                                    grpc_port=n.grpc_port)
                    if not seen:
                        entry.error = f"volume {vid} not found"
                    continue
                if not nodes:
                    entry.error = f"volume {vid} not found"
                    continue
                for n in nodes:
                    entry.locations.add(url=n.url, public_url=n.public_url,
                                        grpc_port=n.grpc_port)
            return resp

        @svc.unary("LookupEcVolume", pb.LookupEcVolumeRequest,
                   pb.LookupEcVolumeResponse)
        def lookup_ec(req, context):
            failpoints.check("master.lookup.ec")
            resp = pb.LookupEcVolumeResponse(volume_id=req.volume_id)
            for sid, nodes in sorted(ms.topo.lookup_ec(req.volume_id).items()):
                e = resp.shard_id_locations.add(shard_id=sid)
                for n in nodes:
                    # data_center lets repair planners classify the link
                    # to each survivor holder (geo plane fold grouping)
                    e.locations.add(url=n.url, public_url=n.public_url,
                                    grpc_port=n.grpc_port,
                                    data_center=(n.rack.dc.id
                                                 if n.rack else ""))
            return resp

        @svc.unary("Statistics", pb.StatisticsRequest, pb.StatisticsResponse)
        def statistics(req, context):
            total = used = files = 0
            for node in ms.topo.all_nodes():
                for v in node.all_volumes():
                    if req.collection and v.collection != req.collection:
                        continue
                    used += v.size
                    files += v.file_count
                for d in node.disks.values():
                    total += d.max_volume_count * ms.topo.volume_size_limit
            return pb.StatisticsResponse(total_size=total, used_size=used,
                                         file_count=files)

        @svc.unary("CollectionList", pb.CollectionListRequest,
                   pb.CollectionListResponse)
        def collection_list(req, context):
            resp = pb.CollectionListResponse()
            for c in sorted(ms.topo.collections()):
                resp.collections.add(name=c)
            return resp

        @svc.unary("CollectionDelete", pb.CollectionDeleteRequest,
                   pb.CollectionDeleteResponse)
        def collection_delete(req, context):
            """Delete every volume of a collection on every holder
            (reference master_grpc_server_collection.go)."""
            from ..pb import volume_server_pb2 as vpb
            targets = []
            with ms.topo.lock:
                for node in ms.topo.all_nodes():
                    for v in node.all_volumes():
                        if v.collection == req.name:
                            targets.append((node, v.id))
            for node, vid in targets:
                try:
                    Stub(node.grpc_address, VOLUME_SERVICE).call(
                        "VolumeDelete",
                        vpb.VolumeDeleteRequest(volume_id=vid),
                        vpb.VolumeDeleteResponse)
                except Exception as e:  # noqa: BLE001
                    log.warning("collection delete vid=%d on %s: %s",
                                vid, node.id, e)
            return pb.CollectionDeleteResponse()

        @svc.unary("EcCollectList", pb.EcCollectListRequest,
                   pb.EcCollectListResponse)
        def ec_collect_list(req, context):  # fork RPC (master.proto:28)
            cols = sorted({c for c in ms.topo.ec_collections.values()})
            return pb.EcCollectListResponse(collections=cols)

        @svc.unary("VolumeList", pb.VolumeListRequest, pb.VolumeListResponse)
        def volume_list(req, context):
            return pb.VolumeListResponse(
                topology_info=ms.topology_info(),
                volume_size_limit_mb=ms.topo.volume_size_limit >> 20)

        @svc.unary("VolumeListWithoutECVolume", pb.VolumeListWithoutECVolumeRequest,
                   pb.VolumeListResponse)
        def volume_list_no_ec(req, context):  # fork RPC (master.proto:30)
            return pb.VolumeListResponse(
                topology_info=ms.topology_info(include_ec=False),
                volume_size_limit_mb=ms.topo.volume_size_limit >> 20)

        @svc.unary("GetMasterConfiguration", pb.GetMasterConfigurationRequest,
                   pb.GetMasterConfigurationResponse)
        def get_conf(req, context):
            return pb.GetMasterConfigurationResponse(
                default_replication=ms.default_replication,
                leader=ms.leader_address,
                volume_size_limit_m_b=ms.topo.volume_size_limit >> 20)

        @svc.unary("LeaseAdminToken", pb.LeaseAdminTokenRequest,
                   pb.LeaseAdminTokenResponse)
        def lease_admin(req, context):
            now = time.monotonic_ns()  # lease age is a duration
            cur = ms._admin_locks.get(req.lock_name)
            if cur and cur[0] != req.previous_token and now - cur[1] < 60e9:
                context.abort(7, f"lock {req.lock_name} held by {cur[2]}")
            token = random.getrandbits(63)
            ms._admin_locks[req.lock_name] = (token, now, req.client_name)
            return pb.LeaseAdminTokenResponse(token=token, lock_ts_ns=now)

        @svc.unary("ReleaseAdminToken", pb.ReleaseAdminTokenRequest,
                   pb.ReleaseAdminTokenResponse)
        def release_admin(req, context):
            cur = ms._admin_locks.get(req.lock_name)
            if cur and cur[0] == req.previous_token:
                ms._admin_locks.pop(req.lock_name, None)
            return pb.ReleaseAdminTokenResponse()

        # -- vacuum automation toggle (reference DisableVacuum/EnableVacuum
        # RPCs; explicit `volume.vacuum` shell runs still work) -------------
        @svc.unary("DisableVacuum", pb.DisableVacuumRequest,
                   pb.DisableVacuumResponse)
        def disable_vacuum(req, context):
            ms.vacuum_disabled = True
            return pb.DisableVacuumResponse()

        @svc.unary("EnableVacuum", pb.EnableVacuumRequest,
                   pb.EnableVacuumResponse)
        def enable_vacuum(req, context):
            ms.vacuum_disabled = False
            return pb.EnableVacuumResponse()

        # -- raft membership (reference RaftAddServer/RaftRemoveServer/
        # RaftListClusterServers; command_cluster_raft_*.go) ----------------
        @svc.unary("RaftAddServer", pb.RaftAddServerRequest,
                   pb.RaftAddServerResponse)
        def raft_add_server(req, context):
            if ms.raft is None:
                context.abort(12, "this master runs without raft")
            if not ms.raft.is_leader:
                context.abort(9, f"not the leader; try {ms.leader_address}")
            if not ms.raft.add_server(req.address):
                context.abort(10, "membership change did not commit")
            ms.peers = list(ms.raft.cluster_members)
            return pb.RaftAddServerResponse()

        @svc.unary("RaftRemoveServer", pb.RaftRemoveServerRequest,
                   pb.RaftRemoveServerResponse)
        def raft_remove_server(req, context):
            if ms.raft is None:
                context.abort(12, "this master runs without raft")
            if not ms.raft.is_leader:
                context.abort(9, f"not the leader; try {ms.leader_address}")
            if req.id not in ms.raft.cluster_members:
                # members are keyed by address; a name that matches nothing
                # must error, not silently commit an unchanged list
                context.abort(5, f"{req.id!r} is not a member "
                                 f"(members: {ms.raft.cluster_members})")
            if not ms.raft.remove_server(req.id):
                context.abort(10, "membership change did not commit")
            ms.peers = list(ms.raft.cluster_members)
            return pb.RaftRemoveServerResponse()

        @svc.unary("RaftListClusterServers", pb.RaftListClusterServersRequest,
                   pb.RaftListClusterServersResponse)
        def raft_list_servers(req, context):
            members = (ms.raft.cluster_members if ms.raft is not None
                       else [ms.address])
            return pb.RaftListClusterServersResponse(cluster_servers=[
                pb.RaftListClusterServersResponse.ClusterServer(
                    id=m, address=m, is_leader=(m == ms.leader_address),
                    suffrage="Voter")
                for m in members])

        @svc.unary("ListClusterNodes", pb.ListClusterNodesRequest,
                   pb.ListClusterNodesResponse)
        def list_cluster_nodes(req, context):
            """Reference cluster.go ListClusterNodes: live filers/brokers
            (anything holding a KeepConnected stream) by client type."""
            with ms._sub_lock:
                metas = list(ms._sub_meta.values())
            return pb.ListClusterNodesResponse(cluster_nodes=[
                pb.ListClusterNodesResponse.ClusterNode(
                    address=addr, version=ver, created_at_ns=ts,
                    grpc_port=gport)
                for addr, ctype, ver, ts, gport in metas
                if not req.client_type or ctype == req.client_type])

        @svc.unary("Ping", pb.PingRequest, pb.PingResponse)
        def ping(req, context):
            now = time.time_ns()
            return pb.PingResponse(start_time_ns=now, remote_time_ns=now,
                                   stop_time_ns=time.time_ns())

        return svc

    # -- heartbeat handling --------------------------------------------------
    def _handle_heartbeat(self, hb: pb.Heartbeat, node):
        if node is None:
            node = self.topo.get_or_create_node(
                hb.ip, hb.port, hb.grpc_port, hb.public_url,
                hb.data_center, hb.rack, dict(hb.max_volume_counts))
            log.info("node %s registered (dc=%s rack=%s)", node.id,
                     hb.data_center, hb.rack)
            from ..ops import events
            events.emit("node.join", node=node.id,
                        dc=hb.data_center or "DefaultDataCenter",
                        rack=hb.rack or "DefaultRack")
        node.last_seen = time.monotonic()
        if hb.max_file_key:
            self.sequencer.set_max(hb.max_file_key)
            node.max_file_key = hb.max_file_key

        if hb.volumes or hb.has_no_volumes:
            vols = [VolumeInfo.from_pb(m) for m in hb.volumes]
            new, deleted = self.topo.sync_volumes(node, vols)
            for v in vols:
                self.layouts.register_volume(v)
            for v in deleted:
                self.layouts.unregister_volume(v)
            if new or deleted:
                self._broadcast_location(
                    node, new_vids=[v.id for v in new],
                    deleted_vids=[v.id for v in deleted])
        if hb.ec_shards or hb.has_no_ec_shards:
            shards = [EcShardInfo(m.id, m.collection, m.ec_index_bits,
                                  m.disk_type or "hdd", m.destroy_time)
                      for m in hb.ec_shards]
            new, deleted = self.topo.sync_ec_shards(node, shards)
            if new or deleted:
                self._broadcast_location(
                    node, new_ec=[s.volume_id for s in new],
                    deleted_ec=[s.volume_id for s in deleted])
        return node

    # -- assign --------------------------------------------------------------
    NEEDS_GROWTH = "__needs_growth__"  # internal redispatch sentinel
    _WANT_GROWTH_COOLDOWN_S = 30.0  # failed writableVolumeCount grows

    def do_assign(self, req: pb.AssignRequest,
                  allow_growth: bool = True) -> pb.AssignResponse:
        # error = master transiently refusing assigns (clients must retry
        # through the envelope); delay = overloaded leader
        failpoints.check("master.assign")
        resp = self._do_assign(req, allow_growth=allow_growth)
        if resp.error != self.NEEDS_GROWTH:
            from ..stats import MASTER_ASSIGN_COUNTER
            MASTER_ASSIGN_COUNTER.inc("error" if resp.error else "ok")
        return resp

    def needs_growth(self, req: pb.AssignRequest) -> bool:
        """True when this assign would have to grow a volume first (the
        slow path: AllocateVolume RPCs + a raft commit). The master HTTP
        handler uses this to keep no-growth assigns inline on the event
        loop and offload growth to a thread."""
        if not self.is_leader:
            return False
        layout = self.layouts.get(req.collection,
                                  req.replication or self.default_replication,
                                  req.ttl, req.disk_type or "hdd")
        layout.ensure_correct_writables()
        want = req.writable_volume_count
        lkey = (req.collection, req.replication or self.default_replication,
                req.ttl, req.disk_type or "hdd")
        if want and layout.active_count() < want and \
                time.monotonic() >= self._want_growth_backoff.get(lkey, 0.0):
            return True
        return layout.pick_for_write() is None

    def _do_assign(self, req: pb.AssignRequest,
                   allow_growth: bool = True) -> pb.AssignResponse:
        if not self.is_leader:
            hint = self.leader_address
            return pb.AssignResponse(
                error=(f"not leader; leader is {hint}" if hint
                       else "not leader; leader unknown"))
        replication = req.replication or self.default_replication
        disk_type = req.disk_type or "hdd"
        layout = self.layouts.get(req.collection, replication, req.ttl, disk_type)
        layout.ensure_correct_writables()
        vid = layout.pick_for_write()
        # writableVolumeCount (reference assign grow option): the caller
        # wants AT LEAST that many writable volumes so concurrent chunk
        # uploads — the filer's windowed fan-out — spread across volume
        # locks instead of serializing on one fsync queue. A cluster
        # that can't host `want` would otherwise pay a doomed
        # topology-wide growth sweep on EVERY assign: failures back off
        # per layout for _WANT_GROWTH_COOLDOWN_S.
        want = req.writable_volume_count or 0
        lkey = (req.collection, replication, req.ttl, disk_type)
        if want and vid is not None and \
                time.monotonic() < self._want_growth_backoff.get(lkey, 0.0):
            want = 0  # recent unsatisfiable ask: serve from what exists
        if vid is None or (want and layout.active_count() < want):
            if not allow_growth:
                # caller (the inline event-loop path) must re-dispatch to
                # a thread: growth is seconds, not microseconds
                return pb.AssignResponse(error=self.NEEDS_GROWTH)
            try:
                self.growth.grow(GrowRequest(
                    collection=req.collection, replication=replication,
                    ttl=req.ttl, disk_type=disk_type,
                    preferred_dc=req.data_center, preferred_rack=req.rack,
                    count=max(1, want - layout.active_count())))
            except Exception as e:  # noqa: BLE001
                if vid is not None:
                    # best-effort spread: the cluster can't host `want`
                    # writables (disks full), but a writable volume
                    # exists — serve the assign rather than failing it,
                    # and stop re-asking for a while
                    self._want_growth_backoff[lkey] = \
                        time.monotonic() + self._WANT_GROWTH_COOLDOWN_S
                    log.warning("writable-count growth to %d failed "
                                "(backing off %.0fs): %s", want,
                                self._WANT_GROWTH_COOLDOWN_S, e)
                else:
                    return pb.AssignResponse(error=f"grow failed: {e}")
            if self.raft is not None:
                # replicate the new MaxVolumeId before handing out fids
                # (reference raft FSM, raft_server.go:53); a failed
                # commit means we lost the quorum — refuse the assign
                # rather than risk split-brain fid allocation
                if not self.raft.propose(
                        {"max_volume_id": self.topo.max_volume_id}):
                    return pb.AssignResponse(
                        error="not leader; commit quorum lost")
            vid = layout.pick_for_write()
            if vid is None:
                return pb.AssignResponse(error="no writable volumes after growth")
        count = max(1, req.count)
        key = self.sequencer.next_id(count)
        cookie = random.getrandbits(32)
        if self.raft is not None:
            # Replicate the sequencer high-water mark (and the lease
            # grant riding the same entry) BEFORE the fids leave this
            # master: an acked range must be durable on a quorum, or a
            # new leader elected after our crash could hand out the same
            # keys again (duplicate fids). A failed commit means the
            # quorum is gone — refuse rather than ack unreplicated keys
            # (the locally-burned range just goes unused).
            cmd: dict = {"seq_hwm": key + count}
            if count > 1:
                cmd["lease"] = {"count": count,
                                "ttl_s": self.fid_leases.ttl_s}
            if not self.raft.propose(cmd):
                return pb.AssignResponse(
                    error="not leader; commit quorum lost")
        nodes = self.topo.lookup(vid)
        if not nodes:
            return pb.AssignResponse(error=f"volume {vid} has no locations")
        primary = random.choice(nodes)
        resp = pb.AssignResponse(
            fid=file_id(vid, key, cookie), count=count,
            location=pb.Location(url=primary.url, public_url=primary.public_url,
                                 grpc_port=primary.grpc_port))
        for n in nodes:
            resp.replicas.add(url=n.url, public_url=n.public_url,
                              grpc_port=n.grpc_port)
        lease_ttl = 0.0
        if count > 1:
            # a multi-count assign IS a fid-range lease: the sequencer
            # reserved [key, key+count) above. With raft on, the grant
            # was recorded by the FSM apply of the entry committed above
            # (on every master, this one included); single-master mode
            # records it directly.
            lease_ttl = (self.fid_leases.ttl_s if self.raft is not None
                         else self.fid_leases.grant(count))
        if self.guard is not None and self.guard.signing_key:
            if count > 1:
                # range-scoped token: ONE signature authorizes all N
                # needles of the lease (per-fid minting at bulk rates
                # would put the master back on the per-needle hot path).
                # exp IS the lease TTL — the token is what makes the
                # TTL real (lease.py contract), so a short lease must
                # mean a short token, never floored by the guard expiry
                from ..security import gen_jwt_for_fid_range
                resp.auth = gen_jwt_for_fid_range(
                    self.guard.signing_key,
                    max(1, int(lease_ttl)),
                    vid, key, count, cookie)
            else:
                from ..security import gen_jwt_for_volume_server
                resp.auth = gen_jwt_for_volume_server(
                    self.guard.signing_key, self.guard.expires_after_sec,
                    resp.fid)
        return resp

    # -- topology dump -------------------------------------------------------
    def topology_info(self, include_ec: bool = True) -> pb.TopologyInfo:
        t = pb.TopologyInfo(id="topo")
        with self.topo.lock:
            for dc in self.topo.dcs.values():
                dci = t.data_center_infos.add(id=dc.id)
                for rack in dc.racks.values():
                    ri = dci.rack_infos.add(id=rack.id)
                    for node in rack.nodes.values():
                        ni = ri.data_node_infos.add(id=node.id,
                                                    grpc_port=node.grpc_port)
                        for dtype, disk in node.disks.items():
                            di = ni.disk_infos[dtype]
                            di.type = dtype
                            di.volume_count = disk.volume_count
                            di.max_volume_count = disk.max_volume_count
                            di.free_volume_count = disk.free_slots()
                            for v in disk.volumes.values():
                                di.volume_infos.add(
                                    id=v.id, size=v.size, collection=v.collection,
                                    file_count=v.file_count,
                                    delete_count=v.delete_count,
                                    deleted_byte_count=v.deleted_byte_count,
                                    read_only=v.read_only,
                                    replica_placement=v.replica_placement.to_byte(),
                                    version=v.version,
                                    compact_revision=v.compact_revision,
                                    disk_type=v.disk_type)
                            if include_ec:
                                for s in disk.ec_shards.values():
                                    di.ec_shard_infos.add(
                                        id=s.volume_id, collection=s.collection,
                                        ec_index_bits=s.shard_bits,
                                        disk_type=s.disk_type,
                                        destroy_time=s.destroy_time)
        return t

    # -- background maintenance ---------------------------------------------
    def _janitor(self) -> None:
        """Dead-node reaping (heartbeat-stream death already unregisters;
        this is belt-and-braces) + layout hygiene. The reference drives
        vacuum/EC cron via shell scripts (master_server.go:269); our shell
        commands call the same seams."""
        while not self._stop.wait(self.pulse_seconds):
            for lo in self.layouts.all_layouts():
                lo.ensure_correct_writables()
            # decay the leases-active gauge even when no assigns arrive
            self.fid_leases.prune()
            try:
                # per-tick health scan keeps the at-risk gauges live for
                # scrapers and journals severity transitions as they
                # happen, not only when someone asks /cluster/health
                self.health.scan()
            except Exception as e:  # noqa: BLE001
                log.warning("health scan: %s", e)
