"""Raft consensus for the master quorum.

Reference: weed/server/raft_server.go (seaweedfs/raft backend) and
raft_hashicorp.go; the replicated state machine is deliberately tiny —
`MaxVolumeId` (raft_server.go:53-91 StateMachine.Save/Recovery/Apply) —
because everything else the master knows is rebuilt from volume-server
heartbeats after a leader change.

This is a compact, correct Raft core (election + log replication +
commit), not a port: RequestVote / AppendEntries ride our gRPC layer as
a `swtpu.raft.Raft` service with JSON-encoded commands, persistent
term/vote/log in a single JSON file, and an apply callback into the
master. Timing defaults suit tests (sub-second failover); production
would raise them.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable

from ..utils.log import logger
from ..utils.rpc import RpcService, Stub

log = logger("raft")

RAFT_SERVICE = "swtpu.raft.Raft"

FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"


@dataclass
class LogEntry:
    term: int
    command: dict = field(default_factory=dict)


class RaftNode:
    def __init__(self, address: str, peers: list[str],
                 apply_fn: Callable[[dict], None],
                 state_path: str | None = None,
                 election_timeout: tuple[float, float] = (0.4, 0.8),
                 heartbeat_interval: float = 0.12):
        self.address = address
        self.peers = [p for p in peers if p != address]
        self.apply_fn = apply_fn
        self.state_path = state_path
        self.election_timeout = election_timeout
        self.heartbeat_interval = heartbeat_interval

        # persistent state (term, voted_for, log)
        self.current_term = 0
        self.voted_for: str | None = None
        self.log: list[LogEntry] = []
        self._load()

        # volatile
        self.role = FOLLOWER
        self.leader_address: str | None = None
        self.commit_index = -1
        self.last_applied = -1
        self.next_index: dict[str, int] = {}
        self.match_index: dict[str, int] = {}

        self._lock = threading.RLock()
        self._election_deadline = 0.0
        self._stop = threading.Event()
        self._commit_cv = threading.Condition(self._lock)
        self._thread: threading.Thread | None = None

    # -- persistence ---------------------------------------------------------
    def _load(self) -> None:
        if not self.state_path or not os.path.exists(self.state_path):
            return
        try:
            with open(self.state_path) as f:
                st = json.load(f)
            self.current_term = st.get("term", 0)
            self.voted_for = st.get("voted_for")
            self.log = [LogEntry(e["term"], e["command"])
                        for e in st.get("log", [])]
        except Exception as e:  # noqa: BLE001
            log.warning("raft state load: %s", e)

    def _persist(self) -> None:
        if not self.state_path:
            return
        d = os.path.dirname(self.state_path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = self.state_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"term": self.current_term,
                       "voted_for": self.voted_for,
                       "log": [{"term": e.term, "command": e.command}
                               for e in self.log]}, f)
        os.replace(tmp, self.state_path)

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "RaftNode":
        self._reset_election_timer()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"raft-{self.address}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    @property
    def is_leader(self) -> bool:
        return self.role == LEADER

    def _reset_election_timer(self) -> None:
        lo, hi = self.election_timeout
        self._election_deadline = time.monotonic() + random.uniform(lo, hi)

    # -- main loop -----------------------------------------------------------
    def _run(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                role = self.role
            if role == LEADER:
                self._broadcast_append()
                self._stop.wait(self.heartbeat_interval)
            else:
                if time.monotonic() >= self._election_deadline:
                    self._start_election()
                self._stop.wait(0.02)

    # -- election ------------------------------------------------------------
    def _start_election(self) -> None:
        with self._lock:
            self.role = CANDIDATE
            self.current_term += 1
            self.voted_for = self.address
            self._persist()
            term = self.current_term
            last_idx = len(self.log) - 1
            last_term = self.log[-1].term if self.log else 0
            self._reset_election_timer()
        log.info("%s: starting election term %d", self.address, term)
        votes = 1
        for peer in self.peers:
            try:
                resp = self._call(peer, "RequestVote", {
                    "term": term, "candidate": self.address,
                    "last_log_index": last_idx, "last_log_term": last_term})
            except Exception:  # noqa: BLE001
                continue
            with self._lock:
                if resp.get("term", 0) > self.current_term:
                    self._become_follower(resp["term"], None)
                    return
                if resp.get("granted") and self.current_term == term:
                    votes += 1
        with self._lock:
            quorum = (len(self.peers) + 1) // 2 + 1
            if self.role == CANDIDATE and self.current_term == term \
                    and votes >= quorum:
                self._become_leader()

    def _become_leader(self) -> None:
        self.role = LEADER
        self.leader_address = self.address
        n = len(self.log)
        self.next_index = {p: n for p in self.peers}
        self.match_index = {p: -1 for p in self.peers}
        self._quorum_seen = time.monotonic()
        # no-op entry: commits all prior-term entries immediately (Raft
        # §8 / the reference raft libraries do the same on election),
        # closing the window where a replicated max_volume_id from the
        # old term sits unapplied on the new leader
        self.log.append(LogEntry(self.current_term, {}))
        self._persist()
        log.info("%s: LEADER for term %d", self.address, self.current_term)

    def _become_follower(self, term: int, leader: str | None) -> None:
        if term > self.current_term:
            self.current_term = term
            self.voted_for = None
            self._persist()
        if self.role != FOLLOWER:
            log.info("%s: -> follower term %d", self.address, term)
        self.role = FOLLOWER
        if leader:
            self.leader_address = leader
        self._reset_election_timer()

    # -- replication (leader) ------------------------------------------------
    def _broadcast_append(self) -> None:
        with self._lock:
            if self.role != LEADER:
                return
            term = self.current_term
            commit = self.commit_index
        reached = 1
        for peer in self.peers:
            with self._lock:
                ni = self.next_index.get(peer, len(self.log))
                prev_idx = ni - 1
                prev_term = (self.log[prev_idx].term
                             if 0 <= prev_idx < len(self.log) else 0)
                entries = [{"term": e.term, "command": e.command}
                           for e in self.log[ni:]]
            try:
                resp = self._call(peer, "AppendEntries", {
                    "term": term, "leader": self.address,
                    "prev_log_index": prev_idx, "prev_log_term": prev_term,
                    "entries": entries, "leader_commit": commit})
            except Exception:  # noqa: BLE001
                continue
            with self._lock:
                if resp.get("term", 0) > self.current_term:
                    self._become_follower(resp["term"], None)
                    return
                reached += 1  # peer answered (success or log mismatch)
                if resp.get("success"):
                    self.match_index[peer] = ni + len(entries) - 1
                    self.next_index[peer] = ni + len(entries)
                else:
                    self.next_index[peer] = max(0, ni - 1)
        with self._lock:
            if self.role != LEADER:
                return
            quorum_n = (len(self.peers) + 1) // 2 + 1
            now = time.monotonic()
            if reached >= quorum_n:
                self._quorum_seen = now
            elif now - getattr(self, "_quorum_seen", now) > \
                    self.election_timeout[1] * 2:
                # leader lease lost: a minority-partitioned leader must
                # stop serving (split-brain guard; the majority side is
                # free to elect)
                log.warning("%s: lost contact with quorum; stepping down",
                            self.address)
                self.role = FOLLOWER
                self.leader_address = None
                self._reset_election_timer()
                return
            # advance commit: highest index replicated on a quorum with
            # an entry from the current term (Raft §5.4.2)
            quorum = (len(self.peers) + 1) // 2 + 1
            for idx in range(len(self.log) - 1, self.commit_index, -1):
                if self.log[idx].term != self.current_term:
                    break
                count = 1 + sum(1 for p in self.peers
                                if self.match_index.get(p, -1) >= idx)
                if count >= quorum:
                    self.commit_index = idx
                    self._commit_cv.notify_all()
                    break
            self._apply_committed()

    def _apply_committed(self) -> None:
        while self.last_applied < self.commit_index:
            self.last_applied += 1
            try:
                self.apply_fn(self.log[self.last_applied].command)
            except Exception as e:  # noqa: BLE001
                log.error("raft apply %d: %s", self.last_applied, e)

    def propose(self, command: dict, timeout: float = 5.0) -> bool:
        """Leader-only: append + replicate; returns True once committed."""
        with self._lock:
            if self.role != LEADER:
                return False
            self.log.append(LogEntry(self.current_term, command))
            self._persist()
            idx = len(self.log) - 1
        self._broadcast_append()
        deadline = time.monotonic() + timeout
        with self._commit_cv:
            while self.commit_index < idx:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self.role != LEADER:
                    return False
                self._commit_cv.wait(min(remaining, 0.1))
        return True

    # -- RPC plumbing --------------------------------------------------------
    def _call(self, peer: str, method: str, payload: dict) -> dict:
        from ..pb import master_pb2 as pb
        stub = Stub(peer, RAFT_SERVICE)
        if method == "RequestVote":
            req = pb.RequestVoteRequest(
                term=payload["term"], candidate=payload["candidate"],
                last_log_index=payload["last_log_index"],
                last_log_term=payload["last_log_term"])
            r = stub.call(method, req, pb.RequestVoteResponse, timeout=1.0)
            return {"term": r.term, "granted": r.granted}
        req = pb.AppendEntriesRequest(
            term=payload["term"], leader=payload["leader"],
            prev_log_index=payload["prev_log_index"],
            prev_log_term=payload["prev_log_term"],
            leader_commit=payload["leader_commit"])
        for e in payload["entries"]:
            req.entries.add(term=e["term"],
                            command=json.dumps(e["command"]).encode())
        r = stub.call(method, req, pb.AppendEntriesResponse, timeout=1.0)
        return {"term": r.term, "success": r.success}

    def build_service(self) -> RpcService:
        from ..pb import master_pb2 as pb
        svc = RpcService(RAFT_SERVICE)
        node = self

        @svc.unary("RequestVote", pb.RequestVoteRequest,
                   pb.RequestVoteResponse)
        def request_vote(req, context):
            out = node._on_request_vote({
                "term": req.term, "candidate": req.candidate,
                "last_log_index": req.last_log_index,
                "last_log_term": req.last_log_term})
            return pb.RequestVoteResponse(term=out["term"],
                                          granted=out["granted"])

        @svc.unary("AppendEntries", pb.AppendEntriesRequest,
                   pb.AppendEntriesResponse)
        def append_entries(req, context):
            out = node._on_append_entries({
                "term": req.term, "leader": req.leader,
                "prev_log_index": req.prev_log_index,
                "prev_log_term": req.prev_log_term,
                "entries": [{"term": e.term,
                             "command": json.loads(e.command or b"{}")}
                            for e in req.entries],
                "leader_commit": req.leader_commit})
            return pb.AppendEntriesResponse(term=out["term"],
                                            success=out["success"])

        return svc

    # -- RPC handlers (any role) ---------------------------------------------
    def _on_request_vote(self, p: dict) -> dict:
        with self._lock:
            if p["term"] > self.current_term:
                self._become_follower(p["term"], None)
            granted = False
            if p["term"] == self.current_term and \
                    self.voted_for in (None, p["candidate"]):
                last_idx = len(self.log) - 1
                last_term = self.log[-1].term if self.log else 0
                up_to_date = (p["last_log_term"], p["last_log_index"]) >= \
                             (last_term, last_idx)
                if up_to_date:
                    granted = True
                    self.voted_for = p["candidate"]
                    self._persist()
                    self._reset_election_timer()
            return {"term": self.current_term, "granted": granted}

    def _on_append_entries(self, p: dict) -> dict:
        with self._lock:
            if p["term"] < self.current_term:
                return {"term": self.current_term, "success": False}
            self._become_follower(p["term"], p["leader"])
            prev_idx = p["prev_log_index"]
            if prev_idx >= 0:
                if prev_idx >= len(self.log) or \
                        self.log[prev_idx].term != p["prev_log_term"]:
                    return {"term": self.current_term, "success": False}
            # append, truncating conflicts
            at = prev_idx + 1
            for i, e in enumerate(p["entries"]):
                idx = at + i
                if idx < len(self.log):
                    if self.log[idx].term != e["term"]:
                        del self.log[idx:]
                        self.log.append(LogEntry(e["term"], e["command"]))
                else:
                    self.log.append(LogEntry(e["term"], e["command"]))
            if p["entries"]:
                self._persist()
            if p["leader_commit"] > self.commit_index:
                self.commit_index = min(p["leader_commit"], len(self.log) - 1)
                self._apply_committed()
            return {"term": self.current_term, "success": True}
