"""Raft consensus for the master quorum.

Reference: weed/server/raft_server.go (seaweedfs/raft backend) and
raft_hashicorp.go; the replicated state machine is deliberately tiny —
`MaxVolumeId` (raft_server.go:53-91 StateMachine.Save/Recovery/Apply) —
because everything else the master knows is rebuilt from volume-server
heartbeats after a leader change.

Compact, correct Raft core: election, log replication, commit, no-op
entry on election, leader-lease step-down on quorum loss, and log
compaction with snapshot install (the FSM snapshot is just the folded
command state, so "InstallSnapshot" piggybacks on AppendEntries).
Indexes are absolute; `log_start` is the absolute index of log[0].
Peer RPCs fan out on a worker pool so one dead peer cannot stall
heartbeats to the healthy ones. Timing defaults suit tests (sub-second
failover); production would raise them.
"""

from __future__ import annotations

import contextvars
import json
import os
import random
import threading
import time
from concurrent.futures import ThreadPoolExecutor, as_completed
from dataclasses import dataclass, field
from typing import Callable

from ..utils import fsutil
from ..utils.log import logger
from ..utils.rpc import RpcService, Stub

log = logger("raft")

RAFT_SERVICE = "swtpu.raft.Raft"

FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"

COMPACT_THRESHOLD = 512  # committed entries kept before compaction


@dataclass
class LogEntry:
    term: int
    command: dict = field(default_factory=dict)


def _fold(state: dict, command: dict) -> dict:
    """Fold a command into FSM snapshot state (monotonic maxes)."""
    mvid = command.get("max_volume_id")
    if mvid:
        state["max_volume_id"] = max(state.get("max_volume_id", 0), mvid)
    hwm = command.get("seq_hwm")
    if hwm:
        # sequencer high-water mark must survive compaction: a node that
        # catches up from the snapshot and later becomes leader would
        # otherwise reissue fid keys the old leader already handed out
        state["seq_hwm"] = max(state.get("seq_hwm", 0), hwm)
    members = command.get("raft_members")
    if members:
        # membership rides the snapshot so a compacted log still tells a
        # restarting/lagging node who the cluster is
        state["_members"] = sorted(members)
    # "lease" grants are deliberately NOT folded: they are ephemeral
    # (TTL-bounded observability state) and re-arming them long after the
    # grant would inflate the leases-active gauge forever
    return state


def _fsync_dir(path: str) -> None:
    """fsync the parent directory of `path` so a just-completed
    os.replace / file creation survives a crash. Without it the rename
    itself can be lost, resurrecting a stale voted_for — which lets the
    node vote twice in one term (the exact double-vote raft §5.2
    forbids)."""
    fsutil.fsync_dir(path)


class RaftNode:
    def __init__(self, address: str, peers: list[str],
                 apply_fn: Callable[[dict], None],
                 state_path: str | None = None,
                 election_timeout: tuple[float, float] = (0.4, 0.8),
                 heartbeat_interval: float = 0.12,
                 rpc_timeout: float = 0.3):
        self.address = address
        self.peers = [p for p in peers if p != address]
        self.cluster_members = sorted(set(list(peers) + [address]))
        self.apply_fn = apply_fn
        self.state_path = state_path
        self.election_timeout = election_timeout
        self.heartbeat_interval = heartbeat_interval
        self.rpc_timeout = rpc_timeout

        # persistent state
        self.current_term = 0
        self.voted_for: str | None = None
        self.log: list[LogEntry] = []
        self.log_start = 0          # absolute index of log[0]
        self.snapshot_state: dict = {}   # folded commands below log_start
        self.snapshot_term = 0      # term of entry log_start-1
        self._wal = None            # append handle for <state_path>.wal

        # volatile — initialized BEFORE _load(): a loaded snapshot may
        # carry a membership config that _apply_config folds into this
        # state (role, election deadline, peer indices)
        self.role = FOLLOWER
        self.leader_address: str | None = None
        self.next_index: dict[str, int] = {}
        self.match_index: dict[str, int] = {}
        self._quorum_seen = time.monotonic()
        self._election_deadline = 0.0
        self._removed = False       # self decommissioned via raft_members
        # (role, term, leader) last published to on_state_change /
        # metrics; compared each _run tick OUTSIDE the raft lock so the
        # callback (admin cron wakeups, follower re-dials) can never
        # deadlock against raft internals
        self.on_state_change: "Callable[[str, int, str | None], None] | None" \
            = None
        self._published: tuple = (None, -1, None)
        self._load()
        self.commit_index = self.log_start - 1
        self.last_applied = self.log_start - 1

        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._commit_cv = threading.Condition(self._lock)
        self._thread: threading.Thread | None = None
        self._pool = ThreadPoolExecutor(
            max_workers=max(2, len(self.peers) or 1),
            thread_name_prefix="raft-rpc")

    # -- absolute index helpers ---------------------------------------------
    @property
    def _last_index(self) -> int:
        return self.log_start + len(self.log) - 1

    def _term_at(self, index: int) -> int:
        if index == self.log_start - 1:
            return self.snapshot_term
        rel = index - self.log_start
        if 0 <= rel < len(self.log):
            return self.log[rel].term
        return 0

    def _entry(self, index: int) -> LogEntry:
        return self.log[index - self.log_start]

    @property
    def _quorum(self) -> int:
        return (len(self.peers) + 1) // 2 + 1

    # -- persistence ---------------------------------------------------------
    # Two files (the reference's hashicorp backend pairs BoltDB log +
    # snapshot files the same way; raft_hashicorp.go:99):
    #   <state_path>        — small JSON metadata (term, vote, log_start,
    #                         snapshot), atomically rewritten when it changes
    #   <state_path>.wal    — append-only log, one JSON line per entry,
    #                         fsync'd per append; O(1) disk work per entry
    #                         instead of rewriting the whole log (r2 weak #6)
    def _load(self) -> None:
        if not self.state_path:
            return
        if not os.path.exists(self.state_path):
            # no metadata yet, but a crash before the FIRST metadata
            # rewrite can still leave fsynced (= acked) WAL appends;
            # ignoring the WAL here would lose committed entries
            try:
                wal_start, self.log = self._read_wal()
                if wal_start is not None:
                    self.log_start = wal_start
            except Exception as e:  # noqa: BLE001
                log.warning("raft wal load: %s", e)
            return
        try:
            with open(self.state_path) as f:
                st = json.load(f)
            self.current_term = st.get("term", 0)
            self.voted_for = st.get("voted_for")
            self.log_start = st.get("log_start", 0)
            self.snapshot_state = st.get("snapshot_state", {})
            self.snapshot_term = st.get("snapshot_term", 0)
            if "log" in st:  # pre-WAL format: whole log inline
                self.log = [LogEntry(e["term"], e["command"])
                            for e in st.get("log", [])]
                # migrate NOW: the next metadata-only persist would drop
                # the inline log and orphan every entry. WAL first: the
                # entries must land in their new home before the
                # metadata rewrite drops the inline copy
                self._persist(wal_first=True)
            else:
                wal_start, self.log = self._read_wal()
                if wal_start is not None:
                    self.log_start = wal_start
            if self.snapshot_state:
                if self.snapshot_state.get("_members"):
                    self._apply_config(self.snapshot_state["_members"])
                self.apply_fn(dict(self.snapshot_state))
        except Exception as e:  # noqa: BLE001
            log.warning("raft state load: %s", e)

    def _read_wal(self) -> "tuple[int | None, list[LogEntry]]":
        """Returns (log_start from the WAL header, entries). The header is
        written atomically WITH the entries, so on a crash between the WAL
        and metadata rewrites the header is the authoritative log_start —
        trusting the stale metadata would shift every entry's index."""
        wal = self.state_path + ".wal"
        out: list[LogEntry] = []
        start = None
        if not os.path.exists(wal):
            return start, out
        with open(wal, "rb") as f:
            for i, line in enumerate(f):
                try:
                    e = json.loads(line)
                    if i == 0 and "log_start" in e:
                        start = e["log_start"]
                        continue
                    out.append(LogEntry(e["t"], e["c"]))
                except Exception:  # noqa: BLE001 — torn tail after a crash
                    break
        return start, out

    def _wal_handle(self):
        if self._wal is None:
            d = os.path.dirname(self.state_path)
            if d:
                os.makedirs(d, exist_ok=True)
            path = self.state_path + ".wal"
            fresh = not os.path.exists(path) or os.path.getsize(path) == 0
            self._wal = open(path, "ab")
            if fresh:
                self._wal.write(
                    json.dumps({"log_start": self.log_start}).encode()
                    + b"\n")
                self._wal.flush()
                os.fsync(self._wal.fileno())
                _fsync_dir(path)  # the file itself must survive a crash
        return self._wal

    def _persist_meta(self) -> None:
        """Atomic rewrite of the small metadata file + fsync."""
        if not self.state_path:
            return
        d = os.path.dirname(self.state_path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = self.state_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"term": self.current_term,
                       "voted_for": self.voted_for,
                       "log_start": self.log_start,
                       "snapshot_state": self.snapshot_state,
                       "snapshot_term": self.snapshot_term}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.state_path)
        # fsync the rename too: vote/term durability must be complete
        # BEFORE the RPC reply leaves (a crash after replying "granted"
        # but before the rename is durable double-votes in this term)
        _fsync_dir(self.state_path)

    def _wal_append(self, entries: "list[LogEntry]") -> None:
        """Append + fsync just the new entries (the per-propose hot path)."""
        if not self.state_path or not entries:
            return
        f = self._wal_handle()
        for e in entries:
            f.write(json.dumps({"t": e.term, "c": e.command}).encode()
                    + b"\n")
        f.flush()
        os.fsync(f.fileno())

    def _persist(self, wal_first: bool = False) -> None:
        """Full rewrite of metadata + WAL. Ordering is load-bearing:
        every committed entry must exist in (snapshot ∪ WAL) at EVERY
        crash point, so whichever file is gaining entries is written
        before the file losing them is rewritten. Compaction folds
        entries WAL→snapshot, hence metadata first by default — a
        wal-first swap would leave a WAL whose header says log_start=N
        next to metadata whose snapshot still ends below N, and the
        folded committed entries would exist NOWHERE on disk
        (crashsim's raft-commit scenario catches exactly this). The
        reverse window (new snapshot + old longer WAL) merely replays
        folded entries twice, and _fold is idempotent (monotonic
        maxes) by design. The pre-WAL format migration in _load moves
        entries the OTHER way (inline metadata log → WAL) and passes
        wal_first=True for the same reason mirrored. Needed after
        truncation/compaction/snapshot-install; appends use
        _wal_append instead."""
        if not self.state_path:
            return
        d = os.path.dirname(self.state_path)
        if d:
            os.makedirs(d, exist_ok=True)
        if self._wal is not None:
            self._wal.close()
            self._wal = None
        if not wal_first:
            self._persist_meta()
        tmp = self.state_path + ".wal.tmp"
        with open(tmp, "wb") as f:
            f.write(json.dumps({"log_start": self.log_start}).encode()
                    + b"\n")
            for e in self.log:
                f.write(json.dumps({"t": e.term, "c": e.command}).encode()
                        + b"\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.state_path + ".wal")
        _fsync_dir(self.state_path)
        if wal_first:
            self._persist_meta()

    def _maybe_compact(self) -> None:
        """Fold committed prefix into the snapshot (caller holds lock).
        The reference snapshots the FSM the same way — MaxVolumeId only."""
        committed = self.commit_index - self.log_start + 1
        if committed <= COMPACT_THRESHOLD:
            return
        keep_from = self.commit_index  # keep the last committed entry
        for i in range(self.log_start, keep_from):
            self.snapshot_state = _fold(self.snapshot_state,
                                        self._entry(i).command)
        self.snapshot_term = self._term_at(keep_from - 1)
        self.log = self.log[keep_from - self.log_start:]
        self.log_start = keep_from
        self._persist()

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "RaftNode":
        self._reset_election_timer()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"raft-{self.address}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._pool.shutdown(wait=False, cancel_futures=True)
        if self._wal is not None:
            self._wal.close()
            self._wal = None

    @property
    def is_leader(self) -> bool:
        return self.role == LEADER

    def _reset_election_timer(self) -> None:
        if self._removed:
            # a decommissioned node never campaigns again (not even after
            # restart — _load replays the config that set the flag)
            self._election_deadline = float("inf")
            return
        lo, hi = self.election_timeout
        self._election_deadline = time.monotonic() + random.uniform(lo, hi)

    # -- main loop -----------------------------------------------------------
    def _run(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                role = self.role
            if role == LEADER:
                self._broadcast_append()
                self._publish_state()
                self._stop.wait(self.heartbeat_interval)
            else:
                if time.monotonic() >= self._election_deadline:
                    self._start_election()
                self._publish_state()
                self._stop.wait(0.02)

    def _publish_state(self) -> None:
        """Poll-publish (role, term, leader) transitions to metrics and
        the on_state_change callback — from the _run loop, outside the
        raft lock, so subscribers (admin cron, follower read cache) can
        take their own locks without an ABBA against raft internals.
        Latency bound: one loop tick (20ms follower / one heartbeat
        interval leader)."""
        with self._lock:
            snap = (self.role, self.current_term, self.leader_address)
        if snap == self._published:
            return
        prev = self._published
        self._published = snap
        try:
            from ..stats import MASTER_LEADER_CHANGES, RAFT_LEADER_CHANGES, \
                RAFT_TERM
            RAFT_TERM.set(value=snap[1])
            if snap[2] and snap[2] != prev[2]:
                # leader identity changed (elections that fizzle without
                # a winner bump terms, not this counter)
                RAFT_LEADER_CHANGES.inc()
                MASTER_LEADER_CHANGES.inc()
        except Exception:  # noqa: BLE001  # swtpu-lint: disable=silent-except (metrics must never stall the raft loop)
            pass
        cb = self.on_state_change
        if cb is not None:
            try:
                cb(snap[0], snap[1], snap[2])
            except Exception as e:  # noqa: BLE001
                log.warning("raft state-change callback: %s", e)

    # -- election ------------------------------------------------------------
    def _start_election(self) -> None:
        with self._lock:
            self.role = CANDIDATE
            self.current_term += 1
            self.voted_for = self.address
            self._persist_meta()
            term = self.current_term
            last_idx = self._last_index
            last_term = self._term_at(last_idx)
            self._reset_election_timer()
        log.info("%s: starting election term %d", self.address, term)
        votes = 1
        futs = {self._pool.submit(
                    contextvars.copy_context().run, self._call, peer,
                    "RequestVote", {
                        "term": term, "candidate": self.address,
                        "last_log_index": last_idx,
                        "last_log_term": last_term,
                    }): peer for peer in self.peers}
        try:
            for fut in as_completed(futs, timeout=self.rpc_timeout * 3):
                try:
                    resp = fut.result()
                except Exception:  # noqa: BLE001
                    continue
                with self._lock:
                    if resp.get("term", 0) > self.current_term:
                        self._become_follower(resp["term"], None)
                        return
                    if resp.get("granted") and self.current_term == term:
                        votes += 1
                        if votes >= self._quorum and self.role == CANDIDATE:
                            self._become_leader()
                            return
        except TimeoutError:
            pass

    def _become_leader(self) -> None:
        self.role = LEADER
        self.leader_address = self.address
        n = self._last_index + 1
        self.next_index = {p: n for p in self.peers}
        self.match_index = {p: -1 for p in self.peers}
        self._quorum_seen = time.monotonic()
        # no-op entry: commits all prior-term entries immediately (Raft
        # §8), closing the window where a replicated max_volume_id from
        # the old term sits unapplied on the new leader
        self.log.append(LogEntry(self.current_term, {}))
        self._wal_append(self.log[-1:])
        log.info("%s: LEADER for term %d", self.address, self.current_term)

    def _become_follower(self, term: int, leader: str | None) -> None:
        if term > self.current_term:
            self.current_term = term
            self.voted_for = None
            self._persist_meta()
        if self.role != FOLLOWER:
            log.info("%s: -> follower term %d", self.address, term)
        self.role = FOLLOWER
        if leader:
            self.leader_address = leader
        self._reset_election_timer()

    # -- replication (leader) ------------------------------------------------
    def _append_args_for(self, peer: str) -> dict:
        """Build AppendEntries for one peer (caller holds lock). Peers
        lagging below log_start get the snapshot piggybacked."""
        ni = self.next_index.get(peer, self._last_index + 1)
        args = {"term": self.current_term, "leader": self.address,
                "leader_commit": self.commit_index}
        if ni < self.log_start:
            # follower is behind the compaction horizon: install snapshot
            args["snapshot"] = {"state": self.snapshot_state,
                                "last_index": self.log_start - 1,
                                "last_term": self.snapshot_term}
            ni = self.log_start
        args["prev_log_index"] = ni - 1
        args["prev_log_term"] = self._term_at(ni - 1)
        args["entries"] = [{"term": e.term, "command": e.command}
                           for e in self.log[ni - self.log_start:]]
        args["_ni"] = ni
        return args

    def _broadcast_append(self) -> None:
        with self._lock:
            if self.role != LEADER:
                return
            term = self.current_term
            per_peer = {p: self._append_args_for(p) for p in self.peers}
        futs = {}
        for peer, args in per_peer.items():
            ni = args.pop("_ni")
            futs[self._pool.submit(
                contextvars.copy_context().run, self._call, peer,
                "AppendEntries", args)] = (peer, ni, len(args["entries"]))
        reached = 1
        try:
            for fut in as_completed(futs, timeout=self.rpc_timeout * 3):
                peer, ni, n_entries = futs[fut]
                try:
                    resp = fut.result()
                except Exception:  # noqa: BLE001
                    continue
                with self._lock:
                    if resp.get("term", 0) > self.current_term:
                        self._become_follower(resp["term"], None)
                        return
                    reached += 1
                    if resp.get("success"):
                        self.match_index[peer] = ni + n_entries - 1
                        self.next_index[peer] = ni + n_entries
                    else:
                        self.next_index[peer] = max(self.log_start - 1,
                                                    ni - 1)
        except TimeoutError:
            pass
        with self._lock:
            if self.role != LEADER:
                return
            now = time.monotonic()
            if reached >= self._quorum:
                self._quorum_seen = now
            elif now - self._quorum_seen > self.election_timeout[1] * 2:
                # leader lease lost: a minority-partitioned leader must
                # stop serving (split-brain guard)
                log.warning("%s: lost contact with quorum; stepping down",
                            self.address)
                self.role = FOLLOWER
                self.leader_address = None
                self._reset_election_timer()
                return
            # advance commit: highest index replicated on a quorum with
            # an entry from the current term (Raft §5.4.2)
            for idx in range(self._last_index, self.commit_index, -1):
                if self._term_at(idx) != self.current_term:
                    break
                count = 1 + sum(1 for p in self.peers
                                if self.match_index.get(p, -1) >= idx)
                if count >= self._quorum:
                    self.commit_index = idx
                    self._commit_cv.notify_all()
                    break
            self._apply_committed()
            self._maybe_compact()

    def _apply_committed(self) -> None:
        while self.last_applied < self.commit_index:
            self.last_applied += 1
            try:
                cmd = self._entry(self.last_applied).command
                if cmd.get("raft_members"):
                    new_members = set(cmd["raft_members"])
                    if self.role == LEADER:
                        # courtesy final append so removed peers learn of
                        # their removal (and go quiet) instead of finding
                        # out by silence
                        for peer in [p for p in self.peers
                                     if p not in new_members]:
                            try:
                                args = self._append_args_for(peer)
                                args.pop("_ni")
                                self._pool.submit(
                                    contextvars.copy_context().run,
                                    self._call, peer, "AppendEntries", args)
                            except Exception as e:  # noqa: BLE001
                                log.debug("config-change catch-up append "
                                          "to %s not queued: %s", peer, e)
                    self._apply_config(cmd["raft_members"])
                elif cmd:
                    self.apply_fn(cmd)
            except Exception as e:  # noqa: BLE001
                log.error("raft apply %d: %s", self.last_applied, e)

    # -- membership change (reference master.proto RaftAddServer/Remove;
    # single-server change applied at commit like hashicorp AddVoter) -------
    def _apply_config(self, members: list[str]) -> None:
        """Adopt a committed membership list (caller holds lock, or is in
        single-threaded _load)."""
        members = sorted(set(members))
        self.cluster_members = members
        if self.address not in members:
            # removed from the cluster: stop voting/campaigning entirely so
            # a stale node can't disrupt the remaining quorum with elections
            self.peers = []
            self.role = FOLLOWER
            self.leader_address = None
            self._removed = True
            self._election_deadline = float("inf")
            log.info("%s: removed from raft cluster", self.address)
            return
        self._removed = False
        self.peers = [m for m in members if m != self.address]
        if self.role == LEADER:
            n = self._last_index + 1
            for p in self.peers:
                self.next_index.setdefault(p, n)
                self.match_index.setdefault(p, -1)
        log.info("%s: raft membership now %s", self.address, members)

    def add_server(self, address: str, timeout: float = 5.0) -> bool:
        """Leader-only: commit a membership list including `address`. The
        new node starts (or restarts) with any seed peer list — the leader
        streams it the log/snapshot, whose config entry teaches it the
        real membership."""
        with self._lock:
            members = set(self.cluster_members) | {address}
        return self.propose({"raft_members": sorted(members)}, timeout)

    def remove_server(self, address: str, timeout: float = 5.0) -> bool:
        """Leader-only; removing the leader itself commits first, then the
        leader steps down when the entry applies."""
        with self._lock:
            members = set(self.cluster_members) - {address}
        return self.propose({"raft_members": sorted(members)}, timeout)

    def propose(self, command: dict, timeout: float = 5.0) -> bool:
        """Leader-only: append + replicate; returns True once committed."""
        with self._lock:
            if self.role != LEADER:
                return False
            self.log.append(LogEntry(self.current_term, command))
            self._wal_append(self.log[-1:])
            idx = self._last_index
        self._broadcast_append()
        deadline = time.monotonic() + timeout
        with self._commit_cv:
            while self.commit_index < idx:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self.role != LEADER:
                    return False
                self._commit_cv.wait(min(remaining, 0.1))
        return True

    # -- RPC plumbing --------------------------------------------------------
    def _call(self, peer: str, method: str, payload: dict) -> dict:
        from ..pb import master_pb2 as pb
        stub = Stub(peer, RAFT_SERVICE)
        if method == "RequestVote":
            req = pb.RequestVoteRequest(
                term=payload["term"], candidate=payload["candidate"],
                last_log_index=payload["last_log_index"],
                last_log_term=payload["last_log_term"])
            r = stub.call(method, req, pb.RequestVoteResponse,
                          timeout=self.rpc_timeout)
            return {"term": r.term, "granted": r.granted}
        req = pb.AppendEntriesRequest(
            term=payload["term"], leader=payload["leader"],
            prev_log_index=payload["prev_log_index"],
            prev_log_term=payload["prev_log_term"],
            leader_commit=payload["leader_commit"])
        snap = payload.get("snapshot")
        for e in payload["entries"]:
            cmd = dict(e["command"])
            req.entries.add(term=e["term"],
                            command=json.dumps(cmd).encode())
        if snap is not None:
            # snapshot piggybacks as the first entry with a marker key
            # (the FSM state is one small dict, so a dedicated
            # InstallSnapshot RPC would be overkill)
            first = pb.RaftLogEntry(
                term=snap["last_term"],
                command=json.dumps({"__snapshot__": snap}).encode())
            entries = [first] + list(req.entries)
            del req.entries[:]
            for e in entries:
                req.entries.add(term=e.term, command=e.command)
        r = stub.call(method, req, pb.AppendEntriesResponse,
                      timeout=self.rpc_timeout)
        return {"term": r.term, "success": r.success}

    def build_service(self) -> RpcService:
        from ..pb import master_pb2 as pb
        svc = RpcService(RAFT_SERVICE)
        node = self

        @svc.unary("RequestVote", pb.RequestVoteRequest,
                   pb.RequestVoteResponse)
        def request_vote(req, context):
            out = node._on_request_vote({
                "term": req.term, "candidate": req.candidate,
                "last_log_index": req.last_log_index,
                "last_log_term": req.last_log_term})
            return pb.RequestVoteResponse(term=out["term"],
                                          granted=out["granted"])

        @svc.unary("AppendEntries", pb.AppendEntriesRequest,
                   pb.AppendEntriesResponse)
        def append_entries(req, context):
            entries = [{"term": e.term,
                        "command": json.loads(e.command or b"{}")}
                       for e in req.entries]
            snapshot = None
            if entries and "__snapshot__" in entries[0]["command"]:
                snapshot = entries[0]["command"]["__snapshot__"]
                entries = entries[1:]
            out = node._on_append_entries({
                "term": req.term, "leader": req.leader,
                "prev_log_index": req.prev_log_index,
                "prev_log_term": req.prev_log_term,
                "entries": entries, "snapshot": snapshot,
                "leader_commit": req.leader_commit})
            return pb.AppendEntriesResponse(term=out["term"],
                                            success=out["success"])

        return svc

    # -- RPC handlers (any role) ---------------------------------------------
    def _on_request_vote(self, p: dict) -> dict:
        with self._lock:
            now = time.monotonic()
            leader_alive = (
                (self.role == LEADER
                 and now - self._quorum_seen < self.election_timeout[0])
                or (self.role != LEADER and self.leader_address is not None
                    and now < self._election_deadline))
            if leader_alive and p["candidate"] not in self.cluster_members:
                # Leader stickiness (hashicorp CheckQuorum analogue): while
                # a live leader exists, a candidate outside our committed
                # membership (removed, or not yet added) can't win or even
                # bump our term — a stale removed node would otherwise
                # depose the leader forever. With NO live leader we vote by
                # the normal rules regardless of config view, else a
                # cluster whose joiner hasn't applied the latest config
                # entry could never elect anyone (liveness).
                return {"term": self.current_term, "granted": False}
            if p["term"] > self.current_term:
                self._become_follower(p["term"], None)
            granted = False
            if p["term"] == self.current_term and \
                    self.voted_for in (None, p["candidate"]):
                last_idx = self._last_index
                last_term = self._term_at(last_idx)
                up_to_date = (p["last_log_term"], p["last_log_index"]) >= \
                             (last_term, last_idx)
                if up_to_date:
                    granted = True
                    self.voted_for = p["candidate"]
                    self._persist_meta()
                    self._reset_election_timer()
            return {"term": self.current_term, "granted": granted}

    def _on_append_entries(self, p: dict) -> dict:
        with self._lock:
            if p["term"] < self.current_term:
                return {"term": self.current_term, "success": False}
            self._become_follower(p["term"], p["leader"])
            if p.get("snapshot"):
                snap = p["snapshot"]
                self.snapshot_state = dict(snap["state"])
                self.snapshot_term = snap["last_term"]
                self.log = []
                self.log_start = snap["last_index"] + 1
                self.commit_index = max(self.commit_index,
                                        snap["last_index"])
                self.last_applied = max(self.last_applied,
                                        snap["last_index"])
                if self.snapshot_state:
                    if self.snapshot_state.get("_members"):
                        self._apply_config(self.snapshot_state["_members"])
                    self.apply_fn(dict(self.snapshot_state))
                self._persist()
            prev_idx = p["prev_log_index"]
            if prev_idx >= self.log_start - 1:
                if prev_idx > self._last_index or \
                        (prev_idx >= self.log_start
                         and self._term_at(prev_idx) != p["prev_log_term"]) \
                        or (prev_idx == self.log_start - 1
                            and self.snapshot_term
                            and p["prev_log_term"] != self.snapshot_term):
                    return {"term": self.current_term, "success": False}
            else:
                # our snapshot is ahead of the leader's prev: stale rpc
                return {"term": self.current_term, "success": False}
            # append, truncating conflicts
            at = prev_idx + 1
            appended: list[LogEntry] = []
            truncated = False
            for i, e in enumerate(p["entries"]):
                idx = at + i
                rel = idx - self.log_start
                if rel < len(self.log):
                    if self.log[rel].term != e["term"]:
                        del self.log[rel:]
                        entry = LogEntry(e["term"], e["command"])
                        self.log.append(entry)
                        truncated = True
                        appended.append(entry)
                else:
                    entry = LogEntry(e["term"], e["command"])
                    self.log.append(entry)
                    appended.append(entry)
            if truncated:
                self._persist()       # conflict: WAL must be rewritten
            elif appended:
                self._wal_append(appended)
            if p["leader_commit"] > self.commit_index:
                self.commit_index = min(p["leader_commit"], self._last_index)
                self._apply_committed()
            return {"term": self.current_term, "success": True}
