"""File-key sequencers (reference weed/sequence: memory_sequencer.go:18
synced via heartbeat MaxFileKey, snowflake_sequencer.go:38)."""

from __future__ import annotations

import threading
import time


class MemorySequencer:
    def __init__(self, start: int = 1):
        self._next = start
        self._lock = threading.Lock()

    def next_id(self, count: int = 1) -> int:
        with self._lock:
            v = self._next
            self._next += count
            return v

    def set_max(self, seen: int) -> None:
        """Heartbeat MaxFileKey sync (master_grpc_server.go:130)."""
        with self._lock:
            if seen >= self._next:
                self._next = seen + 1

    @property
    def peek(self) -> int:
        return self._next


class SnowflakeSequencer:
    """41b ms-timestamp | 10b node | 12b sequence."""

    EPOCH_MS = 1_600_000_000_000

    def __init__(self, node_id: int = 0):
        self.node_id = node_id & 0x3FF
        self._lock = threading.Lock()
        self._last_ms = 0
        self._seq = 0

    def next_id(self, count: int = 1) -> int:
        with self._lock:
            ms = int(time.time() * 1000) - self.EPOCH_MS
            if ms == self._last_ms:
                self._seq += count
                if self._seq > 0xFFF:
                    while ms <= self._last_ms:
                        ms = int(time.time() * 1000) - self.EPOCH_MS
                    self._seq = 0
            else:
                self._seq = 0
            self._last_ms = ms
            return (ms << 22) | (self.node_id << 12) | self._seq

    def set_max(self, seen: int) -> None:
        pass  # time-based; nothing to sync
