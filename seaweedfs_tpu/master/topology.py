"""Cluster topology tree: DataCenter -> Rack -> DataNode -> Disk.

Reference: weed/topology/{topology,node,data_node,disk}.go and the EC
registration paths topology_ec.go:102/:131. The master holds one Topology;
volume servers stream heartbeats that register/diff their volume and EC-shard
lists; lookups and placement walk this tree.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from .. import ec as ec_accounting
from ..storage.types import TTL, DiskType, ReplicaPlacement


@dataclass
class VolumeInfo:
    id: int
    size: int = 0
    collection: str = ""
    file_count: int = 0
    delete_count: int = 0
    deleted_byte_count: int = 0
    read_only: bool = False
    replica_placement: ReplicaPlacement = field(default_factory=ReplicaPlacement)
    ttl: TTL = field(default_factory=TTL)
    version: int = 3
    disk_type: str = "hdd"
    compact_revision: int = 0
    modified_at_second: int = 0

    @classmethod
    def from_pb(cls, m) -> "VolumeInfo":
        return cls(
            id=m.id, size=m.size, collection=m.collection,
            file_count=m.file_count, delete_count=m.delete_count,
            deleted_byte_count=m.deleted_byte_count, read_only=m.read_only,
            replica_placement=ReplicaPlacement.from_byte(m.replica_placement),
            ttl=TTL.from_bytes(m.ttl.to_bytes(2, "little")),
            version=m.version or 3, disk_type=m.disk_type or "hdd",
            compact_revision=m.compact_revision,
            modified_at_second=m.modified_at_second)

    def layout_key(self) -> tuple:
        return (self.collection, str(self.replica_placement), str(self.ttl),
                self.disk_type)


@dataclass
class EcShardInfo:
    volume_id: int
    collection: str
    shard_bits: int
    disk_type: str = "hdd"
    destroy_time: int = 0  # fork: EC TTL


class Disk:
    def __init__(self, disk_type: str, max_volume_count: int = 0):
        self.type = disk_type
        self.max_volume_count = max_volume_count
        self.volumes: dict[int, VolumeInfo] = {}
        self.ec_shards: dict[int, EcShardInfo] = {}

    @property
    def volume_count(self) -> int:
        return len(self.volumes)

    @property
    def ec_shard_count(self) -> int:
        return sum(ec_accounting.shard_count(s.shard_bits)
                   for s in self.ec_shards.values())

    def free_slots(self, ec_shards_per_slot: int = 14) -> int:
        used = self.volume_count + (self.ec_shard_count + ec_shards_per_slot - 1) // ec_shards_per_slot
        return max(0, self.max_volume_count - used)


class DataNode:
    def __init__(self, ip: str, port: int, grpc_port: int = 0,
                 public_url: str = "", rack: "Rack | None" = None):
        self.ip = ip
        self.port = port
        self.grpc_port = grpc_port or port + 10000
        self.public_url = public_url or f"{ip}:{port}"
        self.rack = rack
        self.disks: dict[str, Disk] = {}
        self.last_seen = time.monotonic()  # staleness clock, not wall
        self.max_file_key = 0

    @property
    def id(self) -> str:
        return f"{self.ip}:{self.port}"

    @property
    def url(self) -> str:
        return f"{self.ip}:{self.port}"

    @property
    def grpc_address(self) -> str:
        return f"{self.ip}:{self.grpc_port}"

    def disk(self, disk_type: str) -> Disk:
        d = self.disks.get(disk_type)
        if d is None:
            d = self.disks[disk_type] = Disk(disk_type)
        return d

    def all_volumes(self):
        for d in self.disks.values():
            yield from d.volumes.values()

    def all_ec_shards(self):
        for d in self.disks.values():
            yield from d.ec_shards.values()

    def free_slots(self, disk_type: str) -> int:
        d = self.disks.get(disk_type)
        return d.free_slots() if d else 0


class Rack:
    def __init__(self, rid: str, dc: "DataCenter"):
        self.id = rid
        self.dc = dc
        self.nodes: dict[str, DataNode] = {}


class DataCenter:
    def __init__(self, did: str):
        self.id = did
        self.racks: dict[str, Rack] = {}

    def rack(self, rid: str) -> Rack:
        r = self.racks.get(rid)
        if r is None:
            r = self.racks[rid] = Rack(rid, self)
        return r


class Topology:
    """Reference topology.go:59. Thread-safe via one coarse lock (the master
    is control-plane only; contention is low)."""

    def __init__(self, volume_size_limit: int = 30_000 * 1024 * 1024):
        self.lock = threading.RLock()
        self.dcs: dict[str, DataCenter] = {}
        self.volume_size_limit = volume_size_limit
        self.max_volume_id = 0
        # vid -> {node_id: DataNode} for normal volumes
        self.volume_locations: dict[int, dict[str, DataNode]] = {}
        # vid -> {shard_id -> set[node_id]}, and vid -> collection
        self.ec_locations: dict[int, dict[int, set[str]]] = {}
        self.ec_collections: dict[int, str] = {}
        # vid -> stripe-width high-water mark (max shard id ever seen + 1):
        # heartbeats don't carry RS(k,m), so the health plane infers each
        # volume's expected n from the ids observed over time — robust to
        # later shard loss, reset only when the volume itself goes away
        self.ec_expected: dict[int, int] = {}
        self.nodes: dict[str, DataNode] = {}

    # -- registration ------------------------------------------------------
    def get_or_create_node(self, ip: str, port: int, grpc_port: int,
                           public_url: str, dc: str, rack: str,
                           max_volume_counts: dict[str, int]) -> DataNode:
        with self.lock:
            nid = f"{ip}:{port}"
            node = self.nodes.get(nid)
            if node is None:
                dco = self.dcs.setdefault(dc or "DefaultDataCenter",
                                          DataCenter(dc or "DefaultDataCenter"))
                ro = dco.rack(rack or "DefaultRack")
                node = DataNode(ip, port, grpc_port, public_url, ro)
                ro.nodes[nid] = node
                self.nodes[nid] = node
            for dtype, cnt in (max_volume_counts or {}).items():
                node.disk(dtype).max_volume_count = cnt
            node.last_seen = time.monotonic()
            return node

    def sync_volumes(self, node: DataNode, volumes: list[VolumeInfo]
                     ) -> tuple[list[VolumeInfo], list[VolumeInfo]]:
        """Full-state sync; returns (new, deleted) (topology.go:322)."""
        with self.lock:
            incoming = {v.id: v for v in volumes}
            existing = {v.id: v for v in node.all_volumes()}
            new, deleted = [], []
            for vid, v in incoming.items():
                self.max_volume_id = max(self.max_volume_id, vid)
                if vid not in existing:
                    new.append(v)
                node.disk(v.disk_type).volumes[vid] = v
                self.volume_locations.setdefault(vid, {})[node.id] = node
            for vid, v in existing.items():
                if vid not in incoming:
                    deleted.append(v)
                    for d in node.disks.values():
                        d.volumes.pop(vid, None)
                    locs = self.volume_locations.get(vid)
                    if locs:
                        locs.pop(node.id, None)
                        if not locs:
                            self.volume_locations.pop(vid, None)
            return new, deleted

    def incremental_volumes(self, node: DataNode, new: list[VolumeInfo],
                            deleted: list[VolumeInfo]) -> None:
        with self.lock:
            for v in new:
                self.max_volume_id = max(self.max_volume_id, v.id)
                node.disk(v.disk_type).volumes[v.id] = v
                self.volume_locations.setdefault(v.id, {})[node.id] = node
            for v in deleted:
                for d in node.disks.values():
                    d.volumes.pop(v.id, None)
                locs = self.volume_locations.get(v.id)
                if locs:
                    locs.pop(node.id, None)
                    if not locs:
                        self.volume_locations.pop(v.id, None)

    def sync_ec_shards(self, node: DataNode, shards: list[EcShardInfo]
                       ) -> tuple[list[EcShardInfo], list[EcShardInfo]]:
        """Full EC-shard sync (topology_ec.go:16 SyncDataNodeEcShards)."""
        with self.lock:
            incoming = {s.volume_id: s for s in shards}
            existing = {s.volume_id: s for s in node.all_ec_shards()}
            new, deleted = [], []
            for vid, s in incoming.items():
                if vid not in existing or existing[vid].shard_bits != s.shard_bits:
                    new.append(s)
                node.disk(s.disk_type).ec_shards[vid] = s
                self.ec_collections[vid] = s.collection
                self._note_ec_width(vid, s.shard_bits)
                locs = self.ec_locations.setdefault(vid, {})
                # full-state diff needs the ABSENT ids too (discard arm)
                for sid in range(ec_accounting.MAX_SHARD_ID):
                    if s.shard_bits >> sid & 1:
                        locs.setdefault(sid, set()).add(node.id)
                    else:
                        locs.get(sid, set()).discard(node.id)
            for vid, s in existing.items():
                if vid not in incoming:
                    deleted.append(s)
                    self._drop_node_ec(node, vid)
            return new, deleted

    def incremental_ec_shards(self, node: DataNode, new: list[EcShardInfo],
                              deleted: list[EcShardInfo]) -> None:
        with self.lock:
            for s in new:
                cur = node.disk(s.disk_type).ec_shards.get(s.volume_id)
                bits = (cur.shard_bits if cur else 0) | s.shard_bits
                node.disk(s.disk_type).ec_shards[s.volume_id] = EcShardInfo(
                    s.volume_id, s.collection, bits, s.disk_type, s.destroy_time)
                self.ec_collections[s.volume_id] = s.collection
                self._note_ec_width(s.volume_id, s.shard_bits)
                locs = self.ec_locations.setdefault(s.volume_id, {})
                for sid in ec_accounting.shard_ids(s.shard_bits):
                    locs.setdefault(sid, set()).add(node.id)
            for s in deleted:
                for d in node.disks.values():
                    cur = d.ec_shards.get(s.volume_id)
                    if cur:
                        cur.shard_bits &= ~s.shard_bits
                        if cur.shard_bits == 0:
                            d.ec_shards.pop(s.volume_id, None)
                locs = self.ec_locations.get(s.volume_id, {})
                for sid in ec_accounting.shard_ids(s.shard_bits):
                    locs.get(sid, set()).discard(node.id)

    def _note_ec_width(self, vid: int, shard_bits: int) -> None:
        # lock held by caller
        ids = ec_accounting.shard_ids(shard_bits)
        if ids:
            self.ec_expected[vid] = max(self.ec_expected.get(vid, 0),
                                        ids[-1] + 1)

    def _drop_node_ec(self, node: DataNode, vid: int) -> None:
        for d in node.disks.values():
            d.ec_shards.pop(vid, None)
        locs = self.ec_locations.get(vid, {})
        for sid in list(locs):
            locs[sid].discard(node.id)
            if not locs[sid]:
                locs.pop(sid)
        if not locs:
            self.ec_locations.pop(vid, None)
            self.ec_collections.pop(vid, None)
            self.ec_expected.pop(vid, None)

    def unregister_node(self, node: DataNode) -> tuple[list[int], list[int]]:
        """Node death: remove all its volumes/shards; returns (vids, ec_vids)
        whose location sets changed (master_grpc_server.go:64-96)."""
        with self.lock:
            vids = [v.id for v in node.all_volumes()]
            ec_vids = [s.volume_id for s in node.all_ec_shards()]
            for vid in vids:
                locs = self.volume_locations.get(vid)
                if locs:
                    locs.pop(node.id, None)
                    if not locs:
                        self.volume_locations.pop(vid, None)
            for vid in ec_vids:
                self._drop_node_ec(node, vid)
            for d in node.disks.values():
                d.volumes.clear()
                d.ec_shards.clear()
            if node.rack:
                node.rack.nodes.pop(node.id, None)
            self.nodes.pop(node.id, None)
            return vids, ec_vids

    # -- lookup ------------------------------------------------------------
    def lookup(self, vid: int) -> list[DataNode]:
        with self.lock:
            return list(self.volume_locations.get(vid, {}).values())

    def lookup_ec(self, vid: int) -> dict[int, list[DataNode]]:
        with self.lock:
            out = {}
            for sid, nids in self.ec_locations.get(vid, {}).items():
                holders = [self.nodes[n] for n in nids if n in self.nodes]
                if holders:  # fully-evacuated shard ids are not locations
                    out[sid] = holders
            return out

    def next_volume_id(self) -> int:
        with self.lock:
            self.max_volume_id += 1
            return self.max_volume_id

    def all_nodes(self) -> list[DataNode]:
        with self.lock:
            return list(self.nodes.values())

    def collections(self) -> set[str]:
        with self.lock:
            out = set()
            for node in self.nodes.values():
                for v in node.all_volumes():
                    out.add(v.collection)
                for s in node.all_ec_shards():
                    out.add(s.collection)
            return out
