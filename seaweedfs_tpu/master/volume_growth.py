"""VolumeGrowth: pick servers for new volume replica sets and allocate.

Reference: weed/topology/volume_growth.go:94 (AutomaticGrowByType),
:147 (findEmptySlotsForOneVolume), :245 (grow + AllocateVolume RPC). The
replica placement xyz code decides the spread: first server in some rack,
`same_rack` more in that rack, `other_rack` in other racks of the same DC,
`other_dc` in other data centers.

Candidate picks inside each structural slot go through the placement
engine's shared scoring core (seaweedfs_tpu/placement/engine.py): free
slots, byte load (volume AND EC shard bytes), and live breaker state
rank the candidates, so a half-dead or shard-crushed node stops winning
placements just because it has free slots. All randomness flows through
ONE injectable `random.Random` (`rng=`) — tests seed it and the pick
paths become reproducible (the spread property tests pin the
same_rack/other_rack contract across randomized topologies).
"""

from __future__ import annotations

import random
from dataclasses import dataclass

from ..storage.types import ReplicaPlacement
from ..utils.log import logger
from .topology import DataNode, Topology

log = logger("growth")


@dataclass
class GrowRequest:
    collection: str = ""
    replication: str = "000"
    ttl: str = ""
    disk_type: str = "hdd"
    preferred_dc: str = ""
    preferred_rack: str = ""
    preferred_node: str = ""
    count: int = 1


class VolumeGrowth:
    def __init__(self, topo: Topology, allocate_fn=None,
                 rng: "random.Random | None" = None, costs_fn=None):
        """allocate_fn(node, vid, req) performs the AllocateVolume RPC; tests
        inject a fake. `rng` seeds every shuffle/choice in the pick paths
        (tests pin it; production uses the module-global stream).
        `costs_fn() -> LinkCostModel | None` (geo plane) prices the
        other-DC replica choice — called lazily so the master can wire
        it before its policy parses."""
        self.topo = topo
        self.allocate_fn = allocate_fn
        self.rng = rng if rng is not None else random
        self.costs_fn = costs_fn

    def find_slots(self, req: GrowRequest) -> list[DataNode]:
        """Pick a replica set honoring the placement code, or raise."""
        rp = ReplicaPlacement.parse(req.replication)
        with self.topo.lock:
            dcs = list(self.topo.dcs.values())
            # shuffle-then-stable-sort: DCs rank by free capacity
            # (emptiest first) with ties staying randomized — repeated
            # grows fill the fleet evenly instead of coin-flipping
            self.rng.shuffle(dcs)
            dcs.sort(key=lambda d: -self._dc_free(d, req.disk_type))
            main_dc = None
            for dc in dcs:
                if req.preferred_dc and dc.id != req.preferred_dc:
                    continue
                # need rp.other_dc other DCs with >=1 free slot
                others = [d for d in dcs if d.id != dc.id
                          and self._dc_free(d, req.disk_type) >= 1]
                if len(others) < rp.other_dc:
                    continue
                picked = self._pick_in_dc(dc, rp, req)
                if picked is None:
                    continue
                main_dc = dc
                servers = picked
                for d in self._order_other_dcs(others, dc, rp.other_dc):
                    n = self._pick_one(self._dc_nodes(d), req)
                    if n is None:
                        break
                    servers.append(n)
                else:
                    return servers
            if main_dc is None:
                raise RuntimeError(
                    f"no free volume slots for replication {req.replication} "
                    f"disk {req.disk_type}")
            raise RuntimeError("insufficient data centers for replication")

    def _order_other_dcs(self, others: list, main_dc, k: int) -> list:
        """The `k` other DCs an other_dc replica lands in. Geo-blind:
        a plain random sample (the historical behavior). With a link
        cost model: a random permutation stably re-sorted by link cost
        from the main DC, so the CHEAPEST cross-DC links carry replica
        traffic first and equal-cost ties stay randomized — on a fleet
        with uniform cross-DC pricing this degrades to the exact
        random sample."""
        costs = self.costs_fn() if self.costs_fn is not None else None
        if costs is None:
            return self.rng.sample(others, k)
        chosen = self.rng.sample(others, len(others))
        chosen.sort(key=lambda d: costs.cost(main_dc.id, "", d.id, ""))
        return chosen[:k]

    def _dc_nodes(self, dc) -> list[DataNode]:
        return [n for r in dc.racks.values() for n in r.nodes.values()]

    def _dc_free(self, dc, disk_type: str) -> int:
        return sum(n.free_slots(disk_type) for n in self._dc_nodes(dc))

    def _pick_one(self, nodes: list[DataNode], req: GrowRequest,
                  exclude: set[str] = frozenset()) -> DataNode | None:
        """Best candidate by the shared placement score (free ratio,
        byte load incl. EC shards, breaker state); exact-score ties
        break through self.rng so a seeded run is reproducible."""
        cands = [n for n in nodes if n.id not in exclude
                 and n.free_slots(req.disk_type) >= 1
                 and (not req.preferred_node or n.id == req.preferred_node)]
        if not cands:
            return None
        from ..placement import engine as placement_engine
        views = [placement_engine.view_of_data_node(
            n, self.topo.volume_size_limit, disk_type=req.disk_type)
            for n in cands]
        best = placement_engine.pick_best(views, rng=self.rng)
        return next(n for n in cands if n.id == best.id)

    def _pick_in_dc(self, dc, rp: ReplicaPlacement, req: GrowRequest
                    ) -> list[DataNode] | None:
        racks = list(dc.racks.values())
        # same shuffle-then-sort as DCs: the emptiest rack hosts the
        # next volume (rack-level even fill), random only across ties
        self.rng.shuffle(racks)
        racks.sort(key=lambda r: -sum(n.free_slots(req.disk_type)
                                      for n in r.nodes.values()))
        for rack in racks:
            if req.preferred_rack and rack.id != req.preferred_rack:
                continue
            other_racks = [r for r in racks if r.id != rack.id
                           and any(n.free_slots(req.disk_type) >= 1
                                   for n in r.nodes.values())]
            if len(other_racks) < rp.other_rack:
                continue
            # same_rack + 1 servers inside this rack
            nodes = list(rack.nodes.values())
            picked: list[DataNode] = []
            used: set[str] = set()
            for _ in range(rp.same_rack + 1):
                n = self._pick_one(nodes, req, exclude=used)
                if n is None:
                    picked = []
                    break
                picked.append(n)
                used.add(n.id)
            if not picked:
                continue
            for r in self.rng.sample(other_racks, rp.other_rack):
                n = self._pick_one(list(r.nodes.values()), req)
                if n is None:
                    return None
                picked.append(n)
            return picked
        return None

    def grow(self, req: GrowRequest) -> list[tuple[int, list[DataNode]]]:
        """Allocate req.count new volumes; returns [(vid, servers)]."""
        out = []
        for _ in range(max(1, req.count)):
            servers = self.find_slots(req)
            vid = self.topo.next_volume_id()
            ok = True
            for node in servers:
                if self.allocate_fn is not None:
                    try:
                        self.allocate_fn(node, vid, req)
                    except Exception as e:  # noqa: BLE001
                        log.warning("allocate vid=%d on %s failed: %s",
                                    vid, node.id, e)
                        ok = False
                        break
            if ok:
                out.append((vid, servers))
        if not out:
            raise RuntimeError("volume growth failed on all candidates")
        return out
