"""VolumeLayout: writable-volume tracking per (collection, rp, ttl, disk).

Reference: weed/topology/volume_layout.go:132 (state), :291 (PickForWrite),
:214 (EnsureCorrectWritables). The layout answers "which volume id should
this write go to" with round-robin over writable volumes whose replica sets
are complete and under the size limit.
"""

from __future__ import annotations

import random
import threading

from .topology import Topology, VolumeInfo


class VolumeLayout:
    def __init__(self, topo: Topology, collection: str, replication: str,
                 ttl: str, disk_type: str):
        self.topo = topo
        self.collection = collection
        self.replication = replication
        self.ttl = ttl
        self.disk_type = disk_type
        self.writable: set[int] = set()
        self.readonly: set[int] = set()
        self.oversized: set[int] = set()
        self.crowded: set[int] = set()
        self.lock = threading.RLock()
        from ..storage.types import ReplicaPlacement
        self._copy_count = ReplicaPlacement.parse(replication).copy_count

    def register(self, v: VolumeInfo) -> None:
        with self.lock:
            if v.read_only:
                self.readonly.add(v.id)
                self.writable.discard(v.id)
            elif v.size >= self.topo.volume_size_limit:
                self.oversized.add(v.id)
                self.writable.discard(v.id)
            else:
                self.readonly.discard(v.id)
                self.writable.add(v.id)

    def unregister(self, vid: int) -> None:
        with self.lock:
            self.writable.discard(vid)
            self.readonly.discard(vid)
            self.oversized.discard(vid)
            self.crowded.discard(vid)

    def ensure_correct_writables(self) -> None:
        """Drop volumes whose replica sets are incomplete or oversized."""
        with self.lock:
            for vid in list(self.writable):
                locs = self.topo.lookup(vid)
                if len(locs) < self._copy_count:
                    self.writable.discard(vid)
                # iterate node volume dicts under the TOPOLOGY lock:
                # heartbeat ingest mutates disk.volumes concurrently
                # ("dictionary changed size during iteration" — caught by
                # tests/stress assign-storm)
                with self.topo.lock:
                    infos = [v for n in locs for v in n.all_volumes()
                             if v.id == vid]
                if any(v.size >= self.topo.volume_size_limit or v.read_only
                       for v in infos):
                    self.writable.discard(vid)

    def pick_for_write(self) -> int | None:
        with self.lock:
            if not self.writable:
                return None
            return random.choice(tuple(self.writable))

    def active_count(self) -> int:
        with self.lock:
            return len(self.writable)

    def should_grow(self, min_active: int = 1) -> bool:
        return self.active_count() < min_active


class LayoutRegistry:
    def __init__(self, topo: Topology):
        self.topo = topo
        self._layouts: dict[tuple, VolumeLayout] = {}
        self.lock = threading.RLock()

    def get(self, collection: str, replication: str, ttl: str,
            disk_type: str) -> VolumeLayout:
        key = (collection, replication, ttl, disk_type)
        with self.lock:
            lo = self._layouts.get(key)
            if lo is None:
                lo = self._layouts[key] = VolumeLayout(
                    self.topo, collection, replication, ttl, disk_type)
            return lo

    def register_volume(self, v: VolumeInfo) -> None:
        self.get(v.collection, str(v.replica_placement), str(v.ttl),
                 v.disk_type).register(v)

    def unregister_volume(self, v: VolumeInfo) -> None:
        self.get(v.collection, str(v.replica_placement), str(v.ttl),
                 v.disk_type).unregister(v.id)

    def all_layouts(self) -> list[VolumeLayout]:
        with self.lock:
            return list(self._layouts.values())
