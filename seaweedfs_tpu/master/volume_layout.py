"""VolumeLayout: writable-volume tracking per (collection, rp, ttl, disk).

Reference: weed/topology/volume_layout.go:132 (state), :291 (PickForWrite),
:214 (EnsureCorrectWritables). The layout answers "which volume id should
this write go to" with round-robin over writable volumes whose replica sets
are complete and under the size limit.
"""

from __future__ import annotations

import random
import threading

from .topology import Topology, VolumeInfo


class VolumeLayout:
    def __init__(self, topo: Topology, collection: str, replication: str,
                 ttl: str, disk_type: str):
        self.topo = topo
        self.collection = collection
        self.replication = replication
        self.ttl = ttl
        self.disk_type = disk_type
        self.writable: set[int] = set()
        self.readonly: set[int] = set()
        self.oversized: set[int] = set()
        self.crowded: set[int] = set()
        self.lock = threading.RLock()
        from ..storage.types import ReplicaPlacement
        self._copy_count = ReplicaPlacement.parse(replication).copy_count

    def register(self, v: VolumeInfo) -> None:
        with self.lock:
            if v.read_only:
                self.readonly.add(v.id)
                self.writable.discard(v.id)
            elif v.size >= self.topo.volume_size_limit:
                self.oversized.add(v.id)
                self.writable.discard(v.id)
            else:
                self.readonly.discard(v.id)
                self.writable.add(v.id)

    def unregister(self, vid: int) -> None:
        with self.lock:
            self.writable.discard(vid)
            self.readonly.discard(vid)
            self.oversized.discard(vid)
            self.crowded.discard(vid)

    def ensure_correct_writables(self) -> None:
        """Drop volumes whose replica sets are incomplete or oversized."""
        with self.lock:
            for vid in list(self.writable):
                locs = self.topo.lookup(vid)
                if len(locs) < self._copy_count:
                    self.writable.discard(vid)
                # iterate node volume dicts under the TOPOLOGY lock:
                # heartbeat ingest mutates disk.volumes concurrently
                # ("dictionary changed size during iteration" — caught by
                # tests/stress assign-storm)
                with self.topo.lock:
                    infos = [v for n in locs for v in n.all_volumes()
                             if v.id == vid]
                if any(v.size >= self.topo.volume_size_limit or v.read_only
                       for v in infos):
                    self.writable.discard(vid)

    def pick_for_write(self) -> int | None:
        """A writable volume id, placement-aware: volumes whose every
        holder sits behind an OPEN circuit breaker are deprioritized
        (an assign pointing at a half-dead node costs the client a
        retry budget), and among the healthy the pick is weighted
        toward holders with lower byte load — the placement engine's
        load definition (volume + EC shard bytes), so hot nodes shed
        new write traffic naturally. Still randomized across the
        preferred tier so one volume never becomes the write hotspot."""
        with self.lock:
            if not self.writable:
                return None
            cands = tuple(self.writable)
            if len(cands) == 1:
                return cands[0]
            healthy, shunned = [], []
            try:
                from .. import ec as ec_accounting
                from ..placement.engine import DEFAULT_SHARD_DIVISOR
                from ..utils import retry
                est_shard = (self.topo.volume_size_limit
                             // DEFAULT_SHARD_DIVISOR)
                # per-NODE byte loads memoized once (several writable
                # vids share holders — recomputing per vid made every
                # assign O(vids x volumes) under the topology lock),
                # counting volume bytes AND estimated EC shard bytes:
                # the engine's one load definition, so a shard-crushed
                # holder can't read as empty on the write path either
                node_bytes: dict[str, int] = {}

                def load_of(h) -> int:
                    b = node_bytes.get(h.id)
                    if b is None:
                        b = sum(v.size for v in h.all_volumes()) + \
                            est_shard * sum(
                                ec_accounting.shard_count(s.shard_bits)
                                for s in h.all_ec_shards())
                        node_bytes[h.id] = b
                    return b

                # iterate holder maps under the topology lock:
                # heartbeat ingest mutates them concurrently
                with self.topo.lock:
                    loads = {}
                    for vid in cands:
                        holders = list(
                            self.topo.volume_locations.get(vid, {})
                            .values())
                        if holders and all(
                                retry.breaker(h.id).state == retry.OPEN
                                for h in holders):
                            shunned.append(vid)
                            continue
                        healthy.append(vid)
                        loads[vid] = max(
                            (load_of(h) for h in holders), default=0)
            except Exception:  # noqa: BLE001  # swtpu-lint: disable=silent-except (placement nuance must never fail an assign)
                return random.choice(cands)
            if not healthy:
                return random.choice(cands)
            # prefer volumes at or under the median holder byte load;
            # <= median (not "first half sorted") so ties — the common
            # fresh-cluster case — keep the WHOLE candidate set and
            # writes stay uniformly spread across servers
            ranked = sorted(loads.get(vid, 0) for vid in healthy)
            median = ranked[(len(ranked) - 1) // 2]
            tier = [vid for vid in healthy
                    if loads.get(vid, 0) <= median]
            return random.choice(tier or healthy)

    def active_count(self) -> int:
        with self.lock:
            return len(self.writable)

    def should_grow(self, min_active: int = 1) -> bool:
        return self.active_count() < min_active


class LayoutRegistry:
    def __init__(self, topo: Topology):
        self.topo = topo
        self._layouts: dict[tuple, VolumeLayout] = {}
        self.lock = threading.RLock()

    def get(self, collection: str, replication: str, ttl: str,
            disk_type: str) -> VolumeLayout:
        key = (collection, replication, ttl, disk_type)
        with self.lock:
            lo = self._layouts.get(key)
            if lo is None:
                lo = self._layouts[key] = VolumeLayout(
                    self.topo, collection, replication, ttl, disk_type)
            return lo

    def register_volume(self, v: VolumeInfo) -> None:
        self.get(v.collection, str(v.replica_placement), str(v.ttl),
                 v.disk_type).register(v)

    def unregister_volume(self, v: VolumeInfo) -> None:
        self.get(v.collection, str(v.replica_placement), str(v.ttl),
                 v.disk_type).unregister(v.id)

    def all_layouts(self) -> list[VolumeLayout]:
        with self.lock:
            return list(self._layouts.values())
