"""ECPipeline — the flagship device pipeline ("model") of the framework.

One "step" is the full data-integrity cycle a storage cluster runs
continuously: encode stripe batches into parity, scrub needle CRCs, and
rebuild lost shards — all on device, sharded over a ('data', 'shard') mesh.
This is the compute plane behind BASELINE configs 2-4 and the target of the
__graft_entry__ compile checks.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..ops import crc32c
from ..parallel import pipeline as pp
from ..parallel.mesh import build_mesh


@dataclass
class ECPipeline:
    d: int = 10
    p: int = 4
    mesh: object = None

    def __post_init__(self):
        if self.mesh is None:
            self.mesh = build_mesh()

    @property
    def n(self) -> int:
        return self.d + self.p

    def n_pad(self) -> int:
        ns = self.mesh.shape["shard"]
        return (self.n + ns - 1) // ns * ns

    # -- single-chip forward (graft entry() target) -------------------------
    def forward(self, data: jax.Array) -> jax.Array:
        """Jittable forward: stripe batch [B, d, L] -> parity [B, p, L].

        Single-chip path rides the Pallas kernel on a real TPU (ops/
        rs_pallas, ~3x the einsum formulation); the einsum path covers
        CPU/virtual-mesh runs where Mosaic can't compile."""
        from ..ops import rs_jax, rs_pallas
        if rs_pallas.available() and data.ndim == 3:
            return rs_pallas.encode_jit(data, self.d, self.p)
        return rs_jax.encode(data, self.d, self.p)

    # -- full distributed step (dryrun_multichip target) --------------------
    def step(self, data: jax.Array, lost: tuple[int, ...]) -> dict:
        """Encode -> scatter into shard layout -> rebuild `lost` -> verify.

        data: [B, d, L] global array (B sharded over 'data').
        Returns device metrics: rebuild byte-mismatch count (must be 0) and
        parity checksum mismatches vs recomputation (must be 0).
        """
        mesh = self.mesh
        d, p, n = self.d, self.p, self.n
        n_pad = self.n_pad()
        parity = pp.encode_sharded(mesh, data, d, p)  # [B, p_pad, L]
        b, _, l = data.shape

        # assemble [B, n_pad, L] shard tensor: data rows then parity rows
        shards = jnp.zeros((b, n_pad, l), dtype=jnp.uint8)
        shards = shards.at[:, :d, :].set(data)
        shards = shards.at[:, d:d + p, :].set(parity[:, :p, :])
        shards = jax.lax.with_sharding_constraint(
            shards, jax.sharding.NamedSharding(mesh, P("data", "shard", None)))

        # zero the lost rows, rebuild from survivors
        present = tuple(i for i in range(n) if i not in lost)
        wiped = shards.at[:, list(lost), :].set(0)
        rebuilt = pp.rebuild_sharded(mesh, wiped, present, d, p)

        mismatch = jnp.sum(
            (rebuilt[:, :n, :] != shards[:, :n, :]).astype(jnp.int32))
        return {"rebuild_mismatch_bytes": mismatch,
                "bytes_encoded": jnp.int64(b) * d * l if jax.config.x64_enabled
                else jnp.int32(b * d * l)}

    def scrub(self, blocks: np.ndarray, lengths: np.ndarray) -> int:
        """Host-facing scrub: needles left-padded into [B, L] + true lengths.
        Computes device CRC states, compares against host-side expected
        values derived from stored checksums. Returns mismatch count."""
        states = pp.scrub_sharded(self.mesh,
                                  pp.shard_put(self.mesh, blocks, P(("data", "shard"), None)),
                                  pp.shard_put(self.mesh, self._expected(blocks, lengths),
                                               P(("data", "shard"))))
        return int(jax.device_get(states))

    @staticmethod
    def _expected(blocks: np.ndarray, lengths: np.ndarray) -> np.ndarray:
        """Expected raw device states for intact blocks (host oracle)."""
        out = np.zeros(len(blocks), dtype=np.uint32)
        for i, (blk, ln) in enumerate(zip(blocks, lengths)):
            msg = blk[len(blk) - ln:]
            true = crc32c.crc32c(msg.tobytes())
            # invert finalize: raw = value ^ correction ^ 0xFFFFFFFF
            corr = crc32c.zero_prefix_correction(np.array([ln]))[0]
            out[i] = np.uint32(true) ^ corr ^ np.uint32(0xFFFFFFFF)
        return out
