"""FUSE mount subsystem (reference weed/mount, 5,330 LoC).

Architecture mirrors the reference: an inode<->path map
(inode_to_path.go), a local metadata cache kept fresh by the filer
metadata subscription (mount/meta_cache), a write-back page cache with
chunk-granular dirty pages and a concurrent upload pipeline
(page_writer.go, page_writer/upload_pipeline.go), and the filesystem
facade WeedFS (weedfs.go) exposing FUSE-shaped operations.

The kernel bridge is pluggable: `WeedFS` is a plain object whose methods
map 1:1 onto FUSE callbacks; when the `fuse` (fusepy) module is present,
`mount()` adapts it onto a real kernel mount. The image has no fusepy,
so tests drive WeedFS directly — same split the reference uses between
weedfs.go (logic) and go-fuse (kernel glue).
"""

from .inode_map import InodeToPath
from .page_writer import ChunkedDirtyPages, MemChunk, SwapFileChunk, UploadPipeline
from .meta_cache import MetaCache
from .weedfs import WeedFS

__all__ = ["InodeToPath", "ChunkedDirtyPages", "MemChunk", "SwapFileChunk",
           "UploadPipeline", "MetaCache", "WeedFS"]
