"""Local control socket for a live kernel mount.

Reference: `weed shell mount.configure` dials the mount process over a
unix socket derived from the mount directory
(command_mount_configure.go: /tmp/seaweedfs-mount-<hash>.sock) and calls
the mount_pb Configure RPC (CollectionCapacity quota). Same wire shape
here: one length-prefixed mount_pb.ConfigureRequest per connection,
answered by a length-prefixed ConfigureResponse (pb/mount.proto).
"""

from __future__ import annotations

import hashlib
import os
import socket
import struct
import threading

from ..pb import mount_pb2 as mpb


def mount_socket_path(mount_dir: str) -> str:
    """Stable per-mountpoint socket path (reference HashToInt32 of the
    dir; any stable digest works as long as shell and mount agree)."""
    h = hashlib.md5(os.path.abspath(mount_dir).encode(),
                    usedforsecurity=False).hexdigest()[:12]
    return f"/tmp/swtpu-mount-{h}.sock"


def _send_msg(conn: socket.socket, msg) -> None:
    raw = msg.SerializeToString()
    conn.sendall(struct.pack(">I", len(raw)) + raw)


def _recv_msg(rf, cls):
    hdr = rf.read(4)
    if len(hdr) < 4:
        raise ConnectionError("control peer closed")
    (n,) = struct.unpack(">I", hdr)
    raw = rf.read(n)
    if len(raw) < n:
        raise ConnectionError("truncated control message")
    msg = cls()
    msg.ParseFromString(raw)
    return msg


def serve_mount_control(wfs, sock_path: str):
    """Answer ConfigureRequest messages against the live WeedFS.
    Returns a stop() closure."""
    try:
        os.unlink(sock_path)
    except FileNotFoundError:
        pass
    srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    srv.bind(sock_path)
    srv.listen(2)
    stop_flag = threading.Event()

    def loop():
        while not stop_flag.is_set():
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            with conn:
                resp = mpb.ConfigureResponse()
                try:
                    conn.settimeout(5.0)  # a silent client must not wedge
                    req = _recv_msg(conn.makefile("rb"),
                                    mpb.ConfigureRequest)
                    # apply unconditionally: capacity 0 CLEARS a quota
                    wfs.configure(req.collection_capacity)
                    resp.collection_capacity = wfs.collection_capacity
                except Exception as e:  # noqa: BLE001
                    resp.error = str(e)
                try:
                    _send_msg(conn, resp)
                except OSError:
                    pass

    t = threading.Thread(target=loop, daemon=True, name="mount-control")
    t.start()

    def stop():
        stop_flag.set()
        try:
            srv.close()
        except OSError:
            pass
        try:
            os.unlink(sock_path)
        except FileNotFoundError:
            pass

    return stop


def configure_mount(mount_dir: str, collection_capacity: int) -> dict:
    """Client side (the shell command): one request/response."""
    path = mount_socket_path(mount_dir)
    c = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    c.settimeout(5.0)
    try:
        c.connect(path)
        _send_msg(c, mpb.ConfigureRequest(
            collection_capacity=collection_capacity))
        resp = _recv_msg(c.makefile("rb"), mpb.ConfigureResponse)
        out = {"ok": not resp.error,
               "collection_capacity": resp.collection_capacity}
        if resp.error:
            out["error"] = resp.error
        return out
    finally:
        c.close()
