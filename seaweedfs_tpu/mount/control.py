"""Local control socket for a live kernel mount.

Reference: `weed shell mount.configure` dials the mount process over a
unix socket derived from the mount directory
(command_mount_configure.go: /tmp/seaweedfs-mount-<hash>.sock) and calls
the mount_pb Configure RPC (CollectionCapacity quota). Same shape here
with newline-delimited JSON instead of gRPC — the socket only ever
carries one tiny local RPC.
"""

from __future__ import annotations

import hashlib
import json
import os
import socket
import threading


def mount_socket_path(mount_dir: str) -> str:
    """Stable per-mountpoint socket path (reference HashToInt32 of the
    dir; any stable digest works as long as shell and mount agree)."""
    h = hashlib.md5(os.path.abspath(mount_dir).encode()).hexdigest()[:12]
    return f"/tmp/swtpu-mount-{h}.sock"


def serve_mount_control(wfs, sock_path: str):
    """Listen for {"collection_capacity": N} lines; apply to the live
    WeedFS. Returns a stop() closure."""
    try:
        os.unlink(sock_path)
    except FileNotFoundError:
        pass
    srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    srv.bind(sock_path)
    srv.listen(2)
    stop_flag = threading.Event()

    def loop():
        while not stop_flag.is_set():
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            with conn:
                try:
                    conn.settimeout(5.0)  # a silent client must not wedge
                    line = conn.makefile("rb").readline()
                    req = json.loads(line or b"{}")
                    if "collection_capacity" in req:
                        wfs.configure(req["collection_capacity"])
                    resp = {"ok": True,
                            "collection_capacity": wfs.collection_capacity}
                except Exception as e:  # noqa: BLE001
                    resp = {"ok": False, "error": str(e)}
                try:
                    conn.sendall(json.dumps(resp).encode() + b"\n")
                except OSError:
                    pass

    t = threading.Thread(target=loop, daemon=True, name="mount-control")
    t.start()

    def stop():
        stop_flag.set()
        try:
            srv.close()
        except OSError:
            pass
        try:
            os.unlink(sock_path)
        except FileNotFoundError:
            pass

    return stop


def configure_mount(mount_dir: str, collection_capacity: int) -> dict:
    """Client side (the shell command): one request/response."""
    path = mount_socket_path(mount_dir)
    c = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    c.settimeout(5.0)
    try:
        c.connect(path)
        c.sendall(json.dumps(
            {"collection_capacity": collection_capacity}).encode() + b"\n")
        return json.loads(c.makefile("rb").readline() or b"{}")
    finally:
        c.close()
