"""Minimal ctypes binding to libfuse 2.9 (x86_64 Linux) — no fusepy needed.

Reference: the Go side uses hanwen/go-fuse (weed/mount, go.mod:141); this is
the Python equivalent of the small slice of the libfuse high-level API the
mount needs: getattr/readdir/create/open/read/write/flush/release/
truncate/unlink/mkdir/rmdir/rename/statfs. Struct layouts match glibc
x86_64 + libfuse 2.9's FUSE_USE_VERSION 26 ABI (same layouts fusepy ships).

Entry point: `fuse_loop(ops_dict, mountpoint, foreground=True)` where
ops_dict maps operation names to python callables that raise FuseError
(errno) on failure.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import errno as errno_mod
import os

c_stat_p = ctypes.c_void_p  # forward decl for readability


class c_timespec(ctypes.Structure):
    _fields_ = [("tv_sec", ctypes.c_long), ("tv_nsec", ctypes.c_long)]


class c_stat(ctypes.Structure):
    # glibc x86_64 struct stat
    _fields_ = [
        ("st_dev", ctypes.c_uint64),
        ("st_ino", ctypes.c_uint64),
        ("st_nlink", ctypes.c_uint64),
        ("st_mode", ctypes.c_uint32),
        ("st_uid", ctypes.c_uint32),
        ("st_gid", ctypes.c_uint32),
        ("__pad0", ctypes.c_int),
        ("st_rdev", ctypes.c_uint64),
        ("st_size", ctypes.c_int64),
        ("st_blksize", ctypes.c_int64),
        ("st_blocks", ctypes.c_int64),
        ("st_atim", c_timespec),
        ("st_mtim", c_timespec),
        ("st_ctim", c_timespec),
        ("__glibc_reserved", ctypes.c_long * 3),
    ]


class c_statvfs(ctypes.Structure):
    _fields_ = [
        ("f_bsize", ctypes.c_ulong),
        ("f_frsize", ctypes.c_ulong),
        ("f_blocks", ctypes.c_uint64),
        ("f_bfree", ctypes.c_uint64),
        ("f_bavail", ctypes.c_uint64),
        ("f_files", ctypes.c_uint64),
        ("f_ffree", ctypes.c_uint64),
        ("f_favail", ctypes.c_uint64),
        ("f_fsid", ctypes.c_ulong),
        ("f_flag", ctypes.c_ulong),
        ("f_namemax", ctypes.c_ulong),
        ("__f_spare", ctypes.c_int * 6),
    ]


class fuse_file_info(ctypes.Structure):
    _fields_ = [
        ("flags", ctypes.c_int),
        ("fh_old", ctypes.c_ulong),
        ("writepage", ctypes.c_int),
        ("flags_bits", ctypes.c_uint),  # direct_io:1 keep_cache:1 ...
        ("fh", ctypes.c_uint64),
        ("lock_owner", ctypes.c_uint64),
    ]


fuse_file_info_p = ctypes.POINTER(fuse_file_info)

# int (*fuse_fill_dir_t)(void *buf, const char *name,
#                        const struct stat *stbuf, off_t off);
fuse_fill_dir_t = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_void_p, ctypes.c_char_p,
    ctypes.POINTER(c_stat), ctypes.c_int64)

_GETATTR = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p,
                            ctypes.POINTER(c_stat))
# buf is c_void_p: a c_char_p arg would arrive as an immutable bytes copy
_READLINK = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p,
                             ctypes.c_void_p, ctypes.c_size_t)
_GETDIR = ctypes.c_void_p
_MKNOD = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p, ctypes.c_uint32,
                          ctypes.c_uint64)
_MKDIR = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p, ctypes.c_uint32)
_UNLINK = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p)
_RMDIR = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p)
_SYMLINK = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p)
_RENAME = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p)
_LINK = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p)
_CHMOD = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p, ctypes.c_uint32)
_CHOWN = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p, ctypes.c_uint32,
                          ctypes.c_uint32)
_TRUNCATE = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p, ctypes.c_int64)
_UTIME = ctypes.c_void_p
_OPEN = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p, fuse_file_info_p)
# buffer args are c_void_p: a c_char_p callback arg would be converted to
# an immutable Python bytes copy, making the read buffer unwritable
_READ = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p, ctypes.c_void_p,
                         ctypes.c_size_t, ctypes.c_int64, fuse_file_info_p)
_WRITE = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p, ctypes.c_void_p,
                          ctypes.c_size_t, ctypes.c_int64, fuse_file_info_p)
_STATFS = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p,
                           ctypes.POINTER(c_statvfs))
_FLUSH = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p, fuse_file_info_p)
_RELEASE = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p, fuse_file_info_p)
_FSYNC = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
                          fuse_file_info_p)
_READDIR = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p, ctypes.c_void_p,
                            fuse_fill_dir_t, ctypes.c_int64,
                            fuse_file_info_p)
_INIT = ctypes.CFUNCTYPE(ctypes.c_void_p, ctypes.c_void_p)
_DESTROY = ctypes.CFUNCTYPE(None, ctypes.c_void_p)
_ACCESS = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p, ctypes.c_int)
_CREATE = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p, ctypes.c_uint32,
                           fuse_file_info_p)
_FTRUNCATE = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p,
                              ctypes.c_int64, fuse_file_info_p)
_FGETATTR = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p,
                             ctypes.POINTER(c_stat), fuse_file_info_p)
_UTIMENS = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p,
                            ctypes.POINTER(c_timespec * 2))
# xattr family (libfuse 2.9 signatures; value buffers as c_void_p so the
# get/list destinations stay writable)
_SETXATTR = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p,
                             ctypes.c_void_p, ctypes.c_size_t, ctypes.c_int)
_GETXATTR = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p,
                             ctypes.c_void_p, ctypes.c_size_t)
_LISTXATTR = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p,
                              ctypes.c_void_p, ctypes.c_size_t)
_REMOVEXATTR = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p,
                                ctypes.c_char_p)


class fuse_operations(ctypes.Structure):
    # field ORDER is the libfuse 2.9 ABI (FUSE_USE_VERSION 26) — do not sort
    _fields_ = [
        ("getattr", _GETATTR),
        ("readlink", _READLINK),
        ("getdir", _GETDIR),
        ("mknod", _MKNOD),
        ("mkdir", _MKDIR),
        ("unlink", _UNLINK),
        ("rmdir", _RMDIR),
        ("symlink", _SYMLINK),
        ("rename", _RENAME),
        ("link", _LINK),
        ("chmod", _CHMOD),
        ("chown", _CHOWN),
        ("truncate", _TRUNCATE),
        ("utime", _UTIME),
        ("open", _OPEN),
        ("read", _READ),
        ("write", _WRITE),
        ("statfs", _STATFS),
        ("flush", _FLUSH),
        ("release", _RELEASE),
        ("fsync", _FSYNC),
        ("setxattr", _SETXATTR),
        ("getxattr", _GETXATTR),
        ("listxattr", _LISTXATTR),
        ("removexattr", _REMOVEXATTR),
        ("opendir", ctypes.c_void_p),
        ("readdir", _READDIR),
        ("releasedir", ctypes.c_void_p),
        ("fsyncdir", ctypes.c_void_p),
        ("init", _INIT),
        ("destroy", _DESTROY),
        ("access", _ACCESS),
        ("create", _CREATE),
        ("ftruncate", _FTRUNCATE),
        ("fgetattr", _FGETATTR),
        ("lock", ctypes.c_void_p),
        ("utimens", _UTIMENS),
        ("bmap", ctypes.c_void_p),
        ("flag_bits", ctypes.c_uint),  # nullpath_ok:1 nopath:1 ... :29
        ("ioctl", ctypes.c_void_p),
        ("poll", ctypes.c_void_p),
        ("write_buf", ctypes.c_void_p),
        ("read_buf", ctypes.c_void_p),
        ("flock", ctypes.c_void_p),
        ("fallocate", ctypes.c_void_p),
    ]


def _libfuse():
    path = ctypes.util.find_library("fuse") or "libfuse.so.2"
    return ctypes.CDLL(path)


def _fill_stat(st: c_stat, attr: dict) -> None:
    ctypes.memset(ctypes.byref(st), 0, ctypes.sizeof(st))
    st.st_mode = attr.get("st_mode", 0)
    st.st_nlink = attr.get("st_nlink", 1)
    st.st_size = attr.get("st_size", 0)
    st.st_uid = attr.get("st_uid") or os.getuid()
    st.st_gid = attr.get("st_gid") or os.getgid()
    st.st_blksize = 4096
    st.st_blocks = (st.st_size + 511) // 512
    for name, key in (("st_atim", "st_atime"), ("st_mtim", "st_mtime"),
                      ("st_ctim", "st_ctime")):
        t = float(attr.get(key, 0))
        getattr(st, name).tv_sec = int(t)
        getattr(st, name).tv_nsec = int((t % 1) * 1e9)


def fuse_loop(handlers, mountpoint: str, fsname: str = "swtpu",
              foreground: bool = True, allow_other: bool = False) -> int:
    """Mount and serve until unmounted (fusermount -u) or killed.

    handlers: object with getattr/readdir/... methods following the
    mount.weedfs.WeedFS path-based API; errors raised as FuseError(errno)
    map to negative errnos.
    """
    lib = _libfuse()

    def guard(fn):
        """Wrap a handler: FuseError -> -errno, unexpected -> -EIO."""
        def inner(*args):
            try:
                return fn(*args) or 0
            except Exception as e:  # noqa: BLE001
                eno = getattr(e, "errno", None) or errno_mod.EIO
                return -int(eno)
        return inner

    @guard
    def op_getattr(path, stbuf):
        attr = handlers.getattr(path.decode())
        _fill_stat(stbuf.contents, attr)

    @guard
    def op_fgetattr(path, stbuf, fi):
        attr = handlers.getattr(path.decode())
        _fill_stat(stbuf.contents, attr)

    @guard
    def op_readdir(path, buf, filler, offset, fi):
        for name in [".", ".."] + list(handlers.readdir(path.decode())):
            if filler(buf, name.encode(), None, 0) != 0:
                break

    @guard
    def op_mkdir(path, mode):
        handlers.mkdir(path.decode(), mode)

    @guard
    def op_rmdir(path):
        handlers.rmdir(path.decode())

    @guard
    def op_unlink(path):
        handlers.unlink(path.decode())

    @guard
    def op_rename(old, new):
        handlers.rename(old.decode(), new.decode())

    @guard
    def op_truncate(path, length):
        handlers.truncate(path.decode(), length)

    @guard
    def op_ftruncate(path, length, fi):
        handlers.truncate(path.decode(), length)

    @guard
    def op_create(path, mode, fi):
        fi.contents.fh = handlers.create(path.decode(), mode)

    @guard
    def op_open(path, fi):
        fi.contents.fh = handlers.open(path.decode())

    @guard
    def op_read(path, buf, size, offset, fi):
        data = handlers.read(fi.contents.fh, offset, size)
        n = len(data)
        ctypes.memmove(buf, data, n)
        return n

    @guard
    def op_write(path, buf, size, offset, fi):
        data = ctypes.string_at(buf, size)
        return handlers.write(fi.contents.fh, offset, data)

    @guard
    def op_flush(path, fi):
        handlers.flush(fi.contents.fh)

    @guard
    def op_release(path, fi):
        handlers.release(fi.contents.fh)

    @guard
    def op_fsync(path, datasync, fi):
        handlers.flush(fi.contents.fh)

    @guard
    def op_statfs(path, st):
        info = handlers.statfs()
        v = st.contents
        ctypes.memset(ctypes.byref(v), 0, ctypes.sizeof(v))
        v.f_bsize = info.get("f_bsize", 4096)
        v.f_frsize = info.get("f_frsize", info.get("f_bsize", 4096))
        v.f_blocks = info.get("f_blocks", 1 << 30)
        v.f_bfree = info.get("f_bfree", 1 << 30)
        v.f_bavail = info.get("f_bavail", info.get("f_bfree", 1 << 30))
        v.f_files = info.get("f_files", 1 << 20)
        v.f_ffree = v.f_favail = info.get("f_ffree", 1 << 20)
        v.f_namemax = info.get("f_namemax", 255)

    @guard
    def op_access(path, mask):
        handlers.getattr(path.decode())  # existence check

    @guard
    def op_symlink(target, linkpath):
        handlers.symlink(target.decode(), linkpath.decode())

    @guard
    def op_readlink(path, buf, size):
        target = handlers.readlink(path.decode()).encode()
        # NUL-terminated, truncated to the kernel's buffer
        data = target[:max(0, size - 1)] + b"\x00"
        ctypes.memmove(buf, data, len(data))

    @guard
    def op_link(old, new):
        handlers.link(old.decode(), new.decode())

    @guard
    def op_setxattr(path, name, value, size, flags):
        data = ctypes.string_at(value, size) if size else b""
        handlers.setxattr(path.decode(), name.decode(), data, flags)

    @guard
    def op_getxattr(path, name, buf, size):
        data = handlers.getxattr(path.decode(), name.decode())
        if size == 0:
            return len(data)  # size probe
        if len(data) > size:
            return -errno_mod.ERANGE
        ctypes.memmove(buf, data, len(data))
        return len(data)

    @guard
    def op_listxattr(path, buf, size):
        names = handlers.listxattr(path.decode())
        blob = b"".join(n.encode() + b"\x00" for n in names)
        if size == 0:
            return len(blob)
        if len(blob) > size:
            return -errno_mod.ERANGE
        if blob:
            ctypes.memmove(buf, blob, len(blob))
        return len(blob)

    @guard
    def op_removexattr(path, name):
        handlers.removexattr(path.decode(), name.decode())

    @guard
    def op_chmod(path, mode):
        handlers.chmod(path.decode(), mode)

    @guard
    def op_chown(path, uid, gid):
        handlers.chown(path.decode(), uid, gid)

    @guard
    def op_utimens(path, times):
        if not times:
            handlers.utimens(path.decode(), None, None)
            return
        ts = times.contents
        def val(t):  # UTIME_NOW(2^30-1)/UTIME_OMIT(2^30-2) in tv_nsec
            if t.tv_nsec == (1 << 30) - 2:
                return None
            if t.tv_nsec == (1 << 30) - 1:
                import time as _t
                return _t.time()
            return t.tv_sec + t.tv_nsec / 1e9
        handlers.utimens(path.decode(), val(ts[0]), val(ts[1]))

    ops = fuse_operations()
    ops.getattr = _GETATTR(op_getattr)
    ops.fgetattr = _FGETATTR(op_fgetattr)
    ops.readdir = _READDIR(op_readdir)
    ops.mkdir = _MKDIR(op_mkdir)
    ops.rmdir = _RMDIR(op_rmdir)
    ops.unlink = _UNLINK(op_unlink)
    ops.rename = _RENAME(op_rename)
    ops.truncate = _TRUNCATE(op_truncate)
    ops.ftruncate = _FTRUNCATE(op_ftruncate)
    ops.create = _CREATE(op_create)
    ops.open = _OPEN(op_open)
    ops.read = _READ(op_read)
    ops.write = _WRITE(op_write)
    ops.flush = _FLUSH(op_flush)
    ops.release = _RELEASE(op_release)
    ops.fsync = _FSYNC(op_fsync)
    ops.statfs = _STATFS(op_statfs)
    ops.symlink = _SYMLINK(op_symlink)
    ops.readlink = _READLINK(op_readlink)
    ops.link = _LINK(op_link)
    ops.setxattr = _SETXATTR(op_setxattr)
    ops.getxattr = _GETXATTR(op_getxattr)
    ops.listxattr = _LISTXATTR(op_listxattr)
    ops.removexattr = _REMOVEXATTR(op_removexattr)
    ops.access = _ACCESS(op_access)
    ops.chmod = _CHMOD(op_chmod)
    ops.chown = _CHOWN(op_chown)
    ops.utimens = _UTIMENS(op_utimens)

    args = [b"swtpu-mount", mountpoint.encode()]
    if foreground:
        args.append(b"-f")
    # use_ino: report the handlers' st_ino (hardlink sets share one inode
    # number) instead of kernel-assigned per-path inodes
    opts = [f"fsname={fsname}", "big_writes", "max_read=131072", "use_ino"]
    if allow_other:
        opts.append("allow_other")
    args += [b"-o", ",".join(opts).encode()]
    argv = (ctypes.c_char_p * len(args))(*args)

    lib.fuse_main_real.restype = ctypes.c_int
    return lib.fuse_main_real(len(args), argv, ctypes.byref(ops),
                              ctypes.sizeof(ops), None)
