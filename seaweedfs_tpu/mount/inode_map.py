"""Inode <-> path bi-map (reference weed/mount/inode_to_path.go).

FUSE speaks inodes; the filer speaks paths. Inodes are allocated
deterministically from the path hash with linear probing on collision
(the reference hashes path+mode, inode_to_path.go AllocateInode), stay
stable across lookups, and are released on Forget.
"""

from __future__ import annotations

import threading
import zlib

ROOT_INODE = 1


class InodeToPath:
    def __init__(self, root: str = "/"):
        self._lock = threading.Lock()
        self._path_to_inode: dict[str, int] = {root: ROOT_INODE}
        self._inode_to_path: dict[int, str] = {ROOT_INODE: root}
        self._refs: dict[int, int] = {ROOT_INODE: 1}

    def lookup(self, path: str) -> int:
        """Get-or-allocate the inode for a path; bumps the kernel ref."""
        with self._lock:
            ino = self._path_to_inode.get(path)
            if ino is None:
                ino = self._allocate(path)
            self._refs[ino] = self._refs.get(ino, 0) + 1
            return ino

    def _allocate(self, path: str) -> int:
        ino = (zlib.crc32(path.encode()) << 1) | 1
        while ino in self._inode_to_path:
            ino += 2  # linear probe, keep odd (root is 1, even left free)
        if ino == ROOT_INODE:
            ino += 2
        self._path_to_inode[path] = ino
        self._inode_to_path[ino] = path
        return ino

    def get_path(self, inode: int) -> str:
        with self._lock:
            p = self._inode_to_path.get(inode)
            if p is None:
                raise KeyError(f"unknown inode {inode}")
            return p

    def has_path(self, path: str) -> bool:
        with self._lock:
            return path in self._path_to_inode

    def get_inode(self, path: str) -> int | None:
        with self._lock:
            return self._path_to_inode.get(path)

    def move_path(self, old: str, new: str) -> None:
        """Rename keeps the inode (inode_to_path.go MovePath)."""
        with self._lock:
            ino = self._path_to_inode.pop(old, None)
            if ino is None:
                return
            stale = self._path_to_inode.pop(new, None)
            if stale is not None:
                self._inode_to_path.pop(stale, None)
                self._refs.pop(stale, None)
            self._path_to_inode[new] = ino
            self._inode_to_path[ino] = new

    def remove_path(self, path: str) -> None:
        with self._lock:
            ino = self._path_to_inode.pop(path, None)
            if ino is not None:
                self._inode_to_path.pop(ino, None)
                self._refs.pop(ino, None)

    def forget(self, inode: int, nlookup: int = 1) -> None:
        """Kernel dropped refs; free the mapping at zero
        (inode_to_path.go Forget)."""
        with self._lock:
            if inode == ROOT_INODE:
                return
            n = self._refs.get(inode, 0) - nlookup
            if n > 0:
                self._refs[inode] = n
                return
            self._refs.pop(inode, None)
            p = self._inode_to_path.pop(inode, None)
            if p is not None:
                self._path_to_inode.pop(p, None)
