"""Local metadata cache synced by the filer metadata subscription.

Reference: weed/mount/meta_cache/meta_cache.go (entries cached in a
local store; meta_cache_subscribe.go applies EventNotifications from
SubscribeMetadata so cached attributes stay fresh across mounts).
Entries are cached per directory on first listing; events invalidate or
update in place.
"""

from __future__ import annotations

import threading

from ..pb import filer_pb2 as fpb
from ..utils.log import logger

log = logger("mount.meta")


class MetaCache:
    def __init__(self, filer_server, subscribe: bool = True):
        self.fs = filer_server
        self._entries: dict[str, fpb.Entry] = {}   # full path -> entry
        self._listed: set[str] = set()             # directories fully cached
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._sub_thread: threading.Thread | None = None
        if subscribe:
            self._start_subscription()

    # -- subscription --------------------------------------------------------
    def _start_subscription(self) -> None:
        import time

        def run():
            since = time.time_ns()
            meta_log = self.fs.filer.meta_log
            for resp in meta_log.subscribe(since, self._stop):
                try:
                    self._apply_event(resp.directory, resp.event_notification)
                except Exception as e:  # noqa: BLE001
                    log.warning("meta event apply: %s", e)

        self._sub_thread = threading.Thread(target=run, daemon=True,
                                            name="meta-cache-sub")
        self._sub_thread.start()

    def _apply_event(self, directory: str, ev: fpb.EventNotification) -> None:
        """Mirror meta_cache_subscribe.go: delete old path, upsert new."""
        with self._lock:
            if ev.HasField("old_entry") and ev.old_entry.name:
                # events carry the old parent in `directory`; renames put
                # the target dir in new_parent_path (filer.proto:183)
                old_path = self._join(directory, ev.old_entry.name)
                self._entries.pop(old_path, None)
                if ev.old_entry.is_directory:
                    # purge cached children + listing markers of the
                    # deleted/moved subtree (reference meta_cache folder
                    # deletion handling)
                    prefix = old_path.rstrip("/") + "/"
                    for p in [p for p in self._entries
                              if p.startswith(prefix)]:
                        del self._entries[p]
                    for d in [d for d in self._listed
                              if d == old_path or d.startswith(prefix)]:
                        self._listed.discard(d)
            if ev.HasField("new_entry") and ev.new_entry.name:
                new_path = self._join(ev.new_parent_path or directory,
                                      ev.new_entry.name)
                e = fpb.Entry()
                e.CopyFrom(ev.new_entry)
                self._entries[new_path] = e

    @staticmethod
    def _join(d: str, n: str) -> str:
        return (d.rstrip("/") + "/" + n) if d != "/" else "/" + n

    # -- lookups -------------------------------------------------------------
    def find(self, directory: str, name: str) -> fpb.Entry | None:
        path = self._join(directory, name)
        with self._lock:
            hit = self._entries.get(path)
            if hit is not None and not hit.hard_link_id:
                e = fpb.Entry()
                e.CopyFrom(hit)
                return e
            # hardlinked entries read through: their truth lives in the
            # shared record, which updates through OTHER names this
            # cache never sees events for (reference keys hardlinks by
            # hard_link_id for the same reason, weedfs_link.go:17)
        entry = self.fs.filer.find_entry(directory, name)
        if entry is not None:
            with self._lock:
                cached = fpb.Entry()
                cached.CopyFrom(entry)
                self._entries[path] = cached
        return entry

    def list(self, directory: str) -> list[fpb.Entry]:
        with self._lock:
            if directory in self._listed:
                prefix = directory.rstrip("/") + "/"
                out = []
                for path, e in self._entries.items():
                    if path.startswith(prefix) and "/" not in path[len(prefix):]:
                        c = fpb.Entry()
                        c.CopyFrom(e)
                        out.append(c)
                return sorted(out, key=lambda e: e.name)
        entries = list(self.fs.filer.list_entries(directory))
        with self._lock:
            for e in entries:
                cached = fpb.Entry()
                cached.CopyFrom(e)
                self._entries[self._join(directory, e.name)] = cached
            self._listed.add(directory)
        return entries

    def invalidate(self, directory: str, name: str) -> None:
        with self._lock:
            self._entries.pop(self._join(directory, name), None)
            # the directory's cached listing no longer reflects reality
            self._listed.discard(directory)

    def close(self) -> None:
        self._stop.set()
