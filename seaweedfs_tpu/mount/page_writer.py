"""Write-back page cache: chunk-granular dirty pages + upload pipeline.

Reference: weed/mount/page_writer.go:22 (PageWriter), dirty_pages_chunked.go
(ChunkedDirtyPages), page_writer/page_chunk_mem.go / page_chunk_swapfile.go
(memory vs swap-file backing), page_writer/upload_pipeline.go (sealed
chunks upload concurrently while writes continue), activity_score.go
(sequential-vs-random scoring decides mem vs swap backing).
"""

from __future__ import annotations

import os
import tempfile
import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable

from ..utils.log import logger

log = logger("mount.pages")


class ActivityScore:
    """Sequential-writes score (reference page_writer/activity_score.go):
    monotonically increasing offsets raise it, seeks lower it. Sequential
    streams early-seal full chunks and stay in memory; a low score
    (random IO) with many live partial chunks spills to swap files."""

    def __init__(self):
        self._last_offset = -1
        self.score = 0

    def track(self, offset: int) -> None:
        if offset >= self._last_offset:
            self.score = min(self.score + 1, 64)
        else:
            self.score = max(self.score - 8, -64)
        self._last_offset = offset

    @property
    def is_sequential(self) -> bool:
        return self.score >= 16


class MemChunk:
    """In-memory page chunk (page_chunk_mem.go)."""

    def __init__(self, chunk_size: int):
        self.buf = bytearray(chunk_size)
        self.intervals: list[tuple[int, int]] = []  # sorted, merged

    def write(self, at: int, data: bytes) -> None:
        self.buf[at:at + len(data)] = data
        self._add_interval(at, at + len(data))

    def read(self, at: int, size: int) -> bytes:
        return bytes(self.buf[at:at + size])

    def _add_interval(self, start: int, stop: int) -> None:
        merged = []
        for s, e in self.intervals:
            if e < start or s > stop:
                merged.append((s, e))
            else:
                start, stop = min(s, start), max(e, stop)
        merged.append((start, stop))
        self.intervals = sorted(merged)

    @property
    def written(self) -> int:
        return sum(e - s for s, e in self.intervals)

    def content(self) -> bytes:
        """Contiguous content from 0 to max written offset (holes zero)."""
        if not self.intervals:
            return b""
        return bytes(self.buf[:self.intervals[-1][1]])

    def destroy(self) -> None:
        self.buf = bytearray(0)


class SwapFileChunk(MemChunk):
    """Disk-backed chunk for big sequential streams
    (page_chunk_swapfile.go); keeps RSS flat while a large file uploads."""

    def __init__(self, chunk_size: int, swap_dir: str | None = None):
        self.chunk_size = chunk_size
        fd, self._path = tempfile.mkstemp(prefix="swtpu-swap-",
                                          dir=swap_dir, suffix=".chunk")
        self._f = os.fdopen(fd, "r+b")
        self._f.truncate(chunk_size)
        self.intervals = []

    def write(self, at: int, data: bytes) -> None:
        self._f.seek(at)
        self._f.write(data)
        self._add_interval(at, at + len(data))

    def read(self, at: int, size: int) -> bytes:
        self._f.seek(at)
        return self._f.read(size)

    def content(self) -> bytes:
        if not self.intervals:
            return b""
        self._f.seek(0)
        return self._f.read(self.intervals[-1][1])

    def destroy(self) -> None:
        try:
            self._f.close()
            os.unlink(self._path)
        except OSError:
            pass


class UploadPipeline:
    """Concurrent sealed-chunk uploader (upload_pipeline.go): sealed
    chunks go to a worker pool; writers keep filling newer chunks.
    `saver(data, logical_offset) -> result` runs on workers; flush()
    drains and returns results ordered by logical offset.

    Backpressure: at most 2x concurrency uploads may be queued or
    running — submit() blocks past that, so a writer streaming faster
    than the uploads drain cannot accumulate the whole file in memory
    (the reference bounds its pipeline the same way). In-flight bytes
    stay readable via read_at until flush() hands the results to the
    caller (reference MaybeReadDataAt on sealed chunks)."""

    def __init__(self, saver: Callable[[bytes, int], object],
                 concurrency: int = 8):
        self._saver = saver
        self._pool = ThreadPoolExecutor(max_workers=concurrency,
                                        thread_name_prefix="upload")
        self._slots = threading.BoundedSemaphore(concurrency * 2)
        self._pending: list[tuple[int, Future]] = []
        self._inflight: dict[int, bytes] = {}  # logical_offset -> data
        self._lock = threading.Lock()

    def submit(self, data: bytes, logical_offset: int) -> None:
        import time as _time
        self._slots.acquire()
        with self._lock:
            self._inflight[logical_offset] = data
        # submit-order timestamp: uploads finish out of order on the
        # worker pool, but newest-chunk-wins resolution must follow
        # write order, not completion order
        ts_ns = _time.time_ns()

        def run():
            try:
                result = self._saver(data, logical_offset)
                if hasattr(result, "modified_ts_ns"):
                    result.modified_ts_ns = ts_ns
                return result
            finally:
                self._slots.release()

        fut = self._pool.submit(run)
        with self._lock:
            self._pending.append((logical_offset, fut))

    def read_at(self, offset: int, size: int) -> list[tuple[int, bytes]]:
        """Overlap of [offset, offset+size) with sealed-but-unmerged data."""
        out = []
        with self._lock:
            for base, data in self._inflight.items():
                lo = max(offset, base)
                hi = min(offset + size, base + len(data))
                if lo < hi:
                    out.append((lo, data[lo - base:hi - base]))
        return out

    def flush(self) -> list[object]:
        """Drain pending uploads. In-flight copies stay readable until
        the caller has merged the results into the file entry and calls
        commit() — dropping them here would open a window where the data
        is in neither the entry nor the overlay."""
        with self._lock:
            pending, self._pending = self._pending, []
            self._flushed_offsets = [off for off, _ in pending]
        results = []
        errors = []
        for off, fut in sorted(pending, key=lambda t: t[0]):
            try:
                results.append(fut.result())
            except Exception as e:  # noqa: BLE001
                errors.append(e)
        if errors:
            raise errors[0]
        return results

    def commit(self) -> None:
        """Caller merged the flushed chunks into the entry; drop copies."""
        with self._lock:
            for off in getattr(self, "_flushed_offsets", []):
                self._inflight.pop(off, None)
            self._flushed_offsets = []

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False, cancel_futures=True)


class ChunkedDirtyPages:
    """Dirty pages of one open file, chunk_size-granular
    (dirty_pages_chunked.go:41). Writes land in page chunks; a chunk
    that is fully covered seals early to the pipeline (so huge streams
    don't hold memory); flush seals the rest and drains the pipeline."""

    def __init__(self, chunk_size: int, saver: Callable[[bytes, int], object],
                 concurrency: int = 8, swap_dir: str | None = None,
                 swap_threshold_chunks: int = 16):
        self.chunk_size = chunk_size
        self._chunks: dict[int, MemChunk] = {}
        self._pipeline = UploadPipeline(saver, concurrency)
        self._activity = ActivityScore()
        self._swap_dir = swap_dir
        self._swap_threshold = swap_threshold_chunks
        self._lock = threading.Lock()
        self.dirty = False

    def _backing(self) -> type:
        # Random IO keeps many partially-written chunks alive (nothing
        # gets full enough to early-seal); spill those to disk. A
        # sequential stream seals chunks as it goes, so it never
        # accumulates live chunks and stays in memory.
        if (not self._activity.is_sequential
                and len(self._chunks) >= self._swap_threshold):
            return SwapFileChunk
        return MemChunk

    def write(self, offset: int, data: bytes) -> None:
        self.dirty = True
        with self._lock:
            self._activity.track(offset)
            pos = 0
            while pos < len(data):
                logical = offset + pos
                ci, at = divmod(logical, self.chunk_size)
                n = min(self.chunk_size - at, len(data) - pos)
                chunk = self._chunks.get(ci)
                if chunk is None:
                    cls = self._backing()
                    chunk = (cls(self.chunk_size, self._swap_dir)
                             if cls is SwapFileChunk
                             else cls(self.chunk_size))
                    self._chunks[ci] = chunk
                chunk.write(at, data[pos:pos + n])
                pos += n
                # early-seal full chunks behind the write frontier
                if chunk.written == self.chunk_size:
                    self._seal(ci)

    def _seal(self, ci: int) -> None:
        """Upload each contiguous dirty interval separately (reference
        dirty_pages_chunked.go saveChunkedFileIntervalToStorage) — holes
        must NOT be zero-filled or they'd clobber underlying file data."""
        chunk = self._chunks.pop(ci, None)
        if chunk is None or not chunk.intervals:
            return
        base = ci * self.chunk_size
        for s, e in chunk.intervals:
            self._pipeline.submit(chunk.read(s, e - s), base + s)
        chunk.destroy()

    def read(self, offset: int, size: int) -> list[tuple[int, bytes]]:
        """Unflushed dirty ranges overlapping [offset, offset+size):
        [(logical_offset, data)] — overlaid on top of stored chunks for
        read-your-writes. Sealed in-flight uploads come first so live
        (newer) writes win when the caller applies overlays in order."""
        out = self._pipeline.read_at(offset, size)
        with self._lock:
            first = offset // self.chunk_size
            last = (offset + size - 1) // self.chunk_size
            for ci in range(first, last + 1):
                chunk = self._chunks.get(ci)
                if chunk is None:
                    continue
                base = ci * self.chunk_size
                for s, e in chunk.intervals:
                    lo = max(offset, base + s)
                    hi = min(offset + size, base + e)
                    if lo < hi:
                        out.append((lo, chunk.read(lo - base, hi - lo)))
        return out

    def flush(self) -> list[object]:
        """Seal everything, drain the pipeline, return saver results.
        Call commit() once the results are merged into the file entry."""
        with self._lock:
            for ci in sorted(self._chunks):
                self._seal(ci)
        results = self._pipeline.flush()
        self.dirty = False
        return results

    def commit(self) -> None:
        self._pipeline.commit()

    def destroy(self) -> None:
        with self._lock:
            for c in self._chunks.values():
                c.destroy()
            self._chunks.clear()
        self._pipeline.shutdown()
