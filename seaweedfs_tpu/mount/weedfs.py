"""WeedFS: the filesystem facade whose methods map 1:1 to FUSE callbacks.

Reference: weed/mount/weedfs.go (WFS), weedfs_file_write.go:37 (Write ->
dirty pages), weedfs_file_sync.go:92 (doFlush: upload pipeline drain +
CreateEntry/UpdateEntry with the merged chunk list), weedfs_file_read.go
(read via chunk views overlaid with dirty pages), weedfs_dir*.go
(mkdir/readdir/unlink), weedfs_attr.go (getattr/setattr incl truncate),
weedfs_rename.go.

File handles keep per-open state (ChunkedDirtyPages). Reads merge the
stored chunk views with unflushed dirty ranges for read-your-writes.

Op-table coverage vs the reference mount: weedfs_symlink.go,
weedfs_xattr.go, weedfs_link.go, weedfs_attr.go (chmod/chown/utimens)
are all implemented. weedfs_file_copy_range.go and weedfs_file_lseek.go
(copy_file_range, SEEK_HOLE/SEEK_DATA) have NO slots in the libfuse 2.9
ABI this binding targets (fuse_operations ends at fallocate; both are
fuse3 additions), so the kernel transparently falls back to read/write
copies and data-only seeks — correct results, without the offload.
"""

from __future__ import annotations

import os
import stat as stat_mod
import threading
import time

from ..filer.chunks import read_views, total_size
from ..pb import filer_pb2 as fpb
from ..utils.log import logger
from .inode_map import ROOT_INODE, InodeToPath
from .meta_cache import MetaCache
from .page_writer import ChunkedDirtyPages

log = logger("mount.weedfs")


class FuseError(OSError):
    def __init__(self, errno_: int, msg: str = ""):
        super().__init__(errno_, msg or os.strerror(errno_))


class FileHandle:
    def __init__(self, fh: int, path: str, entry: fpb.Entry,
                 dirty: ChunkedDirtyPages):
        self.fh = fh
        self.path = path
        self.entry = entry
        self.dirty = dirty
        self.size = max(entry.attributes.file_size, total_size(entry.chunks))


class WeedFS:
    def __init__(self, filer_server, chunk_size_mb: int = 4,
                 concurrency: int = 8, swap_dir: str | None = None,
                 subscribe_meta: bool = True):
        self.fs = filer_server
        self.chunk_size = chunk_size_mb << 20
        self.concurrency = concurrency
        self.swap_dir = swap_dir
        self.inodes = InodeToPath()
        self.meta = MetaCache(filer_server, subscribe=subscribe_meta)
        self._handles: dict[int, FileHandle] = {}
        self._next_fh = 2
        self._lock = threading.Lock()
        # serializes whole-entry read-modify-writes (flush vs setxattr vs
        # truncate): the loser of an unserialized RMW would overwrite the
        # winner's chunk list or extended map
        self._entry_mu = threading.Lock()
        # mount.configure quota (reference mount_pb ConfigureRequest
        # CollectionCapacity): 0 = unlimited; reported via statfs
        self.collection_capacity = 0

    # -- helpers -------------------------------------------------------------
    @staticmethod
    def _split(path: str) -> tuple[str, str]:
        from ..filer.filer import split_path
        return split_path(path)

    def _entry(self, path: str) -> fpb.Entry:
        if path == "/":
            e = fpb.Entry(name="/", is_directory=True)
            e.attributes.file_mode = 0o755
            return e
        d, n = self._split(path)
        entry = self.meta.find(d, n)
        if entry is None:
            raise FuseError(2, path)  # ENOENT
        return entry

    def _attr(self, path: str, entry: fpb.Entry) -> dict:
        a = entry.attributes
        mode = a.file_mode & 0o7777
        if entry.is_directory:
            mode |= stat_mod.S_IFDIR
        elif a.symlink_target:
            mode |= stat_mod.S_IFLNK
        else:
            mode |= stat_mod.S_IFREG
        if a.symlink_target:
            size = len(a.symlink_target)
        else:
            size = (0 if entry.is_directory
                    else max(a.file_size, total_size(entry.chunks)))
        if entry.hard_link_id:
            # all names of a hardlink set share one inode number
            # (weedfs_link.go:17 "use the hardlink id as inode") so
            # os.path.samefile and `find -samefile` work across names
            ino = int.from_bytes(bytes(entry.hard_link_id)[:8], "big") or 1
        else:
            ino = self.inodes.lookup(path)
        return {"st_ino": ino, "st_mode": mode,
                "st_size": size, "st_mtime": a.mtime or 0,
                "st_ctime": a.crtime or a.mtime or 0,
                "st_uid": a.uid, "st_gid": a.gid,
                "st_nlink": max(1, entry.hard_link_counter)}

    # -- FUSE ops ------------------------------------------------------------
    def lookup(self, parent_path: str, name: str) -> dict:
        path = parent_path.rstrip("/") + "/" + name
        return self.getattr(path)

    def getattr(self, path: str) -> dict:
        return self._attr(path, self._entry(path))

    def readdir(self, path: str) -> list[str]:
        entry = self._entry(path)
        if not entry.is_directory:
            raise FuseError(20, path)  # ENOTDIR
        return [e.name for e in self.meta.list(path)]

    def mkdir(self, path: str, mode: int = 0o755) -> dict:
        d, n = self._split(path)
        if self.meta.find(d, n) is not None:
            raise FuseError(17, path)  # EEXIST
        e = fpb.Entry(name=n, is_directory=True)
        e.attributes.file_mode = mode
        e.attributes.mtime = e.attributes.crtime = int(time.time())
        self.fs.filer.create_entry(d, e)
        self.meta.invalidate(d, n)
        return self.getattr(path)

    def rmdir(self, path: str) -> None:
        entry = self._entry(path)
        if not entry.is_directory:
            raise FuseError(20, path)
        if next(iter(self.fs.filer.list_entries(path, limit=1)), None):
            raise FuseError(39, path)  # ENOTEMPTY
        d, n = self._split(path)
        self.fs.filer.delete_entry(d, n, is_recursive=False)
        self.meta.invalidate(d, n)
        self.inodes.remove_path(path)

    def unlink(self, path: str) -> None:
        d, n = self._split(path)
        if self.meta.find(d, n) is None:
            raise FuseError(2, path)
        self.fs.filer.delete_entry(d, n, is_delete_data=True)
        self.meta.invalidate(d, n)
        self.inodes.remove_path(path)

    def rename(self, old: str, new: str) -> None:
        od, on = self._split(old)
        nd, nn = self._split(new)
        if self.meta.find(nd, nn) is not None:
            self.fs.filer.delete_entry(nd, nn, is_recursive=True,
                                       is_delete_data=True)
            self.meta.invalidate(nd, nn)
        self.fs.filer.rename(od, on, nd, nn)
        self.meta.invalidate(od, on)
        self.meta.invalidate(nd, nn)
        self.inodes.move_path(old, new)

    # -- setattr family (reference weedfs_attr.go: chmod/chown/utimens
    # persist through the filer like any metadata change) -------------------
    def _update_entry_meta(self, path: str, mutate) -> None:
        """Shared metadata-only read-modify-write (setattr + xattr): one
        lock, one gc-free mtime-preserving update, one invalidation."""
        d, n = self._split(path)
        with self._entry_mu:
            entry = self.fs.filer.find_entry(d, n)
            if entry is None:
                raise FuseError(2, path)
            updated = fpb.Entry()
            updated.CopyFrom(entry)
            mutate(updated)
            self.fs.filer.update_entry(d, updated, gc_chunks=False,
                                       touch_mtime=False)
        self.meta.invalidate(d, n)

    _setattr = _update_entry_meta

    def chmod(self, path: str, mode: int) -> None:
        def mutate(e: fpb.Entry) -> None:
            e.attributes.file_mode = (e.attributes.file_mode & ~0o7777) | \
                (mode & 0o7777)
        self._setattr(path, mutate)

    def chown(self, path: str, uid: int, gid: int) -> None:
        def mutate(e: fpb.Entry) -> None:
            # -1 means "leave unchanged" (chown(2) semantics); the FUSE
            # layer passes 0xFFFFFFFF for it
            if uid not in (0xFFFFFFFF, -1):
                e.attributes.uid = uid
            if gid not in (0xFFFFFFFF, -1):
                e.attributes.gid = gid
        self._setattr(path, mutate)

    def utimens(self, path: str, atime: float | None,
                mtime: float | None) -> None:
        def mutate(e: fpb.Entry) -> None:
            if mtime is not None:
                e.attributes.mtime = int(mtime)
        self._setattr(path, mutate)

    # -- symlinks (reference weedfs_symlink.go) ------------------------------
    def symlink(self, target: str, path: str) -> dict:
        """`ln -s target path`: a zero-chunk entry whose attributes carry
        the target (weedfs_symlink.go:33 stores SymlinkTarget the same
        way)."""
        d, n = self._split(path)
        if self.meta.find(d, n) is not None:
            raise FuseError(17, path)  # EEXIST
        e = fpb.Entry(name=n)
        e.attributes.file_mode = 0o777
        e.attributes.symlink_target = target
        e.attributes.mtime = e.attributes.crtime = int(time.time())
        self.fs.filer.create_entry(d, e)
        self.meta.invalidate(d, n)
        return self.getattr(path)

    def readlink(self, path: str) -> str:
        entry = self._entry(path)
        if not entry.attributes.symlink_target:
            raise FuseError(22, path)  # EINVAL — not a symlink
        return entry.attributes.symlink_target

    # -- hardlinks (reference weedfs_link.go; shared record in the filer) ----
    def link(self, old: str, new: str) -> dict:
        od, on = self._split(old)
        nd, nn = self._split(new)
        if self.meta.find(nd, nn) is not None:
            raise FuseError(17, new)
        src = self.meta.find(od, on)
        if src is None:
            raise FuseError(2, old)
        if src.is_directory:
            raise FuseError(31, old)  # EMLINK — no dir hardlinks
        try:
            self.fs.filer.link(od, on, nd, nn)
        except FileNotFoundError:
            raise FuseError(2, old) from None
        except FileExistsError:
            raise FuseError(17, new) from None
        except IsADirectoryError:
            raise FuseError(31, old) from None
        self.meta.invalidate(od, on)
        self.meta.invalidate(nd, nn)
        return self.getattr(new)

    # -- extended attributes (reference weedfs_xattr.go; stored in
    # Entry.extended under the same "xattr-" key prefix the filer uses) ------
    XATTR_PREFIX = "xattr-"
    MAX_XATTR_NAME = 255
    MAX_XATTR_VALUE = 65536

    # POSIX: xattr changes touch ctime only, never mtime — which is what
    # the shared metadata-only RMW already guarantees
    _xattr_update = _update_entry_meta

    def setxattr(self, path: str, name: str, value: bytes,
                 flags: int = 0) -> None:
        if not name or len(name) > self.MAX_XATTR_NAME:
            raise FuseError(22 if not name else 34)  # EINVAL / ERANGE
        if len(value) > self.MAX_XATTR_VALUE:
            raise FuseError(7)  # E2BIG
        key = self.XATTR_PREFIX + name

        def mutate(e: fpb.Entry) -> None:
            if flags & 1 and key in e.extended:  # XATTR_CREATE
                raise FuseError(17, name)
            if flags & 2 and key not in e.extended:  # XATTR_REPLACE
                raise FuseError(61, name)  # ENODATA/ENOATTR
            e.extended[key] = value

        self._xattr_update(path, mutate)

    def getxattr(self, path: str, name: str) -> bytes:
        entry = self._entry(path)
        key = self.XATTR_PREFIX + name
        if key not in entry.extended:
            raise FuseError(61, name)  # ENODATA/ENOATTR
        return bytes(entry.extended[key])

    def listxattr(self, path: str) -> list[str]:
        entry = self._entry(path)
        return sorted(k[len(self.XATTR_PREFIX):] for k in entry.extended
                      if k.startswith(self.XATTR_PREFIX))

    def removexattr(self, path: str, name: str) -> None:
        key = self.XATTR_PREFIX + name

        def mutate(e: fpb.Entry) -> None:
            if key not in e.extended:
                raise FuseError(61, name)
            del e.extended[key]

        self._xattr_update(path, mutate)

    # -- open files ----------------------------------------------------------
    def create(self, path: str, mode: int = 0o644) -> int:
        d, n = self._split(path)
        if self.meta.find(d, n) is not None:
            raise FuseError(17, path)
        e = fpb.Entry(name=n)
        e.attributes.file_mode = mode
        e.attributes.mtime = e.attributes.crtime = int(time.time())
        self.fs.filer.create_entry(d, e)
        self.meta.invalidate(d, n)
        return self.open(path)

    def open(self, path: str) -> int:
        entry = self._entry(path)
        if entry.is_directory:
            raise FuseError(21, path)  # EISDIR
        dirty = ChunkedDirtyPages(
            self.chunk_size, self._make_saver(), self.concurrency,
            swap_dir=self.swap_dir)
        with self._lock:
            fh = self._next_fh
            self._next_fh += 1
            self._handles[fh] = FileHandle(fh, path, entry, dirty)
        return fh

    def _handle(self, fh: int) -> FileHandle:
        h = self._handles.get(fh)
        if h is None:
            raise FuseError(9, f"fh {fh}")  # EBADF
        return h

    def _make_saver(self):
        def saver(data: bytes, logical_offset: int) -> fpb.FileChunk:
            chunk = self.fs._save_blob(data)
            chunk.offset = logical_offset
            return chunk
        return saver

    def write(self, fh: int, offset: int, data: bytes) -> int:
        h = self._handle(fh)
        h.dirty.write(offset, data)
        h.size = max(h.size, offset + len(data))
        return len(data)

    def read(self, fh: int, offset: int, size: int) -> bytes:
        h = self._handle(fh)
        size = max(0, min(size, h.size - offset))
        if size == 0:
            return b""
        buf = bytearray(size)
        stored = self.fs.read_entry_bytes(h.entry, offset, size)
        buf[:len(stored)] = stored
        # overlay unflushed dirty ranges (read-your-writes)
        for lo, data in h.dirty.read(offset, size):
            at = lo - offset
            buf[at:at + len(data)] = data
        return bytes(buf)

    def flush(self, fh: int) -> None:
        """doFlush (weedfs_file_sync.go:92): drain the pipeline, merge
        new chunks into the entry, update the filer."""
        h = self._handle(fh)
        if not h.dirty.dirty:
            return
        new_chunks = h.dirty.flush()  # uploads happen OUTSIDE the mutex
        d, n = self._split(h.path)
        with self._entry_mu:
            entry = self.fs.filer.find_entry(d, n) or h.entry
            updated = fpb.Entry()
            updated.CopyFrom(entry)
            updated.chunks.extend(new_chunks)
            updated.attributes.file_size = max(
                h.size, total_size(updated.chunks))
            updated.attributes.mtime = int(time.time())
            self.fs.filer.update_entry(d, updated)
        h.entry = updated
        h.dirty.commit()  # entry now holds the chunks; drop overlay copies
        self.meta.invalidate(d, n)

    fsync = flush

    def release(self, fh: int) -> None:
        h = self._handles.get(fh)
        if h is None:
            return
        try:
            self.flush(fh)
        finally:
            h.dirty.destroy()
            with self._lock:
                self._handles.pop(fh, None)

    def truncate(self, path: str, length: int) -> None:
        """setattr(size) — weedfs_attr.go truncates the chunk list."""
        # flush open handles first so no unflushed dirty interval beyond
        # `length` can resurrect the truncated bytes at the next flush
        for h in list(self._handles.values()):
            if h.path == path and h.dirty.dirty:
                self.flush(h.fh)
        d, n = self._split(path)
        with self._entry_mu:
            entry = self.fs.filer.find_entry(d, n)
            if entry is None:
                raise FuseError(2, path)
            kept = [c for c in entry.chunks if c.offset < length]
            updated = fpb.Entry()
            updated.CopyFrom(entry)
            del updated.chunks[:]
            for c in kept:
                nc = updated.chunks.add()
                nc.CopyFrom(c)
                if nc.offset + nc.size > length:
                    nc.size = length - nc.offset
            updated.attributes.file_size = length
            self.fs.filer.update_entry(d, updated)
        self.meta.invalidate(d, n)
        for h in self._handles.values():
            if h.path == path:
                h.size = length
                h.entry = updated

    def configure(self, collection_capacity: int) -> None:
        """mount.configure RPC body (reference weedfs_grpc_server.go
        Configure): adjust the quota on a live mount."""
        self.collection_capacity = max(0, int(collection_capacity))
        self._usage_cached_at = 0.0  # force re-measure on next statfs

    _usage_cached_at = 0.0
    _usage_cached = 0
    USAGE_TTL_S = 5.0  # statfs is a kernel hot path; don't walk per call

    def statfs(self) -> dict:
        if self.collection_capacity:
            import time as _time
            bsize = self.chunk_size
            blocks = max(1, self.collection_capacity // bsize)
            now = _time.monotonic()
            if now - self._usage_cached_at > self.USAGE_TTL_S:
                try:
                    self._usage_cached = sum(
                        (e.attributes.file_size or 0)
                        for _, e in self._walk_all("/"))
                    self._usage_cached_at = now
                except Exception as e:  # noqa: BLE001 — quota display best-effort
                    log.debug("statfs usage scan failed: %s", e)
            free = max(0, blocks - self._usage_cached // bsize)
            return {"f_bsize": bsize, "f_blocks": blocks,
                    "f_bfree": free, "f_bavail": free,
                    "f_files": 1 << 20, "f_ffree": 1 << 20}
        return {"f_bsize": self.chunk_size, "f_blocks": 1 << 30,
                "f_bfree": 1 << 30, "f_bavail": 1 << 30,
                "f_files": 1 << 20, "f_ffree": 1 << 20}

    def _walk_all(self, directory: str):
        for e in self.meta.list(directory):
            path = (directory.rstrip("/") + "/" + e.name)
            yield path, e
            if e.is_directory:
                yield from self._walk_all(path)

    def forget(self, inode: int, nlookup: int = 1) -> None:
        self.inodes.forget(inode, nlookup)

    def destroy(self) -> None:
        for fh in list(self._handles):
            try:
                self.release(fh)
            except Exception as e:  # noqa: BLE001
                log.debug("handle %s release at unmount failed: %s", fh, e)
        self.meta.close()


def mount(weedfs: WeedFS, mountpoint: str):  # pragma: no cover - needs fusepy
    """Kernel mount via fusepy when available (the image has no fusepy;
    the reference uses go-fuse, weedfs.go). Raises RuntimeError otherwise."""
    try:
        import fuse  # type: ignore[import-not-found]
    except ImportError as e:
        raise RuntimeError(
            "fusepy not installed; WeedFS is only drivable in-process") from e

    class _Ops(fuse.Operations):  # type: ignore[misc]
        def getattr(self, path, fh=None):
            return weedfs.getattr(path)

        def readdir(self, path, fh):
            return [".", ".."] + weedfs.readdir(path)

        def mkdir(self, path, mode):
            weedfs.mkdir(path, mode)

        def rmdir(self, path):
            weedfs.rmdir(path)

        def unlink(self, path):
            weedfs.unlink(path)

        def rename(self, old, new):
            weedfs.rename(old, new)

        def create(self, path, mode, fi=None):
            return weedfs.create(path, mode)

        def open(self, path, flags):
            return weedfs.open(path)

        def read(self, path, size, offset, fh):
            return weedfs.read(fh, offset, size)

        def write(self, path, data, offset, fh):
            return weedfs.write(fh, offset, data)

        def flush(self, path, fh):
            weedfs.flush(fh)

        def release(self, path, fh):
            weedfs.release(fh)

        def truncate(self, path, length, fh=None):
            weedfs.truncate(path, length)

        def statfs(self, path):
            return weedfs.statfs()

        def symlink(self, target, source):
            weedfs.symlink(source, target)  # fusepy arg order

        def readlink(self, path):
            return weedfs.readlink(path)

        def link(self, target, source):
            weedfs.link(source, target)

        def chmod(self, path, mode):
            weedfs.chmod(path, mode)

        def chown(self, path, uid, gid):
            weedfs.chown(path, uid, gid)

        def utimens(self, path, times=None):
            if times:
                weedfs.utimens(path, times[0], times[1])

        def setxattr(self, path, name, value, options, position=0):
            weedfs.setxattr(path, name, value, options)

        def getxattr(self, path, name, position=0):
            return weedfs.getxattr(path, name)

        def listxattr(self, path):
            return weedfs.listxattr(path)

        def removexattr(self, path, name):
            weedfs.removexattr(path, name)

    return fuse.FUSE(_Ops(), mountpoint, foreground=True)
