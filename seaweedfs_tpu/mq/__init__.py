"""Message queue (reference weed/mq, 6,379 LoC — SURVEY.md §2.7).

Topics split into partitions over a 4096-slot ring (mq/topic/
partition.go); brokers register in the master cluster and own partition
ranges (pub_balancer/balancer.go); pub/sub are gRPC streams with acked
offsets (broker/broker_grpc_pub.go, _sub.go); closed segments persist
through the filer under /topics/<ns>/<topic>/.
"""

from .topic import Partition, TopicRef, partition_for_key, split_ring
from .broker import BrokerServer

__all__ = ["TopicRef", "Partition", "partition_for_key", "split_ring",
           "BrokerServer"]
