"""Message queue (reference weed/mq, 6,379 LoC — SURVEY.md §2.7).

Topics split into partitions over a 4096-slot ring (mq/topic/
partition.go); brokers register in the master cluster and own partition
ranges (pub_balancer/balancer.go); pub/sub are gRPC streams with acked
offsets (broker/broker_grpc_pub.go, _sub.go); closed segments persist
through the filer under /topics/<ns>/<topic>/. Consumer groups
coordinate through the broker-side sub coordinator
(mq/sub_coordinator/) with sticky rebalancing and filer-persisted
committed offsets; structured records are typed by mq/schema
(mq/schema/ in the reference) with columnar-numpy batch mapping.
"""

from .broker import BrokerServer
from .consumer import ConsumerRecord, GroupConsumer, group_consume
from .schema import Schema, infer_record_type, record_type_begin
from .topic import Partition, TopicRef, partition_for_key, split_ring

__all__ = ["TopicRef", "Partition", "partition_for_key", "split_ring",
           "BrokerServer", "GroupConsumer", "ConsumerRecord",
           "group_consume", "Schema", "infer_record_type",
           "record_type_begin"]
