"""MQ broker daemon (reference weed/mq/broker).

gRPC service `swtpu.mq.Broker`: ConfigureTopic / LookupTopicBrokers /
ListTopics / Publish (stream) / Subscribe (stream). Partition logs are
in-memory lists with length-prefixed segment flushes into the filer at
/topics/<ns>/<topic>/<range>/seg-<n> (reference persists segments via
the filer the same way, broker_server.go) — a broker restart replays
persisted segments. Multiple brokers register in the master cluster
(client_type "broker", reference cluster.go:104); partition ownership is
deterministic over the sorted live-broker list so every broker answers
lookups identically (pub_balancer/balancer.go re-designed without the
coordinator: ownership = hash-ordered assignment).
"""

from __future__ import annotations

import os
import struct
import threading
import time

import hashlib

from ..client.master_client import MasterClient
from ..pb import mq_pb2 as mq
from ..utils import fsutil
from ..utils.log import logger
from ..utils.rpc import MASTER_SERVICE, RpcService, Stub, serve
from .sub_coordinator import Coordinator
from .topic import Partition, TopicRef, split_ring

log = logger("mq.broker")

MQ_SERVICE = "swtpu.mq.Broker"
SEGMENT_FLUSH_COUNT = 1000  # messages per persisted segment


def _stable_hash(s: str) -> int:
    """Deterministic across processes (unlike hash()) so every broker
    ranks the same owner for a partition or group. blake2b, not md5:
    md5 raises on FIPS-enforcing builds (usedforsecurity defaults True)
    and this is placement hashing, not cryptography."""
    return int.from_bytes(
        hashlib.blake2b(s.encode(), digest_size=8).digest(), "big")


class PartitionLog:
    """One partition's message log: bounded in-memory tail + filer segments.

    Only the un-sealed tail (< SEGMENT_FLUSH_COUNT messages) lives in
    memory; sealed segments are dropped after persisting and reads of old
    offsets come back from the filer. The partial tail is re-written by
    `flush_tail` (periodic + on broker stop) so a restart loses at most
    the last flush interval, not 999 acked messages. Without a filer the
    log is memory-only and unbounded (standalone dev mode)."""

    def __init__(self, topic: TopicRef, partition: Partition, filer=None):
        self.topic = topic
        self.partition = partition
        self.filer = filer
        self.messages: list[tuple[bytes, bytes, int]] = []  # un-sealed tail
        self.base_offset = 0  # offset of messages[0] == sealed message count
        self._full_segments = 0
        self._seg_cache: tuple[int, list] | None = None  # last parsed seg
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # serializes segment WRITES so the out-of-lock periodic tail flush
        # can never clobber a just-sealed full segment with a stale partial
        self._io_mu = threading.Lock()
        self._max_sealed = -1  # highest segment index written as full
        self._last_tail_flush = (-1, -1)  # (segment idx, length) persisted
        if filer is not None:
            self._replay()

    # -- persistence ---------------------------------------------------------
    @property
    def _dir(self) -> str:
        return (f"/topics/{self.topic.namespace}/{self.topic.name}/"
                f"{self.partition.range_start:04d}-"
                f"{self.partition.range_stop:04d}")

    def _segment_path(self, n: int) -> str:
        return f"{self._dir}/seg-{n:06d}"

    @staticmethod
    def _parse_records(data: bytes) -> list[tuple[bytes, bytes, int]]:
        out = []
        pos = 0
        while pos + 4 <= len(data):
            (ln,) = struct.unpack_from("<I", data, pos)
            pos += 4
            rec = data[pos:pos + ln]
            pos += ln
            klen = struct.unpack_from("<I", rec, 0)[0]
            key = rec[4:4 + klen]
            ts = struct.unpack_from("<q", rec, 4 + klen)[0]
            value = rec[12 + klen:]
            out.append((key, value, ts))
        return out

    def _read_segment(self, n: int) -> list[tuple[bytes, bytes, int]]:
        from ..filer.filer import split_path
        d, name = split_path(self._segment_path(n))
        entry = self.filer.filer.find_entry(d, name)
        if entry is None:
            return []
        return self._parse_records(self.filer.read_entry_bytes(entry))

    def _replay(self) -> None:
        """Restore offsets on broker restart: count sealed segments by
        existence (no payload fetch), parse only the trailing segment and
        keep it in memory as the tail if partial."""
        from ..filer.filer import split_path
        n = 0
        while True:
            d, name = split_path(self._segment_path(n))
            if self.filer.filer.find_entry(d, name) is None:
                break
            n += 1
        tail: list[tuple[bytes, bytes, int]] = (
            self._read_segment(n - 1) if n else [])
        if n and len(tail) < SEGMENT_FLUSH_COUNT:
            self._full_segments = n - 1
            self.messages = tail
        else:
            self._full_segments = n
            self.messages = []
        self.base_offset = self._full_segments * SEGMENT_FLUSH_COUNT
        self._max_sealed = self._full_segments - 1
        if n:
            log.info("%s %s: replayed %d segments (next offset %d)",
                     self.topic, self.partition, n,
                     self.base_offset + len(self.messages))

    def _write_segment(self, n: int,
                       batch: list[tuple[bytes, bytes, int]]) -> None:
        blob = bytearray()
        for key, value, ts in batch:
            rec = (struct.pack("<I", len(key)) + key
                   + struct.pack("<q", ts) + value)
            blob += struct.pack("<I", len(rec)) + rec
        self.filer.write_file(self._segment_path(n), bytes(blob),
                              mime="application/octet-stream")

    def flush_tail(self) -> None:
        """Persist the partial tail segment (re-written in place as it
        grows; sealed for good once full). The filer write runs OUTSIDE
        the partition lock so the periodic flusher doesn't stall appends
        and in-memory reads for a whole upload."""
        if self.filer is None:
            return
        with self._io_mu:
            with self._lock:
                n, batch = self._full_segments, list(self.messages)
            # never write MORE than a segment's worth: a crash would make
            # _replay mis-count the oversized file as exactly one sealed
            # segment, orphaning the excess and reusing their offsets
            batch = batch[:SEGMENT_FLUSH_COUNT]
            if not batch or n <= self._max_sealed:
                return  # nothing new, or that index already sealed full
            if self._last_tail_flush == (n, len(batch)):
                return  # idle partition: skip the redundant re-upload
            self._write_segment(n, batch)
            self._last_tail_flush = (n, len(batch))

    def _seal_full_segments(self) -> None:
        """Persist full segments; memory is trimmed only AFTER each file
        write so readers never hit a window where a sealed offset is
        neither in memory nor on the filer."""
        with self._io_mu:
            while True:
                with self._lock:
                    if len(self.messages) < SEGMENT_FLUSH_COUNT:
                        return
                    n = self._full_segments
                    batch = self.messages[:SEGMENT_FLUSH_COUNT]
                self._write_segment(n, batch)
                with self._lock:
                    self._full_segments = n + 1
                    self.messages = self.messages[SEGMENT_FLUSH_COUNT:]
                    self.base_offset += SEGMENT_FLUSH_COUNT
                self._max_sealed = max(self._max_sealed, n)

    # -- log ops -------------------------------------------------------------
    def append(self, key: bytes, value: bytes, ts_ns: int) -> int:
        with self._lock:
            self.messages.append((key, value, ts_ns))
            offset = self.base_offset + len(self.messages) - 1
            need_seal = (self.filer is not None
                         and len(self.messages) >= SEGMENT_FLUSH_COUNT)
            self._cv.notify_all()
        if need_seal:
            self._seal_full_segments()
        return offset

    @property
    def next_offset(self) -> int:
        with self._lock:
            return self.base_offset + len(self.messages)

    def read(self, offset: int, max_count: int = 256
             ) -> list[tuple[int, bytes, bytes, int]]:
        with self._lock:
            if offset >= self.base_offset:
                start = offset - self.base_offset
                return [(self.base_offset + start + i, k, v, ts)
                        for i, (k, v, ts) in enumerate(
                            self.messages[start:start + max_count])]
            filer = self.filer
        if filer is None:
            return []
        # old offset: serve from the sealed segment that contains it,
        # keeping the last-parsed segment around — a replaying subscriber
        # reads each 1000-record segment in ~4 ×256 batches
        seg = offset // SEGMENT_FLUSH_COUNT
        base = seg * SEGMENT_FLUSH_COUNT
        cached = self._seg_cache
        if cached is None or cached[0] != seg:
            cached = (seg, self._read_segment(seg))
            self._seg_cache = cached
        records = cached[1]
        lo = offset - base
        return [(base + lo + i, k, v, ts)
                for i, (k, v, ts) in enumerate(
                    records[lo:lo + max_count])]

    def wait_for(self, offset: int, timeout: float) -> bool:
        with self._cv:
            if self.base_offset + len(self.messages) > offset:
                return True
            self._cv.wait(timeout)
            return self.base_offset + len(self.messages) > offset


class LocalSegmentStore:
    """Duck-typed stand-in for an in-process FilerServer: persists broker
    segments to a local directory so the STANDALONE `mq.broker` verb is
    durable too (r2 weak #5 — previously memory-only and unbounded).
    Exposes exactly the three calls PartitionLog uses: .filer.find_entry,
    .read_entry_bytes, .write_file."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self.filer = self  # PartitionLog dials `filer.filer.find_entry`

    def _path(self, directory: str, name: str = "") -> str:
        return os.path.join(self.root, directory.lstrip("/"), name)

    def find_entry(self, directory: str, name: str):
        p = self._path(directory, name)
        return p if os.path.exists(p) else None

    def read_entry_bytes(self, entry: str) -> bytes:
        with open(entry, "rb") as f:
            return f.read()

    def write_file(self, path: str, data: bytes, **_kw) -> None:
        from ..filer.filer import split_path
        d, name = split_path(path)
        os.makedirs(self._path(d), exist_ok=True)
        tmp = self._path(d, name) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._path(d, name))
        # the flush path acks the batch once this entry lands: pin the
        # rename so a crash can't un-publish acked messages
        fsutil.fsync_dir(self._path(d, name))


class BrokerServer:
    def __init__(self, master_address: str, ip: str = "127.0.0.1",
                 port: int = 17777, filer_server=None,
                 data_dir: str | None = None,
                 rebalance_delay_s: float | None = None):
        self.ip, self.port = ip, port
        # segment persistence: an in-process filer, or a local directory
        # for the standalone verb, or memory-only (tests)
        if filer_server is None and data_dir:
            filer_server = LocalSegmentStore(data_dir)
        self.filer = filer_server  # optional persistence
        self.mc = MasterClient(master_address, client_type="broker",
                               client_address=f"{ip}:{port}")
        self.topics: dict[str, list[Partition]] = {}
        # configure-time leader assignment: topic -> {range_start: broker}
        self.topic_leaders: dict[str, dict[int, str]] = {}
        # topic -> serialized RecordType (mq_schema.proto); b"" = schemaless
        self.topic_schemas: dict[str, bytes] = {}
        self.logs: dict[tuple[str, int], PartitionLog] = {}
        self._lock = threading.Lock()
        self._grpc = None
        self._stop = threading.Event()
        self.flush_interval = 2.0  # partial-tail persistence cadence (s)
        # consumer-group coordination (sub_coordinator.py); leadership and
        # coordinator placement both hash over the live-broker ring below
        self.coordinator = Coordinator(self._group_partitions,
                                       rebalance_delay_s)
        self._broker_cache: tuple[float, list[str]] = (0.0, [self.address])
        self._last_membership: list[str] = [self.address]
        self.membership_poll_s = 0.5
        # committed offsets: (topic_name, range_start, group) -> offset;
        # memory cache over the filer-persisted offset files
        self._offsets: dict[tuple[str, int, str], int] = {}

    @property
    def address(self) -> str:
        return f"{self.ip}:{self.port}"

    def start(self) -> "BrokerServer":
        self.mc.start()
        self._grpc = serve(f"{self.ip}:{self.port}", [self._build_service()])
        if self.filer is not None:
            threading.Thread(target=self._flusher, daemon=True,
                             name=f"mq-flush-{self.port}").start()
        threading.Thread(target=self._membership_watch, daemon=True,
                         name=f"mq-members-{self.port}").start()
        log.info("mq broker %s up", self.address)
        return self

    def stop(self) -> None:
        self._stop.set()
        self.coordinator.shutdown()
        # stop accepting publishes BEFORE the final flush — an append acked
        # after its partition's flush would be lost despite a clean stop
        if self._grpc:
            self._grpc.stop(grace=0.5).wait()
        for lg in list(self.logs.values()):
            try:
                lg.flush_tail()
            except Exception as e:  # noqa: BLE001
                log.warning("flush tail of %s %s: %s",
                            lg.topic, lg.partition, e)
        self.mc.stop()

    def kill(self) -> None:
        """Abrupt death for failover tests: drop the gRPC plane and the
        master registration WITHOUT the final tail flush a clean stop()
        performs — acked-but-unflushed tails are lost, like a crash."""
        self._stop.set()
        self.coordinator.shutdown()
        if self._grpc:
            self._grpc.stop(grace=0).wait()
        self.mc.stop()

    # -- live-broker ring ----------------------------------------------------
    def live_brokers(self) -> list[str]:
        """Sorted live broker addresses from the master cluster list
        (cluster.go:104 membership), ~0.5 s cached; always includes self
        so a broker is usable before/without master registration."""
        now = time.monotonic()
        ts, cached = self._broker_cache
        if now - ts < 0.5:
            return cached
        addrs = {self.address}
        try:
            from ..pb import master_pb2 as mpb
            resp = Stub(self.mc.leader, MASTER_SERVICE).call(
                "ListClusterNodes",
                mpb.ListClusterNodesRequest(client_type="broker"),
                mpb.ListClusterNodesResponse, timeout=2)
            addrs.update(n.address for n in resp.cluster_nodes)
        except Exception:  # noqa: BLE001  # swtpu-lint: disable=silent-except (masterless dev mode: self only)
            pass
        out = sorted(addrs)
        self._broker_cache = (now, out)
        return out

    def leader_for(self, topic_name: str, partition: Partition) -> str:
        """Partition→broker ownership: the assignment RECORDED at
        configure time (reference pub_balancer/allocate.go picks brokers
        and the assignment sticks) as long as that broker is alive;
        otherwise a deterministic hash over the live ring, so a broker
        death re-homes exactly its partitions and every broker answers
        lookups identically."""
        brokers = self.live_brokers()
        assigned = self.topic_leaders.get(topic_name, {}).get(
            partition.range_start)
        if assigned in brokers:
            return assigned
        h = _stable_hash(f"{topic_name}:{partition.range_start}")
        return brokers[h % len(brokers)]

    def coordinator_for(self, topic_name: str, group: str) -> str:
        brokers = self.live_brokers()
        h = _stable_hash(f"{topic_name}/{group}")
        return brokers[h % len(brokers)]

    def _group_partitions(self, topic_name: str
                          ) -> list[tuple[Partition, str]]:
        """Coordinator callback: the topic's partitions with their CURRENT
        leaders (fed into every rebalance)."""
        ns, _, name = topic_name.partition(".")
        parts = self._topic_partitions(TopicRef(ns, name)) or []
        return [(p, self.leader_for(topic_name, p)) for p in parts]

    def _membership_watch(self) -> None:
        """React to broker join/death: when the live ring changes, every
        consumer group rebalances onto the new partition leadership
        (reference OnPartitionChange / OnSubRemoveBroker)."""
        while not self._stop.wait(self.membership_poll_s):
            self._broker_cache = (0.0, self._broker_cache[1])  # force renew
            live = self.live_brokers()
            if live == self._last_membership:
                continue
            log.info("broker membership %s -> %s", self._last_membership,
                     live)
            self._last_membership = live
            for t in self.coordinator.topic_names():
                self.coordinator.on_partition_change(t)

    # -- committed offsets ---------------------------------------------------
    def _offset_path(self, topic_name: str, p: Partition, group: str) -> str:
        ns, _, name = topic_name.partition(".")
        return (f"/topics/{ns}/{name}/{p.range_start:04d}-"
                f"{p.range_stop:04d}/offset.{group}")

    def commit_offset(self, topic_name: str, p: Partition, group: str,
                      offset: int) -> None:
        self._offsets[(topic_name, p.range_start, group)] = offset
        if self.filer is not None:
            self.filer.write_file(self._offset_path(topic_name, p, group),
                                  struct.pack("<q", offset),
                                  mime="application/octet-stream")

    def fetch_offset(self, topic_name: str, p: Partition, group: str) -> int:
        """Highest committed offset, -1 if the group never committed.
        Reads through to the filer so a freshly failed-over broker sees
        commits made via its dead peer."""
        if self.filer is not None:
            from ..filer.filer import split_path
            d, n = split_path(self._offset_path(topic_name, p, group))
            entry = self.filer.filer.find_entry(d, n)
            if entry is not None:
                data = self.filer.read_entry_bytes(entry)
                if len(data) >= 8:
                    off = struct.unpack("<q", data[:8])[0]
                    self._offsets[(topic_name, p.range_start, group)] = off
                    return off
            return self._offsets.get((topic_name, p.range_start, group), -1)
        return self._offsets.get((topic_name, p.range_start, group), -1)

    def _flusher(self) -> None:
        while not self._stop.wait(self.flush_interval):
            for lg in list(self.logs.values()):
                try:
                    lg.flush_tail()
                except Exception as e:  # noqa: BLE001
                    log.warning("periodic flush of %s %s: %s",
                                lg.topic, lg.partition, e)

    # -- topic/partition state ----------------------------------------------
    def _log_for(self, tref: TopicRef, partition: Partition) -> PartitionLog:
        key = (str(tref), partition.range_start)
        with self._lock:
            lg = self.logs.get(key)
            if lg is None:
                lg = PartitionLog(tref, partition, self.filer)
                self.logs[key] = lg
            return lg

    def configure_topic(self, tref: TopicRef, partition_count: int,
                        record_type: bytes = b"") -> list[Partition]:
        """Create (or re-read) a topic. First configuration assigns each
        partition a leader round-robin over the live ring STARTING at
        this broker (reference pub_balancer allocates to brokers and the
        assignment sticks in the topic conf); reconfiguring an existing
        topic with the same count keeps its assignment. `record_type` is
        the serialized schema (mq_schema.proto RecordType) persisted with
        the topic conf — reference ConfigureTopicRequest.record_type."""
        tname = str(tref)
        existing = self._topic_partitions(tref)
        if existing is not None and len(existing) == max(1, partition_count):
            if record_type and self.topic_schemas.get(tname) != record_type:
                with self._lock:
                    self.topic_schemas[tname] = record_type
                self._persist_topic_conf(tref)
            return existing
        parts = split_ring(max(1, partition_count))
        ring = self.live_brokers()
        start = ring.index(self.address) if self.address in ring else 0
        leaders = {p.range_start: ring[(start + i) % len(ring)]
                   for i, p in enumerate(parts)}
        with self._lock:
            self.topics[tname] = parts
            self.topic_leaders[tname] = leaders
            if record_type:
                self.topic_schemas[tname] = record_type
        self._persist_topic_conf(tref)
        return parts

    def _topic_schema(self, tref: TopicRef) -> bytes:
        """Read-through schema lookup: a broker that cached the topic
        BEFORE another broker registered a schema must still see it (the
        conf lives in the shared filer)."""
        tname = str(tref)
        schema = self.topic_schemas.get(tname, b"")
        if not schema and self.filer is not None:
            import base64
            import json

            from ..filer.filer import split_path
            d, n = split_path(
                f"/topics/{tref.namespace}/{tref.name}/topic.conf")
            entry = self.filer.filer.find_entry(d, n)
            if entry is not None:
                conf = json.loads(self.filer.read_entry_bytes(entry))
                if conf.get("record_type_b64"):
                    schema = base64.b64decode(conf["record_type_b64"])
                    with self._lock:
                        self.topic_schemas[tname] = schema
        return schema

    def _persist_topic_conf(self, tref: TopicRef) -> None:
        if self.filer is None:
            return
        import base64
        import json
        tname = str(tref)
        with self._lock:
            parts = self.topics.get(tname, [])
            leaders = dict(self.topic_leaders.get(tname, {}))
            schema = self.topic_schemas.get(tname, b"")
        conf = {"partition_count": len(parts),
                "leaders": {str(k): v for k, v in leaders.items()}}
        if schema:
            conf["record_type_b64"] = base64.b64encode(schema).decode()
        self.filer.write_file(
            f"/topics/{tref.namespace}/{tref.name}/topic.conf",
            json.dumps(conf).encode(), mime="application/json")

    def _topic_partitions(self, tref: TopicRef) -> list[Partition] | None:
        parts = self.topics.get(str(tref))
        if parts is not None:
            return parts
        if self.filer is not None:
            import json

            from ..filer.filer import split_path
            d, n = split_path(
                f"/topics/{tref.namespace}/{tref.name}/topic.conf")
            entry = self.filer.filer.find_entry(d, n)
            if entry is not None:
                import base64
                conf = json.loads(self.filer.read_entry_bytes(entry))
                parts = split_ring(conf["partition_count"])
                with self._lock:
                    self.topics[str(tref)] = parts
                    self.topic_leaders[str(tref)] = {
                        int(k): v
                        for k, v in conf.get("leaders", {}).items()}
                    if conf.get("record_type_b64"):
                        self.topic_schemas[str(tref)] = base64.b64decode(
                            conf["record_type_b64"])
                return parts
        return None

    # -- gRPC ----------------------------------------------------------------
    def _build_service(self) -> RpcService:
        svc = RpcService(MQ_SERVICE)
        broker = self

        def tref_of(t: mq.Topic) -> TopicRef:
            return TopicRef(t.namespace or "default", t.name)

        def part_of(p: mq.Partition) -> Partition:
            return Partition(p.range_start, p.range_stop,
                             p.ring_size or 4096)

        def fill_assignments(resp, tref: TopicRef, parts: list[Partition]):
            tname = str(tref)
            for p in parts:
                a = resp.assignments.add(
                    leader_broker=broker.leader_for(tname, p))
                a.partition.range_start = p.range_start
                a.partition.range_stop = p.range_stop
                a.partition.ring_size = p.ring_size

        @svc.unary("ConfigureTopic", mq.ConfigureTopicRequest,
                   mq.ConfigureTopicResponse)
        def configure(req, ctx):
            tref = tref_of(req.topic)
            parts = broker.configure_topic(tref, req.partition_count or 1,
                                           bytes(req.record_type))
            resp = mq.ConfigureTopicResponse()
            fill_assignments(resp, tref, parts)
            return resp

        @svc.unary("GetTopicConfiguration",
                   mq.GetTopicConfigurationRequest,
                   mq.GetTopicConfigurationResponse)
        def get_topic_configuration(req, ctx):
            """Reference GetTopicConfiguration: partitions + the topic's
            registered schema (subscribers fetch it to decode records)."""
            tref = tref_of(req.topic)
            parts = broker._topic_partitions(tref)
            if parts is None:
                ctx.abort(5, f"topic {tref} not found")
            resp = mq.GetTopicConfigurationResponse(
                partition_count=len(parts),
                record_type=broker._topic_schema(tref))
            resp.topic.CopyFrom(req.topic)
            fill_assignments(resp, tref, parts)
            return resp

        @svc.unary("LookupTopicBrokers", mq.LookupTopicBrokersRequest,
                   mq.LookupTopicBrokersResponse)
        def lookup(req, ctx):
            tref = tref_of(req.topic)
            parts = broker._topic_partitions(tref)
            if parts is None:
                ctx.abort(5, f"topic {tref} not found")
            resp = mq.LookupTopicBrokersResponse()
            resp.topic.CopyFrom(req.topic)
            fill_assignments(resp, tref, parts)
            return resp

        @svc.unary("Ping", mq.PingRequest, mq.PingResponse)
        def ping(req, ctx):
            return mq.PingResponse(remote_time_ns=time.time_ns())

        @svc.unary("BalanceTopics", mq.BalanceTopicsRequest,
                   mq.BalanceTopicsResponse)
        def balance_topics(req, ctx):
            """Reference mq.proto BalanceTopics (shell mq.balance): re-derive
            every topic's partition ring from its configured count — healing
            any drift — and report the resulting assignment. Ownership stays
            deterministic over the ring (broker docstring), so no partition
            hand-off messages are needed."""
            resp = mq.BalanceTopicsResponse()
            ring = broker.live_brokers()
            healed_topics: "list[TopicRef]" = []
            with broker._lock:  # one lock span: a concurrent
                # ConfigureTopic must not be reverted from a stale snapshot
                for full in sorted(broker.topics):
                    rebuilt = split_ring(len(broker.topics[full]))
                    broker.topics[full] = rebuilt
                    # heal ONLY dead-leader assignments, with the same
                    # deterministic fallback leader_for uses — every other
                    # broker computes the identical answer from its own
                    # cached conf, so views stay convergent without a
                    # cross-broker conf push
                    leaders = dict(broker.topic_leaders.get(full, {}))
                    healed = False
                    for p in rebuilt:
                        if leaders.get(p.range_start) not in ring:
                            h = _stable_hash(f"{full}:{p.range_start}")
                            leaders[p.range_start] = ring[h % len(ring)]
                            healed = True
                    broker.topic_leaders[full] = leaders
                    ns, _, name = full.partition(".")
                    if healed:
                        healed_topics.append(TopicRef(ns, name))
                    a = resp.assignments.add()
                    a.topic.namespace, a.topic.name = ns, name
                    for p in rebuilt:
                        a.partitions.add(range_start=p.range_start,
                                         range_stop=p.range_stop,
                                         ring_size=p.ring_size)
            # persist OUTSIDE broker._lock (it re-acquires it), via the
            # one conf writer — a hand-rolled dict here silently dropped
            # record_type_b64, so a healed topic lost its registered schema
            for tref in healed_topics:
                broker._persist_topic_conf(tref)
            return resp

        @svc.unary("ListTopics", mq.ListTopicsRequest, mq.ListTopicsResponse)
        def list_topics(req, ctx):
            resp = mq.ListTopicsResponse()
            with broker._lock:
                names = sorted(broker.topics)
            for full in names:
                ns, _, name = full.partition(".")
                resp.topics.add(namespace=ns, name=name)
            return resp

        @svc.stream_stream("Publish", mq.PublishRequest, mq.PublishResponse)
        def publish(request_iter, ctx):
            """Reference broker_grpc_pub.go: first message is init,
            then data; each append acks with its offset."""
            lg = None
            for req in request_iter:
                if req.HasField("init"):
                    tref = tref_of(req.init.topic)
                    if broker._topic_partitions(tref) is None:
                        broker.configure_topic(tref, 1)
                    lg = broker._log_for(tref, part_of(req.init.partition))
                    continue
                if lg is None:
                    yield mq.PublishResponse(error="publish before init")
                    return
                ts = req.data.ts_ns or time.time_ns()
                off = lg.append(bytes(req.data.key),
                                bytes(req.data.value), ts)
                yield mq.PublishResponse(ack_sequence=off)

        @svc.unary("FindCoordinator", mq.FindCoordinatorRequest,
                   mq.FindCoordinatorResponse)
        def find_coordinator(req, ctx):
            tname = str(tref_of(req.topic))
            return mq.FindCoordinatorResponse(
                coordinator=broker.coordinator_for(tname,
                                                   req.consumer_group))

        @svc.stream_stream("SubscriberToSubCoordinator",
                           mq.SubscriberToSubCoordinatorRequest,
                           mq.SubscriberToSubCoordinatorResponse)
        def sub_coordinate(request_iter, ctx):
            """Reference broker_grpc_sub_coordinator.go: member joins with
            init, holds the stream open, and receives a generation-stamped
            Assignment after every rebalance; the stream breaking (death
            or leave) removes the member and triggers a rebalance for the
            survivors."""
            first = next(request_iter)
            group = first.init.consumer_group
            iid = first.init.consumer_group_instance_id
            tname = str(tref_of(first.init.topic))
            inst = broker.coordinator.add_subscriber(group, iid, tname)

            def drain():
                # consume acks until the client goes away, then unblock
                # the response loop with a poison pill
                try:
                    for _ in request_iter:
                        pass
                except Exception as e:  # noqa: BLE001
                    log.debug("subscribe request stream drain ended: %s", e)
                inst.responses.put(None)

            threading.Thread(target=drain, daemon=True,
                             name=f"mq-coord-drain-{iid}").start()
            ctx.add_callback(lambda: inst.responses.put(None))
            try:
                while ctx.is_active():
                    item = inst.responses.get()
                    if item is None:
                        return
                    gen, slots = item
                    resp = mq.SubscriberToSubCoordinatorResponse()
                    resp.assignment.generation = gen
                    for slot in slots:
                        pa = resp.assignment.partition_assignments.add(
                            leader_broker=slot.broker)
                        pa.partition.range_start = slot.range_start
                        pa.partition.range_stop = slot.range_stop
                        pa.partition.ring_size = slot.ring_size
                    yield resp
            finally:
                broker.coordinator.remove_subscriber(group, iid, tname)

        @svc.unary("DescribeConsumerGroups",
                   mq.DescribeConsumerGroupsRequest,
                   mq.DescribeConsumerGroupsResponse)
        def describe_groups(req, ctx):
            """Groups coordinated by THIS broker for the topic, with
            member assignments and committed offsets (the shell fans out
            to every live broker and merges)."""
            tname = str(tref_of(req.topic))
            resp = mq.DescribeConsumerGroupsResponse()
            coord = broker.coordinator
            with coord._lock:
                snap = [(g, cg.generation, dict(cg.instances),
                         list(cg.mapping))
                        for (t, g), cg in coord.groups.items()
                        if t == tname]
            for gname, gen, instances, mapping in snap:
                g = resp.groups.add(name=gname, generation=gen)
                by_inst: dict[str, list] = {i: [] for i in instances}
                for slot in mapping:
                    by_inst.setdefault(slot.assigned_instance_id,
                                       []).append(slot)
                for iid in sorted(instances):
                    m = g.members.add(instance_id=iid)
                    for slot in by_inst.get(iid, []):
                        m.partitions.add(range_start=slot.range_start,
                                         range_stop=slot.range_stop,
                                         ring_size=slot.ring_size)
                for p, _leader in broker._group_partitions(tname):
                    off = broker.fetch_offset(tname, p, gname)
                    po = g.offsets.add(committed=off)
                    po.partition.range_start = p.range_start
                    po.partition.range_stop = p.range_stop
                    po.partition.ring_size = p.ring_size
            return resp

        @svc.unary("CommitOffset", mq.CommitOffsetRequest,
                   mq.CommitOffsetResponse)
        def commit_offset(req, ctx):
            broker.commit_offset(str(tref_of(req.topic)),
                                 part_of(req.partition),
                                 req.consumer_group, req.offset)
            return mq.CommitOffsetResponse()

        @svc.unary("FetchOffset", mq.FetchOffsetRequest,
                   mq.FetchOffsetResponse)
        def fetch_offset(req, ctx):
            off = broker.fetch_offset(str(tref_of(req.topic)),
                                      part_of(req.partition),
                                      req.consumer_group)
            return mq.FetchOffsetResponse(offset=off, found=off >= 0)

        @svc.unary_stream("Subscribe", mq.SubscribeRequest,
                          mq.SubscribeResponse)
        def subscribe(req, ctx):
            """Reference broker_grpc_sub.go: replay from offset, then
            follow if requested."""
            init = req.init
            tref = tref_of(init.topic)
            if broker._topic_partitions(tref) is None:
                ctx.abort(5, f"topic {tref} not found")
            lg = broker._log_for(tref, part_of(init.partition))
            offset = (lg.next_offset if init.start_offset < 0
                      else init.start_offset)
            while ctx.is_active():
                batch = lg.read(offset)
                for off, k, v, ts in batch:
                    resp = mq.SubscribeResponse(offset=off)
                    resp.data.key, resp.data.value = k, v
                    resp.data.ts_ns = ts
                    yield resp
                    offset = off + 1
                if not batch:
                    if not init.follow:
                        yield mq.SubscribeResponse(is_end_of_stream=True)
                        return
                    lg.wait_for(offset, timeout=0.5)

        return svc
