"""MQ client: publisher + subscriber over the broker gRPC
(reference weed/mq/client: pub_client / sub_client)."""

from __future__ import annotations

import queue
import time

from ..pb import mq_pb2 as mq
from ..utils.rpc import Stub
from .broker import MQ_SERVICE
from .topic import Partition, TopicRef, partition_for_key, split_ring


class Publisher:
    """Leader-aware publisher: each partition's Publish stream dials the
    broker that LookupTopicBrokers names as its leader, and a dead leader
    (broker crash) is survived by re-looking-up on the remaining seed
    brokers and re-sending the unacked message (reference
    pub_client/publish.go re-dials the same way)."""

    def __init__(self, broker_address: "str | list[str]", namespace: str,
                 topic: str, partition_count: int = 1, schema=None):
        self.seeds = ([broker_address] if isinstance(broker_address, str)
                      else list(broker_address))
        self.stub = Stub(self.seeds[0], MQ_SERVICE)
        self.tref = TopicRef(namespace, topic)
        self.schema = schema  # mq.schema.Schema: typed-record publishing
        resp = self.stub.call("ConfigureTopic", _configure_req(
            self.tref, partition_count,
            schema.schema_bytes() if schema is not None else b""),
            mq.ConfigureTopicResponse)
        self.partitions = [Partition(a.partition.range_start,
                                     a.partition.range_stop,
                                     a.partition.ring_size)
                           for a in resp.assignments]
        self._leaders = {a.partition.range_start: a.leader_broker
                         for a in resp.assignments}
        self._queues: dict[int, queue.Queue] = {}
        self._streams: dict[int, object] = {}

    def _refresh_leaders(self) -> None:
        for addr in self.seeds:
            try:
                resp = Stub(addr, MQ_SERVICE).call(
                    "LookupTopicBrokers", _lookup_req(self.tref),
                    mq.LookupTopicBrokersResponse, timeout=2)
                self._leaders = {a.partition.range_start: a.leader_broker
                                 for a in resp.assignments}
                return
            except Exception:  # noqa: BLE001
                continue

    def _drop_stream(self, p: Partition) -> None:
        q = self._queues.pop(p.range_start, None)
        if q is not None:
            q.put(None)
        self._streams.pop(p.range_start, None)

    def _stream_for(self, p: Partition):
        if p.range_start in self._streams:
            return (self._queues[p.range_start],
                    self._streams[p.range_start])
        q: queue.Queue = queue.Queue()

        def reqs():
            init = mq.PublishRequest()
            init.init.topic.namespace = self.tref.namespace
            init.init.topic.name = self.tref.name
            init.init.partition.range_start = p.range_start
            init.init.partition.range_stop = p.range_stop
            init.init.partition.ring_size = p.ring_size
            yield init
            while True:
                item = q.get()
                if item is None:
                    return
                yield item

        leader = self._leaders.get(p.range_start, self.seeds[0])
        stream = Stub(leader, MQ_SERVICE).stream_stream(
            "Publish", reqs(), mq.PublishRequest, mq.PublishResponse)
        self._queues[p.range_start] = q
        self._streams[p.range_start] = iter(stream)
        return q, self._streams[p.range_start]

    def publish(self, key: bytes, value: bytes, retries: int = 8) -> int:
        """Send one message; returns the acked partition offset. A broken
        stream re-resolves the partition leader and re-sends. Semantics
        are AT-LEAST-ONCE (same as the reference's re-dial): if the
        leader appended the message but died before the ack arrived, the
        retry appends it again on the survivor."""
        p = partition_for_key(key, self.partitions)
        req = mq.PublishRequest()
        req.data.key, req.data.value = key, value
        req.data.ts_ns = time.time_ns()
        last_err: Exception | None = None
        for attempt in range(retries):
            try:
                q, stream = self._stream_for(p)
                q.put(req)
                ack = next(stream)
                if ack.error:
                    raise RuntimeError(ack.error)
                return ack.ack_sequence
            except Exception as e:  # noqa: BLE001
                last_err = e
                self._drop_stream(p)
                time.sleep(min(0.2 * (attempt + 1), 1.0))
                self._refresh_leaders()
        raise RuntimeError(f"publish to {p} failed: {last_err}")

    def publish_record(self, key: bytes, record) -> int:
        """Typed publish: encode `record` (dict/dataclass) with the
        topic's registered schema."""
        if self.schema is None:
            raise ValueError("publisher has no schema (pass schema=)")
        return self.publish(key, self.schema.encode(record))

    def close(self) -> None:
        for q in self._queues.values():
            q.put(None)


def _configure_req(tref: TopicRef, n: int,
                   record_type: bytes = b"") -> mq.ConfigureTopicRequest:
    req = mq.ConfigureTopicRequest(partition_count=n,
                                   record_type=record_type)
    req.topic.namespace = tref.namespace
    req.topic.name = tref.name
    return req


def topic_schema(broker_address: str, namespace: str, topic: str):
    """Fetch a topic's registered schema (GetTopicConfiguration); None
    for schemaless topics. Subscribers decode records with it."""
    from .schema import Schema
    req = mq.GetTopicConfigurationRequest()
    req.topic.namespace = namespace
    req.topic.name = topic
    resp = Stub(broker_address, MQ_SERVICE).call(
        "GetTopicConfiguration", req, mq.GetTopicConfigurationResponse)
    return Schema.from_bytes(bytes(resp.record_type)) \
        if resp.record_type else None


def subscribe(broker_address: str, namespace: str, topic: str,
              start_offset: int = 0, follow: bool = False,
              partition: Partition | None = None):
    """Yield (offset, key, value) from one partition (default: the whole
    ring when the topic has a single partition)."""
    stub = Stub(broker_address, MQ_SERVICE)
    tref = TopicRef(namespace, topic)
    if partition is None:
        resp = stub.call("LookupTopicBrokers",
                         _lookup_req(tref), mq.LookupTopicBrokersResponse)
        a = resp.assignments[0]
        partition = Partition(a.partition.range_start,
                              a.partition.range_stop,
                              a.partition.ring_size)
    req = mq.SubscribeRequest()
    req.init.topic.namespace = tref.namespace
    req.init.topic.name = tref.name
    req.init.partition.range_start = partition.range_start
    req.init.partition.range_stop = partition.range_stop
    req.init.partition.ring_size = partition.ring_size
    req.init.start_offset = start_offset
    req.init.follow = follow
    for resp in stub.call_stream("Subscribe", req, mq.SubscribeResponse,
                                 timeout=3600):
        if resp.is_end_of_stream:
            return
        yield resp.offset, bytes(resp.data.key), bytes(resp.data.value)


def _lookup_req(tref: TopicRef) -> mq.LookupTopicBrokersRequest:
    req = mq.LookupTopicBrokersRequest()
    req.topic.namespace = tref.namespace
    req.topic.name = tref.name
    return req
