"""MQ client: publisher + subscriber over the broker gRPC
(reference weed/mq/client: pub_client / sub_client)."""

from __future__ import annotations

import queue
import time

from ..pb import mq_pb2 as mq
from ..utils.rpc import Stub
from .broker import MQ_SERVICE
from .topic import Partition, TopicRef, partition_for_key, split_ring


class Publisher:
    def __init__(self, broker_address: str, namespace: str, topic: str,
                 partition_count: int = 1):
        self.stub = Stub(broker_address, MQ_SERVICE)
        self.tref = TopicRef(namespace, topic)
        resp = self.stub.call("ConfigureTopic", _configure_req(
            self.tref, partition_count), mq.ConfigureTopicResponse)
        self.partitions = [Partition(a.partition.range_start,
                                     a.partition.range_stop,
                                     a.partition.ring_size)
                           for a in resp.assignments]
        self._queues: dict[int, queue.Queue] = {}
        self._streams: dict[int, object] = {}

    def _stream_for(self, p: Partition):
        if p.range_start in self._streams:
            return (self._queues[p.range_start],
                    self._streams[p.range_start])
        q: queue.Queue = queue.Queue()

        def reqs():
            init = mq.PublishRequest()
            init.init.topic.namespace = self.tref.namespace
            init.init.topic.name = self.tref.name
            init.init.partition.range_start = p.range_start
            init.init.partition.range_stop = p.range_stop
            init.init.partition.ring_size = p.ring_size
            yield init
            while True:
                item = q.get()
                if item is None:
                    return
                yield item

        stream = self.stub.stream_stream("Publish", reqs(),
                                         mq.PublishRequest,
                                         mq.PublishResponse)
        self._queues[p.range_start] = q
        self._streams[p.range_start] = iter(stream)
        return q, self._streams[p.range_start]

    def publish(self, key: bytes, value: bytes) -> int:
        """Send one message; returns the acked partition offset."""
        p = partition_for_key(key, self.partitions)
        q, stream = self._stream_for(p)
        req = mq.PublishRequest()
        req.data.key, req.data.value = key, value
        req.data.ts_ns = time.time_ns()
        q.put(req)
        ack = next(stream)
        if ack.error:
            raise RuntimeError(ack.error)
        return ack.ack_sequence

    def close(self) -> None:
        for q in self._queues.values():
            q.put(None)


def _configure_req(tref: TopicRef, n: int) -> mq.ConfigureTopicRequest:
    req = mq.ConfigureTopicRequest(partition_count=n)
    req.topic.namespace = tref.namespace
    req.topic.name = tref.name
    return req


def subscribe(broker_address: str, namespace: str, topic: str,
              start_offset: int = 0, follow: bool = False,
              partition: Partition | None = None):
    """Yield (offset, key, value) from one partition (default: the whole
    ring when the topic has a single partition)."""
    stub = Stub(broker_address, MQ_SERVICE)
    tref = TopicRef(namespace, topic)
    if partition is None:
        resp = stub.call("LookupTopicBrokers",
                         _lookup_req(tref), mq.LookupTopicBrokersResponse)
        a = resp.assignments[0]
        partition = Partition(a.partition.range_start,
                              a.partition.range_stop,
                              a.partition.ring_size)
    req = mq.SubscribeRequest()
    req.init.topic.namespace = tref.namespace
    req.init.topic.name = tref.name
    req.init.partition.range_start = partition.range_start
    req.init.partition.range_stop = partition.range_stop
    req.init.partition.ring_size = partition.ring_size
    req.init.start_offset = start_offset
    req.init.follow = follow
    for resp in stub.call_stream("Subscribe", req, mq.SubscribeResponse,
                                 timeout=3600):
        if resp.is_end_of_stream:
            return
        yield resp.offset, bytes(resp.data.key), bytes(resp.data.value)


def _lookup_req(tref: TopicRef) -> mq.LookupTopicBrokersRequest:
    req = mq.LookupTopicBrokersRequest()
    req.topic.namespace = tref.namespace
    req.topic.name = tref.name
    return req
