"""Consumer-group client (reference weed/mq/client/sub_client/: the
subscriber session holds a SubscriberToSubCoordinator stream for
assignments and one Subscribe stream per assigned partition).

Lifecycle: FindCoordinator on any live broker -> join the coordination
stream -> each Assignment (re)spawns partition workers. A worker fetches
the group's committed offset, subscribes from offset+1 on the partition
leader, and funnels records into one poll() queue. Any stream death —
coordinator or partition — re-resolves against the surviving brokers and
resumes from committed offsets, so a broker crash costs redelivery of at
most the uncommitted window (at-least-once; commit-per-record gives
effectively-once for side-effect-free processing).
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass

from ..pb import mq_pb2 as mq
from ..utils.log import logger
from ..utils.rpc import Stub
from .broker import MQ_SERVICE
from .topic import Partition, TopicRef

log = logger("mq.consumer")


@dataclass(frozen=True)
class ConsumerRecord:
    partition: Partition
    leader: str  # broker serving the partition when this was read
    offset: int
    key: bytes
    value: bytes
    ts_ns: int


class GroupConsumer:
    """One consumer-group member."""

    def __init__(self, brokers: list[str] | str, namespace: str, topic: str,
                 group: str, instance_id: str,
                 retry_interval_s: float = 0.2):
        self.seeds = ([brokers] if isinstance(brokers, str)
                      else list(brokers))
        self.tref = TopicRef(namespace, topic)
        self.group = group
        self.instance_id = instance_id
        self.retry = retry_interval_s
        self.records: "queue.Queue[ConsumerRecord]" = queue.Queue()
        self.generation = 0
        self.assigned: dict[int, tuple[Partition, str]] = {}
        self._workers: dict[int, threading.Event] = {}  # range_start -> stop
        # highest offset ALREADY put on the records queue, per partition:
        # a worker restart (stream death, leader failover) resumes from the
        # committed offset, and this watermark drops the redelivered slice
        # this member has already seen — exactly-once delivery within one
        # member; cross-member handoff remains at-least-once past the
        # committed offset (same contract as the reference)
        self._delivered: dict[int, int] = {}
        self._lock = threading.Lock()
        self._closed = threading.Event()
        self._assigned_once = threading.Event()
        self._thread = threading.Thread(target=self._session, daemon=True,
                                        name=f"mq-consumer-{instance_id}")
        self._thread.start()

    # -- public --------------------------------------------------------------
    def poll(self, timeout: float = 5.0) -> ConsumerRecord | None:
        """Next record from any assigned partition. Records whose
        partition has been revoked since they were queued are dropped —
        a revoked partition's uncommitted tail belongs to its NEW owner,
        and delivering it here after the owner re-reads it would be a
        guaranteed duplicate (the remaining cross-member window is the
        in-flight record the app is processing at revoke time:
        at-least-once, same contract as the reference / Kafka sans EOS)."""
        deadline = time.monotonic() + timeout
        while True:
            try:
                rec = self.records.get(
                    timeout=max(0.0, deadline - time.monotonic()))
            except queue.Empty:
                return None
            with self._lock:
                if rec.partition.range_start in self._workers:
                    return rec
            # revoked while queued: drop and keep polling

    def commit(self, rec: ConsumerRecord) -> None:
        """Persist rec.offset as processed; resume after failure happens
        at rec.offset + 1. Tries the record's leader first, then any
        live broker (offsets live in the shared filer)."""
        req = mq.CommitOffsetRequest(consumer_group=self.group,
                                     offset=rec.offset)
        req.topic.namespace = self.tref.namespace
        req.topic.name = self.tref.name
        req.partition.range_start = rec.partition.range_start
        req.partition.range_stop = rec.partition.range_stop
        req.partition.ring_size = rec.partition.ring_size
        for addr in [rec.leader, *self.seeds]:
            try:
                Stub(addr, MQ_SERVICE).call("CommitOffset", req,
                                            mq.CommitOffsetResponse,
                                            timeout=5)
                return
            except Exception:  # noqa: BLE001
                continue
        raise RuntimeError(f"commit offset {rec.offset} failed on all brokers")

    def wait_assigned(self, timeout: float = 10.0) -> bool:
        return self._assigned_once.wait(timeout)

    def close(self) -> None:
        self._closed.set()
        with self._lock:
            for ev in self._workers.values():
                ev.set()

    # -- coordinator session -------------------------------------------------
    def _find_coordinator(self) -> str | None:
        req = mq.FindCoordinatorRequest(consumer_group=self.group)
        req.topic.namespace = self.tref.namespace
        req.topic.name = self.tref.name
        for addr in self.seeds:
            try:
                resp = Stub(addr, MQ_SERVICE).call(
                    "FindCoordinator", req, mq.FindCoordinatorResponse,
                    timeout=2)
                if resp.coordinator:
                    return resp.coordinator
            except Exception:  # noqa: BLE001
                continue
        return None

    def _session(self) -> None:
        while not self._closed.is_set():
            coord = self._find_coordinator()
            if coord is None:
                self._closed.wait(self.retry)
                continue
            try:
                self._run_coordination(coord)
            except Exception as e:  # noqa: BLE001
                if not self._closed.is_set():
                    log.info("%s: coordinator %s lost (%s); rejoining",
                             self.instance_id, coord, e)
            self._closed.wait(self.retry)
        # shutdown: stop all workers
        self._apply_assignment(self.generation + 1, [])

    def _run_coordination(self, coord: str) -> None:
        # a fresh coordinator (failover) starts its generations over at 1:
        # reset ours so its first assignment isn't dropped as stale
        with self._lock:
            self.generation = 0
        stub = Stub(coord, MQ_SERVICE)

        def reqs():
            init = mq.SubscriberToSubCoordinatorRequest()
            init.init.consumer_group = self.group
            init.init.consumer_group_instance_id = self.instance_id
            init.init.topic.namespace = self.tref.namespace
            init.init.topic.name = self.tref.name
            yield init
            while not self._closed.wait(0.5):
                pass  # stream held open; half-close on close()

        stream = stub.stream_stream(
            "SubscriberToSubCoordinator", reqs(),
            mq.SubscriberToSubCoordinatorRequest,
            mq.SubscriberToSubCoordinatorResponse)
        for resp in stream:
            if self._closed.is_set():
                stream.cancel()
                return
            a = resp.assignment
            slots = [(Partition(pa.partition.range_start,
                                pa.partition.range_stop,
                                pa.partition.ring_size or 4096),
                      pa.leader_broker)
                     for pa in a.partition_assignments]
            self._apply_assignment(a.generation, slots)
            self._assigned_once.set()

    def _apply_assignment(self, generation: int,
                          slots: list[tuple[Partition, str]]) -> None:
        """Diff against current workers: stop revoked partitions, spawn
        newly assigned ones. A re-assigned partition with a NEW leader is
        restarted so it follows the failover."""
        with self._lock:
            if 0 < generation <= self.generation:
                return  # stale assignment from a lagging coordinator
            self.generation = generation
            want = {p.range_start: (p, leader) for p, leader in slots}
            for rs in list(self._workers):
                if rs not in want or self.assigned.get(rs) != want[rs]:
                    self._workers.pop(rs).set()
                    self.assigned.pop(rs, None)
                    if rs not in want:
                        # truly revoked (not a leader-change restart):
                        # purge its queued records NOW — if the partition
                        # later returns, stale first-ownership records
                        # would pass poll's membership check while the
                        # fresh worker re-reads the same offsets (double
                        # delivery) — and reset the watermark, since
                        # suppressing offsets we queued but never
                        # processed would turn the purge into loss
                        self._purge_queued(rs)
                        self._delivered.pop(rs, None)
            to_start = []
            for rs, (p, leader) in want.items():
                if rs in self._workers:
                    continue
                stop = threading.Event()
                self._workers[rs] = stop
                self.assigned[rs] = (p, leader)
                to_start.append(threading.Thread(
                    target=self._consume_partition,
                    args=(p, leader, stop), daemon=True,
                    name=f"mq-part-{self.instance_id}-{rs}"))
        # spawn OUTSIDE the lock: Thread.start() blocks on the new
        # thread's bootstrap, and under load N spawns serialized behind
        # self._lock stall every concurrent poll()/commit() for the
        # whole rebalance (locktrack long-hold finding)
        for t in to_start:
            t.start()

    def _purge_queued(self, range_start: int) -> None:
        """Drop a revoked partition's not-yet-polled records, preserving
        the order of everything else."""
        keep: list[ConsumerRecord] = []
        while True:
            try:
                rec = self.records.get_nowait()
            except queue.Empty:
                break
            if rec.partition.range_start != range_start:
                keep.append(rec)
        for rec in keep:
            self.records.put(rec)

    # -- partition worker ----------------------------------------------------
    def _fetch_offset(self, p: Partition, leader: str) -> int:
        req = mq.FetchOffsetRequest(consumer_group=self.group)
        req.topic.namespace = self.tref.namespace
        req.topic.name = self.tref.name
        req.partition.range_start = p.range_start
        req.partition.range_stop = p.range_stop
        req.partition.ring_size = p.ring_size
        for addr in [leader, *self.seeds]:
            try:
                resp = Stub(addr, MQ_SERVICE).call(
                    "FetchOffset", req, mq.FetchOffsetResponse, timeout=5)
                return resp.offset if resp.found else -1
            except Exception:  # noqa: BLE001
                continue
        return -1

    def _lookup_leader(self, p: Partition) -> str | None:
        req = mq.LookupTopicBrokersRequest()
        req.topic.namespace = self.tref.namespace
        req.topic.name = self.tref.name
        for addr in self.seeds:
            try:
                resp = Stub(addr, MQ_SERVICE).call(
                    "LookupTopicBrokers", req,
                    mq.LookupTopicBrokersResponse, timeout=2)
                for a in resp.assignments:
                    if a.partition.range_start == p.range_start:
                        return a.leader_broker
            except Exception:  # noqa: BLE001
                continue
        return None

    def _consume_partition(self, p: Partition, leader: str,
                           stop: threading.Event) -> None:
        while not stop.is_set() and not self._closed.is_set():
            start = self._fetch_offset(p, leader) + 1
            req = mq.SubscribeRequest()
            req.init.topic.namespace = self.tref.namespace
            req.init.topic.name = self.tref.name
            req.init.partition.range_start = p.range_start
            req.init.partition.range_stop = p.range_stop
            req.init.partition.ring_size = p.ring_size
            req.init.consumer_group = self.group
            req.init.consumer_id = self.instance_id
            req.init.start_offset = start
            req.init.follow = True
            try:
                stream = Stub(leader, MQ_SERVICE).call_stream(
                    "Subscribe", req, mq.SubscribeResponse, timeout=3600)
                for resp in stream:
                    if stop.is_set() or self._closed.is_set():
                        stream.cancel()
                        return
                    if resp.is_end_of_stream:
                        break
                    # watermark + enqueue under the consumer lock, fenced
                    # on THIS worker still owning the partition: a revoke
                    # (purge + watermark reset, _apply_assignment) cannot
                    # be undone by an in-flight record, and a purge can
                    # never interleave with a concurrent put
                    with self._lock:
                        if stop.is_set() or \
                                self._workers.get(p.range_start) is not stop:
                            return
                        if resp.offset <= self._delivered.get(
                                p.range_start, -1):
                            continue  # redelivery already queued
                        self._delivered[p.range_start] = resp.offset
                        self.records.put(ConsumerRecord(
                            p, leader, resp.offset, bytes(resp.data.key),
                            bytes(resp.data.value), resp.data.ts_ns))
            except Exception as e:  # noqa: BLE001
                if stop.is_set() or self._closed.is_set():
                    return
                log.info("%s: partition %s stream on %s died (%s)",
                         self.instance_id, p, leader, e)
            if stop.wait(self.retry):
                return
            # leader may have moved (broker death): re-resolve before
            # the next attempt; the coordinator will also push a fresh
            # assignment, which restarts this worker via _apply_assignment
            leader = self._lookup_leader(p) or leader


def group_consume(brokers, namespace: str, topic: str, group: str,
                  instance_id: str, count: int,
                  timeout: float = 30.0,
                  commit_each: bool = True) -> list[ConsumerRecord]:
    """Convenience: consume exactly `count` records as one group member,
    committing after each (test harness + CLI verb helper)."""
    c = GroupConsumer(brokers, namespace, topic, group, instance_id)
    out: list[ConsumerRecord] = []
    deadline = time.monotonic() + timeout
    try:
        while len(out) < count and time.monotonic() < deadline:
            rec = c.poll(timeout=max(0.05,
                                     min(1.0, deadline - time.monotonic())))
            if rec is None:
                continue
            out.append(rec)
            if commit_each:
                c.commit(rec)
    finally:
        c.close()
    return out
