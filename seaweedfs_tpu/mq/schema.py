"""Structured-record schema for MQ topics (reference weed/mq/schema/:
schema.go, schema_builder.go, struct_to_schema.go, to_schema_value.go).

Three capabilities, mirroring the reference:
  * infer_record_type(value)   — Python dict/dataclass -> RecordType proto
    (struct_to_schema.go's reflection walk, over Python types);
  * encode/decode              — typed record dict <-> RecordValue proto
    bytes, validated against the schema (value_builder.go /
    to_schema_value.go);
  * to_columnar/from_columnar  — a batch of records <-> flat numpy
    columns. The reference maps records onto PARQUET (to_parquet_schema.go
    with def/rep levels); the tpu-native analogue is columnar numpy:
    nested record fields flatten to dotted column paths exactly like
    parquet column paths, and list fields become (offsets, values) pairs —
    the layout `jax.device_put` ingests without host-side reshuffling.
    Full parquet def/rep level encoding for nullable nesting is a
    documented simplification: fields here are required (proto3
    semantics), so def levels are constant and omitted.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

from ..pb import mq_schema_pb2 as spb

# -- scalar type table -------------------------------------------------------

_SCALAR_DTYPES = {
    spb.BOOL: np.dtype(np.bool_),
    spb.INT32: np.dtype(np.int32),
    spb.INT64: np.dtype(np.int64),
    spb.FLOAT: np.dtype(np.float32),
    spb.DOUBLE: np.dtype(np.float64),
}
_VALUE_FIELD = {
    spb.BOOL: "bool_value",
    spb.INT32: "int32_value",
    spb.INT64: "int64_value",
    spb.FLOAT: "float_value",
    spb.DOUBLE: "double_value",
    spb.BYTES: "bytes_value",
    spb.STRING: "string_value",
}


def scalar(kind: int) -> spb.Type:
    return spb.Type(scalar_type=kind)


TypeBool = scalar(spb.BOOL)
TypeInt32 = scalar(spb.INT32)
TypeInt64 = scalar(spb.INT64)
TypeFloat = scalar(spb.FLOAT)
TypeDouble = scalar(spb.DOUBLE)
TypeBytes = scalar(spb.BYTES)
TypeString = scalar(spb.STRING)


# -- builder (reference schema_builder.go) -----------------------------------

class RecordTypeBuilder:
    """record_type_begin().with_field(...).record_type_end() chain."""

    def __init__(self):
        self._fields: list[spb.Field] = []

    def with_field(self, name: str, ftype: spb.Type) -> "RecordTypeBuilder":
        self._fields.append(spb.Field(name=name, type=ftype))
        return self

    def with_record_field(self, name: str,
                          rec: "RecordTypeBuilder") -> "RecordTypeBuilder":
        self._fields.append(spb.Field(
            name=name, type=spb.Type(record_type=rec.build())))
        return self

    def with_list_field(self, name: str,
                        element: spb.Type) -> "RecordTypeBuilder":
        self._fields.append(spb.Field(name=name, type=spb.Type(
            list_type=spb.ListType(element_type=element))))
        return self

    def build(self) -> spb.RecordType:
        rt = spb.RecordType()
        for i, f in enumerate(sorted(self._fields, key=lambda f: f.name)):
            f.field_index = i
            rt.fields.append(f)
        return rt


def record_type_begin() -> RecordTypeBuilder:
    return RecordTypeBuilder()


# -- inference (reference struct_to_schema.go) -------------------------------

def _infer_type(v: Any) -> spb.Type:
    if isinstance(v, bool):
        return TypeBool
    if isinstance(v, int):
        return TypeInt64 if abs(v) > 0x7FFFFFFF else TypeInt32
    if isinstance(v, float):
        return TypeDouble
    if isinstance(v, bytes):
        return TypeBytes
    if isinstance(v, str):
        return TypeString
    if isinstance(v, (list, tuple)):
        if not v:
            raise ValueError("cannot infer element type of an empty list")
        return spb.Type(list_type=spb.ListType(element_type=_infer_type(v[0])))
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        v = dataclasses.asdict(v)
    if isinstance(v, dict):
        return spb.Type(record_type=infer_record_type(v))
    raise TypeError(f"unsupported field type {type(v).__name__}")


def infer_record_type(value: Any) -> spb.RecordType:
    """Schema from an example record (dict or dataclass instance)."""
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        value = dataclasses.asdict(value)
    if not isinstance(value, dict):
        raise TypeError("record must be a dict or dataclass instance")
    b = record_type_begin()
    for name, v in value.items():
        b.with_field(name, _infer_type(v))
    return b.build()


# -- value encode/decode (reference to_schema_value.go, value_builder.go) ----

def _encode_value(v: Any, ftype: spb.Type) -> spb.Value:
    out = spb.Value()
    kind = ftype.WhichOneof("kind")
    if kind == "scalar_type":
        attr = _VALUE_FIELD[ftype.scalar_type]
        if ftype.scalar_type == spb.BOOL and not isinstance(v, bool):
            raise TypeError(f"expected bool, got {type(v).__name__}")
        setattr(out, attr, v)
    elif kind == "list_type":
        for item in v:
            out.list_value.values.append(
                _encode_value(item, ftype.list_type.element_type))
        # presence: an empty list must still mark the oneof
        out.list_value.SetInParent()
    elif kind == "record_type":
        if dataclasses.is_dataclass(v) and not isinstance(v, type):
            v = dataclasses.asdict(v)
        out.record_value.CopyFrom(_encode_record(v, ftype.record_type))
    else:
        raise TypeError(f"field type has no kind: {ftype}")
    return out


def _encode_record(record: dict, rt: spb.RecordType) -> spb.RecordValue:
    rv = spb.RecordValue()
    for f in rt.fields:
        if f.name not in record:
            raise KeyError(f"record missing field {f.name!r}")
        rv.fields[f.name].CopyFrom(_encode_value(record[f.name], f.type))
    extra = set(record) - {f.name for f in rt.fields}
    if extra:
        raise KeyError(f"record has fields not in schema: {sorted(extra)}")
    return rv


def _decode_value(val: spb.Value, ftype: spb.Type) -> Any:
    kind = ftype.WhichOneof("kind")
    if kind == "scalar_type":
        return getattr(val, _VALUE_FIELD[ftype.scalar_type])
    if kind == "list_type":
        return [_decode_value(x, ftype.list_type.element_type)
                for x in val.list_value.values]
    return _decode_record(val.record_value, ftype.record_type)


def _decode_record(rv: spb.RecordValue, rt: spb.RecordType) -> dict:
    return {f.name: _decode_value(rv.fields[f.name], f.type)
            for f in rt.fields}


class Schema:
    """A validated RecordType + its codec (reference schema.go Schema)."""

    def __init__(self, record_type: spb.RecordType):
        self.record_type = record_type
        self.fields = {f.name: f for f in record_type.fields}

    @classmethod
    def infer(cls, example: Any) -> "Schema":
        return cls(infer_record_type(example))

    @classmethod
    def from_bytes(cls, data: bytes) -> "Schema":
        rt = spb.RecordType()
        rt.ParseFromString(data)
        return cls(rt)

    def schema_bytes(self) -> bytes:
        return self.record_type.SerializeToString()

    def encode(self, record: dict | Any) -> bytes:
        if dataclasses.is_dataclass(record) and not isinstance(record, type):
            record = dataclasses.asdict(record)
        return _encode_record(record, self.record_type).SerializeToString()

    def decode(self, data: bytes) -> dict:
        rv = spb.RecordValue()
        rv.ParseFromString(data)
        return _decode_record(rv, self.record_type)

    # -- columnar batches (the parquet-mapping analogue) ---------------------
    def _columns(self, rt: spb.RecordType | None = None, prefix: str = ""
                 ) -> list[tuple[str, spb.Type]]:
        cols = []
        for f in (rt or self.record_type).fields:
            path = f"{prefix}{f.name}"
            kind = f.type.WhichOneof("kind")
            if kind == "record_type":
                cols.extend(self._columns(f.type.record_type, path + "."))
            else:
                cols.append((path, f.type))
        return cols

    def to_columnar(self, records: list[dict]) -> dict[str, np.ndarray]:
        """Batch of records -> {column path: numpy array}. Scalar columns
        are dense arrays; bytes/str columns are object arrays; a list
        column becomes `path.offsets` (int64, n+1 prefix sums — parquet's
        repetition structure collapsed for required fields) plus
        `path.values`."""
        def get(rec: dict, path: str):
            cur: Any = rec
            for part in path.split("."):
                if dataclasses.is_dataclass(cur) and not isinstance(cur, type):
                    cur = dataclasses.asdict(cur)
                cur = cur[part]
            return cur

        out: dict[str, np.ndarray] = {}
        for path, ftype in self._columns():
            kind = ftype.WhichOneof("kind")
            vals = [get(r, path) for r in records]
            if kind == "scalar_type":
                dt = _SCALAR_DTYPES.get(ftype.scalar_type)
                out[path] = (np.array(vals, dtype=dt) if dt is not None
                             else np.array(vals, dtype=object))
            else:  # list
                el = ftype.list_type.element_type
                dt = (_SCALAR_DTYPES.get(el.scalar_type)
                      if el.WhichOneof("kind") == "scalar_type" else None)
                lens = np.array([len(v) for v in vals], dtype=np.int64)
                out[f"{path}.offsets"] = np.concatenate(
                    ([0], np.cumsum(lens)))
                flat = [x for v in vals for x in v]
                out[f"{path}.values"] = (
                    np.array(flat, dtype=dt) if dt is not None
                    else np.array(flat, dtype=object))
        return out

    def from_columnar(self, cols: dict[str, np.ndarray]) -> list[dict]:
        paths = self._columns()
        n = None
        for path, ftype in paths:
            key = (path if ftype.WhichOneof("kind") == "scalar_type"
                   else f"{path}.offsets")
            m = (len(cols[key]) if ftype.WhichOneof("kind") == "scalar_type"
                 else len(cols[key]) - 1)
            if n is None:
                n = m
            elif n != m:
                raise ValueError(f"column {path}: {m} rows, expected {n}")
        records: list[dict] = [{} for _ in range(n or 0)]

        def put(rec: dict, path: str, v: Any):
            parts = path.split(".")
            for part in parts[:-1]:
                rec = rec.setdefault(part, {})
            rec[parts[-1]] = v

        for path, ftype in paths:
            if ftype.WhichOneof("kind") == "scalar_type":
                col = cols[path]
                for i in range(n):
                    put(records[i], path, col[i].item()
                        if isinstance(col[i], np.generic) else col[i])
            else:
                offs = cols[f"{path}.offsets"]
                vals = cols[f"{path}.values"]
                for i in range(n):
                    seg = vals[offs[i]:offs[i + 1]]
                    put(records[i], path,
                        [x.item() if isinstance(x, np.generic) else x
                         for x in seg])
        return records
