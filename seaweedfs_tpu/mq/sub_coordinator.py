"""Consumer-group coordination (reference weed/mq/sub_coordinator/:
coordinator.go, consumer_group.go, partition_consumer_mapping.go).

A Coordinator lives inside each broker; clients are pointed at THE
coordinator for a (topic, group) by the deterministic FindCoordinator
hash, so exactly one broker balances any given group. Each ConsumerGroup
holds its member instances and a PartitionConsumerMapping; membership or
partition-list changes schedule a debounced rebalance that recomputes a
sticky assignment (surviving members keep their partitions; orphaned
partitions go round-robin to underloaded members — the balance goals at
partition_consumer_mapping.go:21-24) and pushes a generation-stamped
Assignment to every member's response stream.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field

from ..utils.log import logger

log = logger("mq.subcoord")

# the reference debounces membership churn by 5s (consumer_group.go:56);
# that cadence is for humans restarting consumers — tests and most real
# rebalances want sub-second convergence, so it is configurable per broker
REBALANCE_DELAY_S = 5.0


@dataclass
class PartitionSlot:
    """One partition slot in a group's mapping (reference
    PartitionSlotToConsumerInstance, partition_list.go)."""
    range_start: int
    range_stop: int
    ring_size: int
    broker: str  # partition leader broker
    assigned_instance_id: str = ""


def balance_sticky(partitions: list[PartitionSlot],
                   instance_ids: list[str],
                   prev: list[PartitionSlot] | None) -> list[PartitionSlot]:
    """Sticky assignment (reference doBalanceSticky,
    partition_consumer_mapping.go:48): keep each partition with its prior
    instance when that instance is still a member, then hand unassigned
    partitions round-robin to instances below the average load."""
    if not partitions or not instance_ids:
        return []
    live = set(instance_ids)
    prev_by_range: dict[tuple[int, int], str] = {}
    for slot in prev or []:
        if slot.assigned_instance_id:
            prev_by_range[(slot.range_start, slot.range_stop)] = \
                slot.assigned_instance_id

    out = [PartitionSlot(p.range_start, p.range_stop, p.ring_size, p.broker)
           for p in partitions]
    counts: dict[str, int] = {i: 0 for i in instance_ids}
    for slot in out:
        keep = prev_by_range.get((slot.range_start, slot.range_stop), "")
        if keep in live:
            slot.assigned_instance_id = keep
            counts[keep] += 1

    avg = len(partitions) / len(instance_ids)
    idx = 0
    for slot in out:
        if slot.assigned_instance_id:
            continue
        for _ in range(len(instance_ids)):
            cand = instance_ids[idx]
            idx = (idx + 1) % len(instance_ids)
            if counts[cand] < avg:
                slot.assigned_instance_id = cand
                counts[cand] += 1
                break

    # divergence from the reference (improvement): its doBalanceSticky only
    # places UNASSIGNED slots, so a newly joined member idles until
    # partitions churn. Steal minimally from overloaded members (Kafka's
    # sticky assignor behavior) until loads differ by at most one.
    while True:
        lo = min(instance_ids, key=lambda i: counts[i])
        hi = max(instance_ids, key=lambda i: counts[i])
        if counts[hi] - counts[lo] <= 1:
            break
        for slot in out:
            if slot.assigned_instance_id == hi:
                slot.assigned_instance_id = lo
                counts[hi] -= 1
                counts[lo] += 1
                break
    return out


class ConsumerGroupInstance:
    """One connected member: its id plus the queue its coordinator stream
    drains (reference ConsumerGroupInstance.ResponseChan)."""

    def __init__(self, instance_id: str):
        self.instance_id = instance_id
        self.responses: "queue.Queue" = queue.Queue()


@dataclass
class ConsumerGroup:
    """Members + mapping for one (topic, group)."""
    topic_name: str
    instances: dict[str, ConsumerGroupInstance] = field(default_factory=dict)
    mapping: list[PartitionSlot] = field(default_factory=list)
    generation: int = 0


class Coordinator:
    """Per-broker group coordinator. `partitions_of` is a callback
    returning the topic's current [(Partition, leader_broker)] so
    rebalances always see live partition leadership (the reference reads
    the pub_balancer's TopicToBrokers map the same way)."""

    def __init__(self, partitions_of, rebalance_delay_s: float | None = None):
        self._partitions_of = partitions_of
        self.delay = (REBALANCE_DELAY_S if rebalance_delay_s is None
                      else rebalance_delay_s)
        # (topic_name, group) -> ConsumerGroup
        self.groups: dict[tuple[str, str], ConsumerGroup] = {}
        self._timers: dict[tuple[str, str], threading.Timer] = {}
        self._lock = threading.Lock()

    def add_subscriber(self, group: str, instance_id: str,
                       topic_name: str) -> ConsumerGroupInstance:
        with self._lock:
            cg = self.groups.setdefault((topic_name, group),
                                        ConsumerGroup(topic_name))
            inst = cg.instances.get(instance_id)
            if inst is None:
                inst = ConsumerGroupInstance(instance_id)
                cg.instances[instance_id] = inst
        self._schedule(topic_name, group,
                       f"add consumer instance {instance_id}")
        return inst

    def remove_subscriber(self, group: str, instance_id: str,
                          topic_name: str) -> None:
        with self._lock:
            cg = self.groups.get((topic_name, group))
            if cg is None:
                return
            cg.instances.pop(instance_id, None)
            empty = not cg.instances
            if empty:
                self.groups.pop((topic_name, group), None)
                t = self._timers.pop((topic_name, group), None)
                if t:
                    t.cancel()
        if not empty:
            self._schedule(topic_name, group,
                           f"remove consumer instance {instance_id}")

    def topic_names(self) -> set[str]:
        """Topics that currently have consumer groups (for the broker's
        membership watcher)."""
        with self._lock:
            return {t for t, _ in self.groups}

    def on_partition_change(self, topic_name: str) -> None:
        """Broker membership / partition leadership moved (reference
        OnPartitionChange, coordinator.go:95): rebalance every group on
        the topic."""
        with self._lock:
            keys = [k for k in self.groups if k[0] == topic_name]
        for tname, group in keys:
            self._schedule(tname, group, "partition list change")

    def shutdown(self) -> None:
        with self._lock:
            for t in self._timers.values():
                t.cancel()
            self._timers.clear()

    # -- rebalance -----------------------------------------------------------
    def _schedule(self, topic_name: str, group: str, reason: str) -> None:
        """Debounce (consumer_group.go:50): restart the timer on every
        membership event so a burst of joins costs one rebalance."""
        key = (topic_name, group)
        with self._lock:
            old = self._timers.pop(key, None)
            if old:
                old.cancel()
            t = threading.Timer(self.delay, self._rebalance,
                                args=(topic_name, group, reason))
            t.daemon = True
            self._timers[key] = t
            t.start()

    def _rebalance(self, topic_name: str, group: str, reason: str) -> None:
        try:
            partitions = self._partitions_of(topic_name)
        except Exception as e:  # noqa: BLE001
            log.warning("rebalance %s/%s: partitions_of failed: %s",
                        topic_name, group, e)
            return
        with self._lock:
            self._timers.pop((topic_name, group), None)
            cg = self.groups.get((topic_name, group))
            if cg is None or not cg.instances:
                return
            slots = [PartitionSlot(p.range_start, p.range_stop, p.ring_size,
                                   leader)
                     for p, leader in partitions]
            cg.mapping = balance_sticky(slots, sorted(cg.instances),
                                        cg.mapping)
            cg.generation += 1
            gen = cg.generation
            members = dict(cg.instances)
            by_instance: dict[str, list[PartitionSlot]] = {}
            for slot in cg.mapping:
                by_instance.setdefault(slot.assigned_instance_id,
                                       []).append(slot)
        log.info("rebalance %s/%s gen %d (%s): %s", topic_name, group, gen,
                 reason,
                 {i: len(by_instance.get(i, [])) for i in members})
        for iid, inst in members.items():
            inst.responses.put((gen, by_instance.get(iid, [])))
