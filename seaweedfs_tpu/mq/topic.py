"""Topics + partition ring math (reference weed/mq/topic/partition.go:
PartitionCount = 4096; a topic's partitions split the ring into
contiguous ranges; message keys hash onto the ring)."""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

RING_SIZE = 4096  # reference topic/partition.go PartitionCount


@dataclass(frozen=True)
class TopicRef:
    namespace: str
    name: str

    def __str__(self) -> str:
        return f"{self.namespace}.{self.name}"


@dataclass(frozen=True)
class Partition:
    range_start: int
    range_stop: int  # exclusive
    ring_size: int = RING_SIZE

    def covers(self, slot: int) -> bool:
        return self.range_start <= slot < self.range_stop

    def __str__(self) -> str:
        return f"[{self.range_start},{self.range_stop})"


def split_ring(partition_count: int, ring_size: int = RING_SIZE
               ) -> list[Partition]:
    """Contiguous equal ranges (reference allocates this way when a
    topic is configured)."""
    if partition_count <= 0:
        raise ValueError("partition_count must be positive")
    step = ring_size // partition_count
    parts = []
    for i in range(partition_count):
        start = i * step
        stop = ring_size if i == partition_count - 1 else (i + 1) * step
        parts.append(Partition(start, stop, ring_size))
    return parts


def key_slot(key: bytes, ring_size: int = RING_SIZE) -> int:
    if not key:
        return 0
    return int.from_bytes(
        hashlib.md5(key, usedforsecurity=False).digest()[:4],
        "big") % ring_size


def partition_for_key(key: bytes, partitions: list[Partition]) -> Partition:
    slot = key_slot(key, partitions[0].ring_size if partitions else RING_SIZE)
    for p in partitions:
        if p.covers(slot):
            return p
    raise ValueError(f"no partition covers slot {slot}")
