// Native GF(2^8) Reed-Solomon + CRC32C kernels (CPU sidecar).
//
// Role in the framework (SURVEY.md §2 native-code checklist): the reference
// relies on klauspost/reedsolomon's AVX2 assembly (VPSHUFB split tables) and
// Go's SSE4.2 crc32 — this file provides the equivalent native CPU paths:
//   * the honest CPU baseline that bench.py's `vs_baseline` measures against,
//   * the low-latency fallback for point operations (single-needle degraded
//     reads) where a device round-trip isn't worth it.
//
// Technique: gf_mul(c, x) via two 16-entry nibble tables,
//   c*x = T_lo[c][x & 15] ^ T_hi[c][x >> 4],
// vectorized 32 bytes at a time with _mm256_shuffle_epi8 — the same split
// -table trick klauspost's galMulAVX2 assembly uses. Scalar fallback keeps
// the library portable.
//
// Build: make -C seaweedfs_tpu/native   (produces libswtpu.so; loaded via
// ctypes in seaweedfs_tpu/ops/native.py)

#include <cstdint>
#include <cstring>

#if defined(__AVX2__)
#include <immintrin.h>
#endif
#if defined(__SSE4_2__)
#include <nmmintrin.h>
#endif

extern "C" {

// ---------------------------------------------------------------------------
// GF(2^8) tables, poly 0x11D (same field as ops/gf8.py; built once).
// ---------------------------------------------------------------------------
static uint8_t GF_MUL[256][256];
static bool gf_ready = false;

static void gf_init() {
    if (gf_ready) return;
    uint8_t exp[512];
    int log[256] = {0};
    int x = 1;
    for (int i = 0; i < 255; i++) {
        exp[i] = (uint8_t)x;
        log[x] = i;
        x <<= 1;
        if (x & 0x100) x ^= 0x11D;
    }
    for (int i = 255; i < 510; i++) exp[i] = exp[i - 255];
    for (int a = 1; a < 256; a++)
        for (int b = 1; b < 256; b++)
            GF_MUL[a][b] = exp[log[a] + log[b]];
    gf_ready = true;
}

// Split nibble tables for one coefficient.
static void make_tables(uint8_t c, uint8_t lo[16], uint8_t hi[16]) {
    for (int v = 0; v < 16; v++) {
        lo[v] = GF_MUL[c][v];
        hi[v] = GF_MUL[c][v << 4];
    }
}

// out[m][L] ^= or = matrix[m][k] (x) in[k][L]   (GF(2^8) matrix apply)
// Rows are contiguous length-L byte arrays. This is the hot loop the
// reference runs per 256 KB batch (ec_encoder.go:183 enc.Encode).
void rs_apply(const uint8_t* in, uint8_t* out, const uint8_t* matrix,
              int k, int m, int64_t L) {
    gf_init();
    for (int j = 0; j < m; j++) {
        uint8_t* dst = out + (int64_t)j * L;
        std::memset(dst, 0, (size_t)L);
        for (int i = 0; i < k; i++) {
            uint8_t c = matrix[j * k + i];
            if (c == 0) continue;
            const uint8_t* src = in + (int64_t)i * L;
            uint8_t lo[16], hi[16];
            make_tables(c, lo, hi);
            int64_t off = 0;
#if defined(__AVX2__)
            __m256i vlo = _mm256_broadcastsi128_si256(_mm_loadu_si128((const __m128i*)lo));
            __m256i vhi = _mm256_broadcastsi128_si256(_mm_loadu_si128((const __m128i*)hi));
            __m256i mask = _mm256_set1_epi8(0x0F);
            for (; off + 32 <= L; off += 32) {
                __m256i v = _mm256_loadu_si256((const __m256i*)(src + off));
                __m256i l = _mm256_and_si256(v, mask);
                __m256i h = _mm256_and_si256(_mm256_srli_epi64(v, 4), mask);
                __m256i prod = _mm256_xor_si256(_mm256_shuffle_epi8(vlo, l),
                                                _mm256_shuffle_epi8(vhi, h));
                __m256i acc = _mm256_loadu_si256((const __m256i*)(dst + off));
                _mm256_storeu_si256((__m256i*)(dst + off),
                                    _mm256_xor_si256(acc, prod));
            }
#endif
            const uint8_t* mul = GF_MUL[c];
            for (; off < L; off++) dst[off] ^= mul[src[off]];
        }
    }
}

// Batched form: B independent stripes, data [B][k][L] -> out [B][m][L].
void rs_apply_batch(const uint8_t* in, uint8_t* out, const uint8_t* matrix,
                    int k, int m, int64_t L, int64_t B) {
    for (int64_t b = 0; b < B; b++)
        rs_apply(in + b * k * L, out + b * m * L, matrix, k, m, L);
}

// ---------------------------------------------------------------------------
// CRC32C: raw-state update (no init/final xor — the Python wrapper handles
// convention), SSE4.2 hardware instruction when available.
// ---------------------------------------------------------------------------
static uint32_t CRC_TBL[256];
static bool crc_ready = false;

static void crc_init() {
    if (crc_ready) return;
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = i;
        for (int j = 0; j < 8; j++) c = (c >> 1) ^ (0x82F63B78u & (0u - (c & 1)));
        CRC_TBL[i] = c;
    }
    crc_ready = true;
}

uint32_t crc32c_update(uint32_t state, const uint8_t* buf, int64_t n) {
    int64_t off = 0;
#if defined(__SSE4_2__)
    uint64_t s = state;
    for (; off + 8 <= n; off += 8) {
        uint64_t v;
        std::memcpy(&v, buf + off, 8);
        s = _mm_crc32_u64(s, v);
    }
    state = (uint32_t)s;
    for (; off < n; off++) state = _mm_crc32_u8(state, buf[off]);
    return state;
#else
    crc_init();
    uint32_t s32 = state;
    for (; off < n; off++) s32 = (s32 >> 8) ^ CRC_TBL[(s32 ^ buf[off]) & 0xFF];
    return s32;
#endif
}

// Batched CRC over B equal-length rows -> states[B] (scrub fallback path).
void crc32c_batch(const uint8_t* rows, int64_t L, int64_t B,
                  uint32_t init, uint32_t* states) {
    for (int64_t b = 0; b < B; b++)
        states[b] = crc32c_update(init, rows + b * L, L);
}

int native_features() {
    int f = 0;
#if defined(__AVX2__)
    f |= 1;
#endif
#if defined(__SSE4_2__)
    f |= 2;
#endif
    return f;
}

}  // extern "C"
