"""Metadata-event notification queues (reference weed/notification).

The filer publishes every namespace mutation (EventNotification) to an
optional message queue besides its own meta log (filer_notify.go:20-66).
The reference ships kafka / AWS SQS / GCP PubSub / GoCDK backends behind
`notification.toml`; this package provides the same seam with two
built-in queues (in-memory fan-out and a durable log file) and gated
stubs for the cloud brokers (their SDKs aren't in the image).
"""

from .queues import (LogFileQueue, MemoryQueue, MessageQueue, open_queue)

__all__ = ["MessageQueue", "MemoryQueue", "LogFileQueue", "open_queue"]
