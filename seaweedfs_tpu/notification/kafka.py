"""Kafka-protocol notification queue: the real wire format, no SDK.

Reference: weed/notification/kafka/kafka_queue.go publishes filer events
to Kafka via the sarama SDK. This module speaks the Kafka protocol
directly — ApiVersions (key 18), Metadata (key 3 v1), and Produce
(key 0 v3) with magic-v2 RecordBatches, including the batch's CRC32C
(Castagnoli, computed by ops/crc32c like every needle checksum) — so
events land on any Kafka 0.11+ broker, and offline on
utils/mini_kafka.MiniKafka which decodes and CRC-verifies the batches.

Produce-only, like the reference's queue (consumers are downstream
systems, not seaweed's concern).
"""

from __future__ import annotations

import socket
import struct
import threading
import time

from ..ops.crc32c import crc32c
from ..pb import filer_pb2 as fpb
from ..utils.log import logger
from .queues import MessageQueue

log = logger("notification.kafka")

API_PRODUCE = 0
API_METADATA = 3
API_VERSIONS = 18


# -- primitive wire encoding -------------------------------------------------

def _str(s: "str | None") -> bytes:
    if s is None:
        return struct.pack(">h", -1)
    b = s.encode()
    return struct.pack(">h", len(b)) + b


def _bytes(b: "bytes | None") -> bytes:
    if b is None:
        return struct.pack(">i", -1)
    return struct.pack(">i", len(b)) + b


def _varint(n: int) -> bytes:
    """Zigzag varint (record fields inside a v2 batch)."""
    z = (n << 1) ^ (n >> 63)
    out = bytearray()
    while True:
        if z & ~0x7F:
            out.append((z & 0x7F) | 0x80)
            z >>= 7
        else:
            out.append(z)
            return bytes(out)


def read_varint(buf: bytes, pos: int) -> "tuple[int, int]":
    shift = z = 0
    while True:
        b = buf[pos]
        pos += 1
        z |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    return (z >> 1) ^ -(z & 1), pos


def encode_record(key: bytes, value: bytes, offset_delta: int) -> bytes:
    body = (b"\x00"                       # attributes
            + _varint(0)                  # timestampDelta
            + _varint(offset_delta)
            + _varint(len(key)) + key
            + _varint(len(value)) + value
            + _varint(0))                 # headers count
    return _varint(len(body)) + body


def encode_record_batch(records: "list[tuple[bytes, bytes]]") -> bytes:
    """Magic-v2 RecordBatch with a real Castagnoli CRC."""
    now_ms = int(time.time() * 1000)
    recs = b"".join(encode_record(k, v, i)
                    for i, (k, v) in enumerate(records))
    after_crc = (struct.pack(">hiqqqhi",
                             0,                    # attributes
                             len(records) - 1,     # lastOffsetDelta
                             now_ms, now_ms,       # first/max timestamp
                             -1, -1, -1)           # producerId/Epoch/baseSeq
                 + struct.pack(">i", len(records)) + recs)
    crc = crc32c(after_crc) & 0xFFFFFFFF
    # the v2 CRC is the RAW Castagnoli state (no final-xor convention
    # difference: kafka uses the standard crc32c, same as ours)
    batch_tail = b"\x02" + struct.pack(">I", crc) + after_crc  # magic + crc
    head = struct.pack(">qi", 0, len(batch_tail) + 4)  # baseOffset, length
    return head + struct.pack(">i", -1) + batch_tail   # partitionLeaderEpoch


class _Conn:
    def __init__(self, host: str, port: int, client_id: str,
                 timeout: float = 10.0):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.rf = self.sock.makefile("rb")
        self.client_id = client_id
        self._corr = 0

    def request(self, api_key: int, api_version: int, body: bytes) -> bytes:
        self._corr += 1
        hdr = (struct.pack(">hhi", api_key, api_version, self._corr)
               + _str(self.client_id))
        msg = hdr + body
        self.sock.sendall(struct.pack(">i", len(msg)) + msg)
        raw = self.rf.read(4)
        if len(raw) < 4:
            raise ConnectionError("kafka broker closed connection")
        (n,) = struct.unpack(">i", raw)
        resp = self.rf.read(n)
        if len(resp) < n:
            # died mid-response: surface as the retryable class
            raise ConnectionError("kafka broker truncated response")
        (corr,) = struct.unpack(">i", resp[:4])
        if corr != self._corr:
            raise OSError(f"kafka correlation mismatch {corr}!={self._corr}")
        return resp[4:]

    def close(self) -> None:
        try:
            self.rf.close()
            self.sock.close()
        except OSError:
            pass


class KafkaQueue(MessageQueue):
    """Filer event notification onto a Kafka topic (kafka_queue.go)."""

    name = "kafka"

    def __init__(self, address: str, topic: str = "seaweedfs_filer"):
        self.topic = topic
        host, _, port = address.rpartition(":")
        self._host = host or address
        self._port = int(port) if port.isdigit() else 9092
        self._local = threading.local()
        self._conns: list[_Conn] = []  # every thread's conn, for close()
        self._conns_lock = threading.Lock()
        # handshake once: ApiVersions + Metadata prove the peer speaks
        # kafka and auto-creates/locates the topic
        c = self._conn()
        c.request(API_VERSIONS, 0, b"")
        c.request(API_METADATA, 1, struct.pack(">i", 1) + _str(self.topic))

    def _conn(self) -> _Conn:
        c = getattr(self._local, "conn", None)
        if c is None:
            c = self._local.conn = _Conn(self._host, self._port,
                                         "seaweedfs-tpu")
            with self._conns_lock:
                self._conns.append(c)
        return c

    def send(self, key: str, ev: fpb.EventNotification) -> None:
        batch = encode_record_batch([(key.encode(),
                                      ev.SerializeToString())])
        body = (_str(None)                     # transactional_id
                + struct.pack(">hi", 1, 10_000)  # acks=1, timeout
                + struct.pack(">i", 1)           # 1 topic
                + _str(self.topic)
                + struct.pack(">i", 1)           # 1 partition
                + struct.pack(">i", 0)           # partition 0
                + _bytes(batch))
        try:
            resp = self._conn().request(API_PRODUCE, 3, body)
        except (ConnectionError, OSError):
            # one reconnect (broker restarted between events)
            self._conn().close()
            self._local.conn = None
            resp = self._conn().request(API_PRODUCE, 3, body)
        # response: [topics][partitions] partition(int32) error(int16) ...
        # error code sits right after the first partition index (topic
        # length on the wire is UTF-8 BYTES, not characters)
        pos = 4 + 2 + len(self.topic.encode()) + 4 + 4
        (err,) = struct.unpack(">h", resp[pos:pos + 2])
        if err:
            raise OSError(f"kafka produce error code {err}")

    def close(self) -> None:
        with self._conns_lock:
            conns, self._conns = self._conns, []
        for c in conns:  # every sender thread's socket, not just ours
            c.close()
        self._local.conn = None
