"""MessageQueue implementations.

Interface mirrors reference notification/configuration.go
(`MessageQueue.SendMessage(key, message)`); messages are
(key=full path, value=EventNotification) pairs.
"""

from __future__ import annotations

import os
import struct
import threading
from typing import Callable, Iterator

from ..pb import filer_pb2 as fpb
from ..utils.log import logger

log = logger("notification")


class MessageQueue:
    name = "abstract"

    def send(self, key: str, ev: fpb.EventNotification) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class MemoryQueue(MessageQueue):
    """In-process fan-out to subscribers (test/dev; plays the role the
    reference's gocdk mempubsub plays)."""

    name = "memory"

    def __init__(self):
        self._subs: list[Callable[[str, fpb.EventNotification], None]] = []
        self._lock = threading.Lock()

    def subscribe(self, fn: Callable[[str, fpb.EventNotification], None]) -> None:
        with self._lock:
            self._subs.append(fn)

    def send(self, key: str, ev: fpb.EventNotification) -> None:
        with self._lock:
            subs = list(self._subs)
        for fn in subs:
            try:
                fn(key, ev)
            except Exception as e:  # noqa: BLE001
                log.warning("subscriber error for %s: %s", key, e)


class LogFileQueue(MessageQueue):
    """Durable length-prefixed record log; `weed filer.replicate` style
    consumers read from an offset (the file-backed analogue of a broker
    topic — same framing as the filer meta log)."""

    name = "logfile"

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "ab")
        self._lock = threading.Lock()

    def send(self, key: str, ev: fpb.EventNotification) -> None:
        rec = fpb.SubscribeMetadataResponse(directory=key)
        rec.event_notification.CopyFrom(ev)
        blob = rec.SerializeToString()
        with self._lock:
            self._f.write(struct.pack("<I", len(blob)))
            self._f.write(blob)
            self._f.flush()

    def read(self, offset: int = 0
             ) -> Iterator[tuple[int, fpb.SubscribeMetadataResponse]]:
        """Yield (next_offset, record) from byte offset."""
        with open(self.path, "rb") as f:
            f.seek(offset)
            while True:
                hdr = f.read(4)
                if len(hdr) < 4:
                    return
                (n,) = struct.unpack("<I", hdr)
                blob = f.read(n)
                if len(blob) < n:
                    return
                rec = fpb.SubscribeMetadataResponse()
                rec.ParseFromString(blob)
                yield f.tell(), rec

    def close(self) -> None:
        with self._lock:
            self._f.close()


def open_queue(spec: str) -> MessageQueue:
    """spec: 'memory', 'logfile:/path', or a gated broker name.
    Reference notification.toml picks one enabled backend the same way."""
    kind, _, arg = spec.partition(":")
    if kind == "memory":
        return MemoryQueue()
    if kind == "logfile":
        return LogFileQueue(arg or "notification.log")
    if kind == "mq":
        addr, _, rest = arg.partition("/")
        ns, _, topic = rest.partition("/")
        return MqQueue(addr, namespace=ns or "notifications",
                       topic=topic or "filer")
    if kind == "kafka":
        # the real Kafka wire protocol, no SDK: 'kafka:host:port/topic'
        from .kafka import KafkaQueue
        addr, _, topic = arg.partition("/")
        return KafkaQueue(addr, topic=topic or "seaweedfs_filer")
    if kind in ("aws_sqs", "gcp_pub_sub", "gocdk_pub_sub"):
        raise RuntimeError(
            f"notification backend {kind!r} requires its broker SDK, "
            "which is not in this image (reference gates these behind "
            "notification.toml the same way)")
    raise ValueError(f"unknown notification queue {spec!r}")


class MqQueue(MessageQueue):
    """Publish metadata events into the framework's OWN message queue
    (the reference fans out to Kafka/SQS/PubSub via notification.toml;
    here the built-in broker plays that role — spec 'mq:host:port' or
    'mq:host:port/namespace/topic'). Lazy-connects and drops events with a
    warning while the broker is down, like the reference's best-effort
    notifiers."""

    name = "mq"

    def __init__(self, broker_address: str, namespace: str = "notifications",
                 topic: str = "filer"):
        self.broker_address = broker_address
        self.namespace, self.topic = namespace, topic
        self._pub = None
        self._lock = threading.Lock()

    def _publisher(self):
        if self._pub is None:
            from ..mq.client import Publisher
            self._pub = Publisher(self.broker_address, self.namespace,
                                  self.topic)
        return self._pub

    def send(self, key: str, ev: fpb.EventNotification) -> None:
        with self._lock:
            try:
                self._publisher().publish(key.encode(),
                                          ev.SerializeToString())
            except Exception as e:  # noqa: BLE001 — best-effort notifier
                self._pub = None
                log.warning("mq notify %s: %s", key, e)

    def close(self) -> None:
        with self._lock:
            if self._pub is not None:
                try:
                    self._pub.close()
                except Exception as e:  # noqa: BLE001
                    log.debug("notification publisher close failed: %s", e)
                self._pub = None
