"""Pluggable ErasureCoder interface — the north-star seam.

BASELINE.json: "...gated behind a new pluggable ErasureCoder interface so the
default [CPU] path is untouched". Implementations:

* ``NumpyCoder`` — pure-numpy GF tables; correctness oracle, slow.
* ``NativeCoder`` — C++ sidecar (seaweedfs_tpu/native), AVX2 PSHUFB split
  tables: the faithful stand-in for klauspost/reedsolomon's asm, used as the
  CPU baseline that `vs_baseline` is measured against.
* ``JaxCoder`` — the TPU path (ops/rs_jax bit-matmul; Pallas kernel when
  available), batching [B, d, L] stripe tensors through the device.

All coders operate on uint8 arrays shaped [d, L] / [B, d, L] and are
stateless w.r.t. data; geometry is fixed per instance.
"""

from __future__ import annotations

import abc

import numpy as np

from . import gf8


class ErasureCoder(abc.ABC):
    #: True when encode() returns an async handle that materializes on
    #: np.asarray (device coders); the streaming pipeline double-buffers
    #: those and takes a zero-copy synchronous fast path for the rest.
    async_dispatch = False
    #: Erasure codec this coder implements — persisted into the .vif seal
    #: so rebuild always decodes with the codec that encoded. Plain RS
    #: coders differ only in compute backend; ops/piggyback.py overrides.
    codec = "rs"

    def __init__(self, d: int, p: int):
        if d <= 0 or p <= 0 or d + p > 256:
            raise ValueError(f"invalid RS geometry ({d},{p})")
        self.d = d
        self.p = p
        self.n = d + p

    @abc.abstractmethod
    def encode(self, data: np.ndarray) -> np.ndarray:
        """data [..., d, L] uint8 -> parity [..., p, L] uint8."""

    @abc.abstractmethod
    def reconstruct(self, survivors: np.ndarray, present: tuple[int, ...],
                    wanted: tuple[int, ...]) -> np.ndarray:
        """survivors [..., d, L] = shards sorted(present)[:d] -> [..., |wanted|, L]."""

    def repair_plan(self, present: "tuple[int, ...]",
                    wanted: "tuple[int, ...]", shard_size: int,
                    ) -> "list[tuple[int, int, int]] | None":
        """Byte ranges [(shard_id, offset, length), ...] of survivors
        sufficient to rebuild `wanted`, or None when nothing beats the
        trivial plan (read d full survivors). Plain RS has no sub-shard
        structure, so the base answer is always None; repair-efficient
        codecs (ops/piggyback.py) override."""
        return None

    def verify(self, shards: np.ndarray) -> bool:
        """shards [..., n, L]: recompute parity from data rows and compare."""
        data = shards[..., : self.d, :]
        parity = shards[..., self.d:, :]
        return bool(np.array_equal(np.asarray(self.encode(data)), np.asarray(parity)))


class NumpyCoder(ErasureCoder):
    def encode(self, data: np.ndarray) -> np.ndarray:
        data = np.asarray(data, dtype=np.uint8)
        if data.ndim == 2:
            return gf8.np_encode(data, self.p)
        return np.stack([gf8.np_encode(b, self.p) for b in data])

    def reconstruct(self, survivors, present, wanted):
        survivors = np.asarray(survivors, dtype=np.uint8)
        rec = gf8.decode_matrix(self.d, self.p, list(present))[list(wanted), :]
        if survivors.ndim == 2:
            return gf8.np_gf_apply(rec, survivors)
        return np.stack([gf8.np_gf_apply(rec, b) for b in survivors])


class JaxCoder(ErasureCoder):
    """Device coder. Accepts numpy or jax arrays; returns device arrays
    (callers `np.asarray` when they need host bytes).

    On a real TPU backend the Pallas kernel (ops/rs_pallas.py) carries the
    hot path — unpack/matmul/pack pinned in VMEM; elsewhere (CPU tests,
    GPU) it falls back to the XLA einsum formulation (ops/rs_jax.py).
    """

    async_dispatch = True

    def __init__(self, d: int, p: int, use_pallas: "bool | None" = None):
        super().__init__(d, p)
        if use_pallas is None:
            from . import rs_pallas
            use_pallas = rs_pallas.available()
        self.use_pallas = use_pallas
        self._interpret = False  # PallasCoder flips this for CPU tests

    def encode(self, data):
        if self.use_pallas:
            from . import rs_pallas
            x, squeeze = _as_batch(data)
            out = rs_pallas.encode_jit(x, self.d, self.p,
                                       interpret=self._interpret)
            return out[0] if squeeze else out
        from . import rs_jax
        return rs_jax.encode_jit(data, self.d, self.p)

    def reconstruct(self, survivors, present, wanted):
        if self.use_pallas:
            from . import rs_pallas
            x, squeeze = _as_batch(survivors)
            out = rs_pallas.reconstruct_jit(
                x, tuple(sorted(present)), tuple(wanted), self.d, self.p,
                interpret=self._interpret)
            return out[0] if squeeze else out
        from . import rs_jax
        return rs_jax.reconstruct_jit(
            survivors, tuple(sorted(present)), tuple(wanted), self.d, self.p)


def _as_batch(arr):
    """Pallas kernels take [B, k, C]; promote [k, C] and remember to squeeze."""
    import jax.numpy as jnp
    arr = jnp.asarray(arr)
    if arr.ndim == 2:
        return arr[None], True
    return arr, False


class PallasCoder(JaxCoder):
    """Force the Pallas path; interpreter mode off-TPU so tests cover the
    kernel logic everywhere."""

    def __init__(self, d: int, p: int):
        from . import rs_pallas
        super().__init__(d, p, use_pallas=True)
        self._interpret = not rs_pallas.available()


_REGISTRY = {"numpy": NumpyCoder, "jax": JaxCoder, "pallas": PallasCoder}

# backend names double as the plain-RS codec: NumpyCoder is the host
# oracle, so "rs" resolves there (repair costing, codec enumeration)
_REGISTRY["rs"] = NumpyCoder

# self-registering implementations live in modules nobody has imported
# yet when a CLI (or a .vif read) asks for them by name; the bool marks
# entries that register a NEW erasure codec (vs just a compute backend),
# so codec enumeration doesn't drag in jax for a help string
_LAZY = {
    "native": ("seaweedfs_tpu.ops.native", False),
    "mesh": ("seaweedfs_tpu.parallel.pipeline", False),
    "piggyback": ("seaweedfs_tpu.ops.piggyback", True),
    "msr": ("seaweedfs_tpu.ops.product_matrix", True),
}


def _lazy_load(name: str) -> None:
    mod, _ = _LAZY[name]
    __import__(mod, fromlist=["_"])


def get_coder(name: str, d: int, p: int) -> ErasureCoder:
    if name not in _REGISTRY and name in _LAZY:
        _lazy_load(name)
    try:
        cls = _REGISTRY[name]
    except KeyError:
        raise ValueError(f"unknown coder {name!r}; have {sorted(_REGISTRY)}") from None
    return cls(d, p)


def register_coder(name: str, cls) -> None:
    _REGISTRY[name] = cls


def registered_codecs() -> "list[str]":
    """Erasure CODEC names (one per wire/disk format, not per compute
    backend) — drives shell help/validation so a new registered codec
    shows up everywhere without hand-edited name lists. Entries may be
    classes or factory callables (mesh); factories without a `codec`
    attribute are plain-RS backends."""
    for name, (_, is_codec) in _LAZY.items():
        if is_codec and name not in _REGISTRY:
            try:
                _lazy_load(name)
            except Exception:  # noqa: BLE001  # swtpu-lint: disable=silent-except (enumeration must list what IS loadable, not fail on what isn't)
                pass
    return sorted({getattr(cls, "codec", "rs")
                   for cls in _REGISTRY.values()})


def codec_coder(codec: str, d: int, p: int,
                backend: str = "numpy") -> ErasureCoder:
    """Construct the coder for an erasure `codec` on a compute
    `backend`. Plain "rs" is the backend coder itself; layered codecs
    (piggyback, msr) wrap the backend as their inner GF engine."""
    if not codec or codec == "rs":
        return get_coder(backend if backend != "auto" else "numpy", d, p)
    if codec not in _REGISTRY and codec in _LAZY:
        _lazy_load(codec)
    cls = _REGISTRY.get(codec)
    if cls is None or getattr(cls, "codec", "rs") != codec:
        raise ValueError(
            f"unknown erasure codec {codec!r}; have {registered_codecs()}")
    # pass the backend only when the constructor takes one — probing via
    # except TypeError would also swallow TypeErrors raised INSIDE the
    # constructor and silently drop the requested backend
    import inspect
    try:
        takes_backend = "backend" in inspect.signature(cls).parameters
    except (TypeError, ValueError):  # uninspectable callable
        takes_backend = False
    if takes_backend:
        return cls(d, p, backend=backend)
    return cls(d, p)


def repair_read_bytes(codec: str, d: int, p: int, missing, shard_size: int,
                      ) -> int:
    """Survivor bytes a rebuild of `missing` must read under `codec` —
    the repair planner's byte-costing primitive. Resolves the codec
    through the registry (numpy inner backend: no data touches it, the
    coder is consulted purely for plan geometry), so any registered
    codec costs correctly without editing this helper."""
    missing = sorted(set(missing))
    coder = codec_coder(codec or "rs", d, p)
    present = tuple(i for i in range(d + p) if i not in missing)
    plan = coder.repair_plan(present, tuple(missing), shard_size)
    if plan is None:
        return d * shard_size
    return sum(ln for _, _, ln in plan)
