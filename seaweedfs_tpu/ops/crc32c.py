"""CRC32-Castagnoli: host oracle + batched device scrub kernel.

The reference verifies a CRC32C per needle on every read and during scrub
(reference: weed/storage/needle/crc.go:13 ``crc32.MakeTable(crc32.Castagnoli)``,
weed/storage/volume_checking.go:91 ``verifyNeedleIntegrity``). The stdlib Go
implementation is SSE4.2 hardware CRC; our host fallback is a table loop (the
C++ sidecar in seaweedfs_tpu/native provides the hardware version), and the
*batched* path — millions of needles scrubbed at once, BASELINE config 4 —
runs on TPU using the fact that CRC is GF(2)-affine in the message bits:

    state' = A @ state  ^  D @ byte_bits      (per byte, over GF(2))

so K bytes fold into one [32, 32] state matrix S_K = A^K and one [32, 8K]
injection matrix C_K, and a batch of B equal-length blocks is two int8
matmuls. Variable needle lengths are handled by LEFT-padding with zeros:
with a zero initial state, leading zero bytes leave the state unchanged, and
the true init (0xFFFFFFFF) is restored afterwards with the length-dependent
affine correction  crc_raw(m, I) = crc_raw(pad||m, 0) ^ A^len @ I,
computed on host from precomputed A^(2^j) powers (a batched 32-bit matvec).
"""

from __future__ import annotations

import functools

import numpy as np

CASTAGNOLI = 0x82F63B78  # reversed (LSB-first) representation
_INIT = 0xFFFFFFFF


@functools.lru_cache(maxsize=1)
def _table() -> np.ndarray:
    t = np.zeros(256, dtype=np.uint32)
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ (CASTAGNOLI if (c & 1) else 0)
        t[i] = c
    return t


_native_update = None  # lazily resolved: False = unavailable, else C fn


def _soft_crc32c(data: bytes | np.ndarray, value: int = 0) -> int:
    t = _table()
    s = value ^ _INIT
    buf = bytes(data) if isinstance(data, (bytes, bytearray, memoryview)) else np.asarray(data, dtype=np.uint8).tobytes()
    for b in buf:
        s = (s >> 8) ^ int(t[(s ^ b) & 0xFF])
    return s ^ _INIT


def crc32c(data: bytes | np.ndarray, value: int = 0) -> int:
    """Standard CRC32C (init/final xor 0xFFFFFFFF); `value` chains calls.

    Dispatches to the C++ sidecar's SSE4.2 hardware loop when it loads
    (~1000x the table loop — this sits on every needle read and write),
    with the pure-Python table loop as the fallback oracle.
    """
    global _native_update
    if _native_update is None:
        try:
            from . import native
            lib = native.load()
            _native_update = lib.crc32c_update if lib is not None else False
        except Exception:  # pragma: no cover - toolchain-less env
            _native_update = False
    if _native_update is False:
        return _soft_crc32c(data, value)
    if isinstance(data, np.ndarray):
        arr = np.ascontiguousarray(data, dtype=np.uint8)
        return _native_update(value ^ _INIT, arr.ctypes.data, arr.size) ^ _INIT
    buf = data if isinstance(data, bytes) else bytes(data)
    return _native_update(value ^ _INIT, buf, len(buf)) ^ _INIT


# ---------------------------------------------------------------------------
# GF(2)-linear formulation. Bit convention: state bit i = (crc >> i) & 1,
# message bits LSB-first per byte — identical to ops/rs_jax.unpack_bits.
# ---------------------------------------------------------------------------

def _byte_step_matrices() -> tuple[np.ndarray, np.ndarray]:
    """A [32,32]: state map per byte; D [32,8]: byte-bit injection."""
    t = _table()
    a = np.zeros((32, 32), dtype=np.uint8)
    for i in range(32):
        s = 1 << i
        out = (s >> 8) ^ (int(t[s & 0xFF]))
        for j in range(32):
            a[j, i] = (out >> j) & 1
    d = np.zeros((32, 8), dtype=np.uint8)
    for i in range(8):
        out = int(t[1 << i])
        for j in range(32):
            d[j, i] = (out >> j) & 1
    return a, d


@functools.lru_cache(maxsize=1)
def _a_d() -> tuple[np.ndarray, np.ndarray]:
    return _byte_step_matrices()


def _m2mul(x: np.ndarray, y: np.ndarray) -> np.ndarray:
    return (x.astype(np.int32) @ y.astype(np.int32) & 1).astype(np.uint8)


@functools.lru_cache(maxsize=64)
def chunk_matrices(k: int) -> tuple[np.ndarray, np.ndarray]:
    """(S_K [32,32], C_K [32,8K]) folding K message bytes into the state.

    state_after = S_K @ state ^ C_K @ bits(chunk), chunk byte 0 first,
    C_K columns [8*i : 8*i+8] belong to byte i (LSB-first).
    """
    a, d = _a_d()
    s = np.eye(32, dtype=np.uint8)
    cols = []
    # byte i passes through A another (k-1-i) times after injection
    powers = [np.eye(32, dtype=np.uint8)]
    for _ in range(k):
        powers.append(_m2mul(a, powers[-1]))
    for i in range(k):
        cols.append(_m2mul(powers[k - 1 - i], d))
    c = np.concatenate(cols, axis=1) if cols else np.zeros((32, 0), np.uint8)
    return powers[k], c


@functools.lru_cache(maxsize=1)
def _a_pow2() -> list[np.ndarray]:
    """A^(2^j) for j in 0..47 as uint32 column bitmasks for fast host matvec."""
    a, _ = _a_d()
    mats = []
    cur = a
    for _ in range(48):
        # column c as uint32 bitmask
        mask = np.zeros(32, dtype=np.uint32)
        for c in range(32):
            mask[c] = int.from_bytes(np.packbits(cur[:, c], bitorder="little").tobytes(), "little")
        mats.append(mask)
        cur = _m2mul(cur, cur)
    return mats


def _matvec_u32(colmask: np.ndarray, vec: np.ndarray) -> np.ndarray:
    """Apply 32x32 GF(2) matrix (uint32 column masks) to batched uint32 vecs."""
    out = np.zeros_like(vec)
    for c in range(32):
        bit = (vec >> np.uint32(c)) & np.uint32(1)
        out ^= colmask[c] * bit
    return out


def zero_prefix_correction(lengths: np.ndarray) -> np.ndarray:
    """A^len @ INIT for a batch of lengths -> uint32 raw-state corrections.

    crc_raw(msg, init=0xFFFFFFFF) = device_raw(zeropad||msg) ^ correction(len).
    """
    lengths = np.asarray(lengths, dtype=np.int64)
    vec = np.full(lengths.shape, _INIT, dtype=np.uint32)
    mats = _a_pow2()
    for j in range(48):
        bit = (lengths >> j) & 1
        if not bit.any():
            continue
        applied = _matvec_u32(mats[j], vec)
        vec = np.where(bit.astype(bool), applied, vec)
    return vec


def finalize(raw_states: np.ndarray, lengths: np.ndarray) -> np.ndarray:
    """Combine device raw states (init-0, left-padded) into true CRC32C values."""
    return (np.asarray(raw_states, dtype=np.uint32)
            ^ zero_prefix_correction(lengths)
            ^ np.uint32(_INIT))


# ---------------------------------------------------------------------------
# Device kernel: batched CRC over [B, L] blocks (L % K == 0), LEFT-padded.
# ---------------------------------------------------------------------------

def device_crc_states(blocks, chunk: int = 512):
    """blocks [B, L] uint8 (L multiple of `chunk`) -> raw states [B] uint32.

    Pure-JAX scan over L/chunk steps; each step is two bit-matmuls batched
    over B. Intended to be wrapped in jit (and shard_mapped over a mesh for
    the distributed scrub — see parallel/pipeline.py).
    """
    import jax
    import jax.numpy as jnp

    from .rs_jax import unpack_bits

    b, l = blocks.shape
    assert l % chunk == 0, (l, chunk)
    s_k, c_k = chunk_matrices(chunk)
    s_kt = jnp.asarray(s_k.T, dtype=jnp.int8)
    c_kt = jnp.asarray(c_k.T, dtype=jnp.int8)

    steps = blocks.reshape(b, l // chunk, chunk).transpose(1, 0, 2)  # [T,B,K]

    def step(state, chunk_bytes):
        bits = unpack_bits(chunk_bytes[..., None])[..., 0]  # [B, 8K] byte-major
        nxt = (
            jnp.einsum("bi,ij->bj", state, s_kt, preferred_element_type=jnp.int32)
            + jnp.einsum("bk,kj->bj", bits, c_kt, preferred_element_type=jnp.int32)
        ) & 1
        return nxt.astype(jnp.int8), None

    if steps.shape[0] == 0:
        # no chunks: state stays zero (plain zeros are fine; scan never runs)
        state = jnp.zeros((b, 32), dtype=jnp.int8)
    else:
        # derive the zero init from the input so it carries the same
        # varying-axes marking under shard_map (scan needs matching carry types)
        init = jnp.tile((steps[0, :, :1] & 0).astype(jnp.int8), (1, 32))
        state, _ = jax.lax.scan(step, init, steps)
    weights = jnp.asarray([np.uint32(1 << i) for i in range(32)], dtype=jnp.uint32)
    return jnp.sum(state.astype(jnp.uint32) * weights, axis=1)
