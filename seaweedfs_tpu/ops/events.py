"""Cluster event journal: a bounded ring of structured state transitions.

PR 1 made recovery *countable* (metrics) and PR 2 made requests
*traceable* (spans), but neither answers the operator's follow-up to a
bad health verdict: "what CHANGED, and when?". This module is the
system's flight recorder: every interesting control-plane transition —
node join/leave, volume grow/readonly, EC encode/rebuild start/finish,
circuit breaker open/close, health severity changes — lands here as one
structured event, correlated with the active trace so an event found at
/debug/events pivots straight into /debug/traces?trace_id=...

Design mirrors tracing/trace.py's TraceBuffer deliberately:

* per-process ring buffer (SWTPU_EVENT_BUFFER events, default 4096)
  bounds memory no matter the event rate, counting what it evicts;
* events are plain dicts so /debug/events is a json.dumps away;
* every event carries a process-monotonic `seq`, so pollers tail the
  journal with /debug/events?since=<last_seq> without missing or
  re-reading anything the ring still holds;
* `emit()` must never break the caller: journal failures are swallowed
  the same way metrics failures are in the hot paths.

The Facebook warehouse study (PAPERS arXiv:1309.0186) found that repair
load and at-risk stripe population move on *minute* timescales after a
node event — which is exactly the correlation this journal exists to
expose: a `node.leave` followed by `health.severity` transitions and,
later, `ec.rebuild.finish` + recovery back to OK.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from ..utils.env import env_int as _env_int
from ..utils.log import logger

log = logger("events")

# severity levels an event may carry (informational; the health plane's
# item severities are attrs on health.* events, not event severities)
INFO, WARN, ERROR = "info", "warn", "error"


class EventJournal:
    """Bounded per-process store of structured cluster events."""

    def __init__(self, capacity: int | None = None):
        self.capacity = capacity or _env_int("SWTPU_EVENT_BUFFER", 4096)
        self._events: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._seq = 0
        self.dropped = 0

    def emit(self, etype: str, severity: str = INFO, **attrs) -> dict:
        """Record one event. Attrs are flattened into the event dict's
        `attrs`; the active trace (if any) is captured for correlation,
        and the event is mirrored onto the active span so a trace found
        first self-explains too (event<->trace linking both ways)."""
        trace_id = span_id = ""
        try:
            from .. import tracing
            trace_id, span_id = tracing.current_ids()
            tracing.add_event(etype, **attrs)
        except Exception:  # noqa: BLE001  # swtpu-lint: disable=silent-except (correlation must never break emit)
            pass
        with self._lock:
            self._seq += 1
            ev = {"seq": self._seq, "ts_ns": time.time_ns(),
                  "type": etype, "severity": severity,
                  "trace_id": trace_id, "span_id": span_id,
                  "attrs": attrs}
            if len(self._events) == self.capacity:
                self.dropped += 1
            self._events.append(ev)
        return ev

    def snapshot(self, since: int = 0, etype: str = "",
                 limit: int = 500) -> list[dict]:
        """Matching events in seq order (ascending). `since` excludes
        events with seq <= since (tail-polling cursor); `etype` is a
        prefix match so `breaker` catches breaker.open/close alike.
        When more than `limit` match, the NEWEST `limit` are returned —
        a fresh reader wants the recent past, a tailing reader's
        `since` keeps it below the limit anyway."""
        with self._lock:
            events = list(self._events)
        out = [ev for ev in events
               if ev["seq"] > since
               and (not etype or ev["type"].startswith(etype))]
        return out[-limit:] if limit else out

    @property
    def last_seq(self) -> int:
        with self._lock:
            return self._seq

    def clear(self) -> None:
        """Test isolation only — operators get a ring, not an eraser."""
        with self._lock:
            self._events.clear()
            self.dropped = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)


JOURNAL = EventJournal()


def emit(etype: str, severity: str = INFO, **attrs) -> None:
    """Module-level convenience: record onto the process journal,
    swallowing ANY failure — an event must never break the operation
    that emitted it (same contract as metrics)."""
    try:
        JOURNAL.emit(etype, severity=severity, **attrs)
    except Exception as e:  # noqa: BLE001
        try:
            log.warning("event emit %s failed: %s", etype, e)
        except Exception:  # noqa: BLE001  # swtpu-lint: disable=silent-except (last resort: logging itself failed)
            pass


def debug_events_payload(query: dict) -> dict:
    """The shared /debug/events response body: JSON events filterable by
    ?since=<seq>&type=<prefix>&limit=N (served by the master, volume,
    filer, and S3 status servers; each process journals its own plane)."""
    try:
        since = max(0, int(query.get("since") or 0))
    except ValueError:
        since = 0
    etype = (query.get("type") or "").strip()
    try:
        limit = max(0, min(int(query.get("limit") or 500), 5000))
    except ValueError:
        limit = 500
    events = JOURNAL.snapshot(since=since, etype=etype, limit=limit)
    return {"count": len(events), "buffered": len(JOURNAL),
            "dropped": JOURNAL.dropped, "last_seq": JOURNAL.last_seq,
            "events": events}
