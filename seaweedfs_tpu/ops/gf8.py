"""GF(2^8) arithmetic and Reed-Solomon matrix construction (host side, numpy).

This is the mathematical core of the erasure-coding plane. The reference
(ZTO-Express/seaweedfs) delegates this to the vendored klauspost/reedsolomon
Go library (reference: weed/storage/erasure_coding/ec_encoder.go:202
``reedsolomon.New(DataShardsCount, ParityShardsCount)``). We re-derive the same
construction from first principles so shards produced by either implementation
interoperate:

* field GF(2^8) with the primitive polynomial x^8+x^4+x^3+x^2+1 (0x11D) and
  generator 2 — the same field used by klauspost/reedsolomon and
  Backblaze/JavaReedSolomon;
* systematic encode matrix built from a Vandermonde matrix V[r,c] = r^c whose
  top k-by-k block is inverted and multiplied through, so the first k rows
  become the identity (klauspost ``buildMatrix``).

The TPU insight (everything downstream builds on this): multiplication by a
*constant* c in GF(2^8) is linear over GF(2), i.e. an 8x8 bit-matrix M(c).
Hence RS encode — parity_j = XOR_i g[j,i] * data_i — expands to a single
binary matrix multiply

    parity_bits[8p, L] = B[8p, 8d] @ data_bits[8d, L]  (mod 2)

which the TPU MXU executes as an int8 matmul followed by ``& 1``. No gathers,
no lookup tables on device. See ops/rs_jax.py / ops/rs_pallas.py.
"""

from __future__ import annotations

import functools

import numpy as np

# Primitive polynomial for GF(2^8): x^8 + x^4 + x^3 + x^2 + 1.
POLY = 0x11D
FIELD = 256


def _build_tables() -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """exp/log tables for generator 2 and the full 256x256 multiply table."""
    exp = np.zeros(512, dtype=np.uint8)
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= POLY
    exp[255:510] = exp[0:255]  # wraparound so exp[a+b] works without mod
    # mul[a, b] via log/exp; row/col 0 are zero.
    la = log[np.arange(256)]
    mul = np.zeros((256, 256), dtype=np.uint8)
    nz = np.arange(1, 256)
    mul[np.ix_(nz, nz)] = exp[(la[nz][:, None] + la[nz][None, :]) % 255]
    return exp, log, mul


GF_EXP, GF_LOG, GF_MUL = _build_tables()


def gf_mul(a: int, b: int) -> int:
    return int(GF_MUL[a, b])


def gf_pow(a: int, n: int) -> int:
    if n == 0:
        return 1
    if a == 0:
        return 0
    return int(GF_EXP[(int(GF_LOG[a]) * n) % 255])


def gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("gf_inv(0)")
    return int(GF_EXP[(255 - int(GF_LOG[a])) % 255])


def gf_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Matrix product over GF(2^8). a: [m,k] uint8, b: [k,n] uint8 -> [m,n]."""
    a = np.asarray(a, dtype=np.uint8)
    b = np.asarray(b, dtype=np.uint8)
    # products[m,k,n] then XOR-reduce over k
    prod = GF_MUL[a[:, :, None], b[None, :, :]]
    return np.bitwise_xor.reduce(prod, axis=1)


def gf_mat_inv(m: np.ndarray) -> np.ndarray:
    """Invert a square matrix over GF(2^8) by Gauss-Jordan elimination."""
    m = np.asarray(m, dtype=np.uint8)
    n = m.shape[0]
    if m.shape != (n, n):
        raise ValueError(f"matrix not square: {m.shape}")
    aug = np.concatenate([m.copy(), np.eye(n, dtype=np.uint8)], axis=1)
    for col in range(n):
        pivot = None
        for r in range(col, n):
            if aug[r, col] != 0:
                pivot = r
                break
        if pivot is None:
            raise np.linalg.LinAlgError("singular matrix over GF(2^8)")
        if pivot != col:
            aug[[col, pivot]] = aug[[pivot, col]]
        inv = gf_inv(int(aug[col, col]))
        aug[col] = GF_MUL[inv, aug[col]]
        for r in range(n):
            if r != col and aug[r, col] != 0:
                aug[r] ^= GF_MUL[int(aug[r, col]), aug[col]]
    return aug[:, n:].copy()


@functools.lru_cache(maxsize=64)
def _encode_matrix_cached(d: int, p: int) -> np.ndarray:
    n = d + p
    if not (0 < d and 0 < p and n <= FIELD):
        raise ValueError(f"invalid RS geometry d={d} p={p}")
    # Vandermonde: V[r, c] = r^c  (klauspost/backblaze construction).
    vand = np.zeros((n, d), dtype=np.uint8)
    for r in range(n):
        for c in range(d):
            vand[r, c] = gf_pow(r, c)
    top_inv = gf_mat_inv(vand[:d, :d])
    enc = gf_matmul(vand, top_inv)
    enc.setflags(write=False)
    return enc


def encode_matrix(d: int, p: int) -> np.ndarray:
    """Systematic [d+p, d] encode matrix: top d rows identity, bottom p parity."""
    return _encode_matrix_cached(d, p)


def parity_matrix(d: int, p: int) -> np.ndarray:
    """The [p, d] parity block of the systematic encode matrix."""
    return encode_matrix(d, p)[d:, :]


def decode_matrix(d: int, p: int, present: "list[int] | np.ndarray") -> np.ndarray:
    """Matrix reconstructing ALL n=d+p shards from d surviving shards.

    `present` lists >=d surviving shard ids (sorted); the first d are used.
    Returns R [n, d] with all-shards = R (x) survivors[:d], such that rows for
    surviving shards are unit rows (copy-through). Mirrors the per-read inverse
    the reference computes inside reedsolomon.Reconstruct
    (reference: weed/storage/erasure_coding/ec_encoder.go:274).
    """
    present = sorted(int(i) for i in present)
    if len(present) < d:
        raise ValueError(f"need >= {d} shards, have {len(present)}")
    use = present[:d]
    enc = encode_matrix(d, p)
    sub = enc[use, :]  # [d, d]
    inv = gf_mat_inv(sub)  # data = inv (x) survivors
    return gf_matmul(enc, inv)  # [n, d]


# ---------------------------------------------------------------------------
# Bit-matrix expansion: the bridge from GF(2^8) to the MXU.
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=256)
def _bit_matrix_of_const(c: int) -> bytes:
    """8x8 GF(2) matrix of 'multiply by c'; M[i, j] = bit i of c * (1 << j)."""
    m = np.zeros((8, 8), dtype=np.uint8)
    for j in range(8):
        prod = gf_mul(c, 1 << j)
        for i in range(8):
            m[i, j] = (prod >> i) & 1
    return m.tobytes()


def bit_matrix_of_const(c: int) -> np.ndarray:
    return np.frombuffer(_bit_matrix_of_const(int(c)), dtype=np.uint8).reshape(8, 8)


def expand_to_bits(mat: np.ndarray) -> np.ndarray:
    """Expand a GF(2^8) matrix [m, k] into its GF(2) bit-matrix [8m, 8k].

    Block (j, i) of the result is the 8x8 bit-matrix of mat[j, i]; with data
    bytes unpacked LSB-first along the row axis, out_bits = B @ in_bits mod 2
    computes the GF(2^8) product. This is what rides the MXU.
    """
    mat = np.asarray(mat, dtype=np.uint8)
    m, k = mat.shape
    out = np.zeros((8 * m, 8 * k), dtype=np.uint8)
    for j in range(m):
        for i in range(k):
            out[8 * j:8 * j + 8, 8 * i:8 * i + 8] = bit_matrix_of_const(mat[j, i])
    return out


# ---------------------------------------------------------------------------
# Reference (numpy) encode/reconstruct — correctness oracle for device paths.
# ---------------------------------------------------------------------------

def np_gf_apply(mat: np.ndarray, shards: np.ndarray) -> np.ndarray:
    """Apply GF matrix [m, k] to shard bytes [k, L] -> [m, L] (numpy oracle)."""
    mat = np.asarray(mat, dtype=np.uint8)
    shards = np.asarray(shards, dtype=np.uint8)
    out = np.zeros((mat.shape[0], shards.shape[1]), dtype=np.uint8)
    for j in range(mat.shape[0]):
        acc = out[j]
        for i in range(mat.shape[1]):
            c = mat[j, i]
            if c:
                acc ^= GF_MUL[c, shards[i]]
    return out


def np_encode(data: np.ndarray, p: int) -> np.ndarray:
    """data [d, L] -> parity [p, L]; pure-numpy oracle."""
    d = data.shape[0]
    return np_gf_apply(parity_matrix(d, p), data)


def np_reconstruct(shards: np.ndarray, present: "list[int]", d: int, p: int) -> np.ndarray:
    """shards [n, L] with garbage rows for missing ids -> full [n, L]."""
    rec = decode_matrix(d, p, present)
    use = sorted(present)[:d]
    return np_gf_apply(rec, shards[use])
