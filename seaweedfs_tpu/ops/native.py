"""ctypes bridge to the C++ sidecar (seaweedfs_tpu/native/libswtpu.so).

Builds the library on first use (g++ via the Makefile) and degrades
gracefully to None when no toolchain is available — callers fall back to the
numpy/JAX paths. The NativeCoder here is the CPU baseline for bench.py:
the same AVX2 split-table algorithm klauspost/reedsolomon uses.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

from . import gf8
from .coder import ErasureCoder, register_coder

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libswtpu.so")
_lock = threading.Lock()
_lib: "ctypes.CDLL | None | bool" = None  # None=untried, False=unavailable


def load() -> "ctypes.CDLL | None":
    global _lib
    with _lock:
        if _lib is None:
            _lib = _try_load()
        return _lib or None


def _try_load():
    if not os.path.exists(_SO_PATH):
        try:
            subprocess.run(["make", "-C", _NATIVE_DIR, "-s"], check=True,
                           capture_output=True, timeout=120)
        except Exception:
            return False
    try:
        lib = ctypes.CDLL(_SO_PATH)
    except OSError:
        return False
    lib.rs_apply_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_int, ctypes.c_int, ctypes.c_int64, ctypes.c_int64]
    lib.rs_apply.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_int, ctypes.c_int, ctypes.c_int64]
    lib.crc32c_update.restype = ctypes.c_uint32
    lib.crc32c_update.argtypes = [ctypes.c_uint32, ctypes.c_void_p, ctypes.c_int64]
    lib.crc32c_batch.argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
                                 ctypes.c_uint32, ctypes.c_void_p]
    lib.native_features.restype = ctypes.c_int
    return lib


def available() -> bool:
    return load() is not None


def features() -> dict:
    lib = load()
    if lib is None:
        return {"available": False}
    f = lib.native_features()
    return {"available": True, "avx2": bool(f & 1), "sse42_crc": bool(f & 2)}


def _apply(mat: np.ndarray, data: np.ndarray) -> np.ndarray:
    """mat [m,k] uint8, data [..., k, L] uint8 C-contiguous -> [..., m, L]."""
    lib = load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    data = np.ascontiguousarray(data, dtype=np.uint8)
    mat = np.ascontiguousarray(mat, dtype=np.uint8)
    m, k = mat.shape
    if data.ndim == 2:
        ksz, L = data.shape
        assert ksz == k
        out = np.empty((m, L), dtype=np.uint8)
        lib.rs_apply(data.ctypes.data, out.ctypes.data, mat.ctypes.data, k, m, L)
        return out
    B, ksz, L = data.shape
    assert ksz == k
    out = np.empty((B, m, L), dtype=np.uint8)
    lib.rs_apply_batch(data.ctypes.data, out.ctypes.data, mat.ctypes.data,
                       k, m, L, B)
    return out


def crc32c(data: bytes | np.ndarray, value: int = 0) -> int:
    """Hardware CRC32C with the standard init/final-xor convention."""
    lib = load()
    if lib is None:
        from .crc32c import crc32c as soft
        return soft(data, value)
    arr = np.frombuffer(data, dtype=np.uint8) if isinstance(
        data, (bytes, bytearray, memoryview)) else np.ascontiguousarray(data, dtype=np.uint8)
    raw = lib.crc32c_update(value ^ 0xFFFFFFFF, arr.ctypes.data, arr.size)
    return raw ^ 0xFFFFFFFF


class NativeCoder(ErasureCoder):
    """AVX2 split-table CPU coder — the reference-equivalent baseline."""

    def __init__(self, d: int, p: int):
        super().__init__(d, p)
        if not available():
            raise RuntimeError("native library unavailable (no g++?)")
        self._parity = gf8.parity_matrix(d, p)

    def encode(self, data: np.ndarray) -> np.ndarray:
        return _apply(self._parity, data)

    def reconstruct(self, survivors, present, wanted):
        rec = gf8.decode_matrix(self.d, self.p, list(present))[list(wanted), :]
        return _apply(rec, survivors)


register_coder("native", NativeCoder)
