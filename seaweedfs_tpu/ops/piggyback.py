"""Piggybacked RS: a repair-efficient erasure code behind the coder seam.

The repair-bandwidth problem (PAPERS arXiv:1309.0186): rebuilding one
lost shard of an RS(d, p) stripe reads d *full* shards off the network —
at Facebook's warehouse cluster that made recovery traffic a first-class
network load. The piggybacking framework (arXiv:1412.3022, the
Hitchhiker construction deployed in HDFS) cuts single-shard repair bytes
~35% without touching the storage overhead, the systematic property, or
the fault tolerance: it is *the same* RS code, with a little data from
one substripe XOR-folded ("piggybacked") onto parities of a second.

Construction (2 substripes over the shard byte range, boundary at L/2):

* every shard's first half (**substripe a**) is a plain RS(d, p)
  codeword over the data shards' first halves;
* every shard's second half (**substripe b**) is a plain RS codeword
  over the second halves, EXCEPT parities 1..p-1, which store

      pb_g = P_g(b)  XOR  (XOR_{i in S_g} a_i)        g = 1 .. p-1

  where S_1..S_{p-1} partition the data ids round-robin. Parity 0 is
  never piggybacked, and data shards are untouched — normal reads and
  the stripe locator (ec/locate.py) cannot tell the codecs apart.

Single data-shard repair (shard f in group S_g) reads *byte ranges*:

  1. b-halves of the other d-1 data shards + parity 0's b-half
     -> decode b_f (plain RS, one unknown);
  2. the piggybacked parity's b-half + a-halves of S_g minus {f}
     -> a_f = pb_g XOR P_g(b) XOR (XOR_{i in S_g, i != f} a_i),
     where P_g(b) is recomputed from the now-complete b substripe.

Total: (d + |S_g|) half-shards = (d + |S_g|) / (2d) of the plain-RS
cost. With RS(10, 4) and groups of ceil(10/3): 0.65-0.70x. With p = 2
the only group is all of [d] and the plan degenerates to the trivial
one (repair_plan returns None) — the codec still round-trips, it just
cannot beat plain RS, which is why the fork's RS(14, 2) default keeps
codec "rs" unless asked.

All heavy GF(2^8) math rides the *inner* coder (numpy / jax / pallas /
native), so the piggyback layer works on every backend: it only adds
XORs and bookkeeping on top of the existing bit-matmul kernels.
"""

from __future__ import annotations

import numpy as np

from .coder import ErasureCoder, get_coder, register_coder


def partition_groups(d: int, p: int) -> "list[list[int]]":
    """Round-robin partition of data ids 0..d-1 into p-1 piggyback
    groups; groups[g-1] backs parity g. Deterministic — both the
    encoder and any future reader derive the same partition from
    (d, p) alone, so nothing extra needs persisting in the .vif."""
    if p < 2:
        return []
    return [[i for i in range(d) if i % (p - 1) == g] for g in range(p - 1)]


class PiggybackCoder(ErasureCoder):
    """Hitchhiker-style piggybacked RS over a pluggable inner backend.

    Array semantics: the last axis is one shard's full byte range and
    the substripe boundary sits at L // 2 (L must be even — shard files
    always are, block sizes being powers of two). encode/reconstruct
    accept [d|k, L] and batched [B, d|k, L] like every other coder.
    """

    codec = "piggyback"
    async_dispatch = False  # host-orchestrated; inner device calls still batch

    def __init__(self, d: int, p: int, backend: str = "numpy"):
        super().__init__(d, p)
        if p < 2:
            raise ValueError("piggyback needs p >= 2 (nothing to fold onto)")
        self.backend = backend
        self.inner = get_coder(backend, d, p)
        self.groups = partition_groups(d, p)

    def group_of(self, f: int) -> tuple[int, list[int]]:
        """(parity index g in 1..p-1, data ids of f's group)."""
        g = f % (self.p - 1)
        return g + 1, self.groups[g]

    # -- array construction --------------------------------------------------
    @staticmethod
    def _split(arr: np.ndarray) -> tuple[np.ndarray, np.ndarray, int]:
        half = arr.shape[-1] // 2
        if arr.shape[-1] != half * 2:
            raise ValueError(f"piggyback needs an even length, got {arr.shape[-1]}")
        return arr[..., :half], arr[..., half:], half

    def _xor_group(self, a_data: np.ndarray, grp: "list[int]") -> np.ndarray:
        """XOR of the group's rows of a_data [..., d, half]."""
        if not grp:  # d < p-1 leaves trailing groups empty: zero piggyback
            return np.zeros(a_data.shape[:-2] + a_data.shape[-1:],
                            dtype=np.uint8)
        return np.bitwise_xor.reduce(a_data[..., grp, :], axis=-2)

    def encode(self, data: np.ndarray) -> np.ndarray:
        data = np.asarray(data, dtype=np.uint8)
        parity = np.array(np.asarray(self.inner.encode(data)), dtype=np.uint8)
        a, _b, _half = self._split(data)
        for g, grp in enumerate(self.groups, start=1):
            parity[..., g, parity.shape[-1] // 2:] ^= self._xor_group(a, grp)
        return parity

    def reconstruct(self, survivors: np.ndarray, present: tuple[int, ...],
                    wanted: tuple[int, ...]) -> np.ndarray:
        """survivors = shards sorted(present)[:d], FULL shard ranges.

        Substripe a is plain RS everywhere, so missing a-halves come
        straight from the inner decode; b-halves of surviving piggybacked
        parities are first "purified" (their piggyback XOR-ed back off
        using the recovered a substripe), decoded as plain RS, and wanted
        piggybacked parities get their piggyback re-applied.
        """
        survivors = np.asarray(survivors, dtype=np.uint8)
        squeeze = survivors.ndim == 2
        if squeeze:
            survivors = survivors[None]
        wanted = tuple(wanted)
        used = tuple(sorted(present))[: self.d]
        a, b, half = self._split(survivors)
        # one inner decode serves both the X_g terms (all data a-halves)
        # and the wanted rows' a-halves
        want_a = tuple(range(self.d)) + tuple(w for w in wanted if w >= self.d)
        a_rows = np.asarray(self.inner.reconstruct(a, present, want_a),
                            dtype=np.uint8)
        a_data = a_rows[:, : self.d]
        xg = {g: self._xor_group(a_data, grp)
              for g, grp in enumerate(self.groups, start=1)}
        b_pure = np.array(b, dtype=np.uint8)
        for idx, s in enumerate(used):
            if s > self.d:  # piggybacked parity survivor
                b_pure[:, idx] ^= xg[s - self.d]
        b_rows = np.asarray(self.inner.reconstruct(b_pure, present, wanted),
                            dtype=np.uint8)
        out = np.empty(survivors.shape[:1] + (len(wanted), 2 * half),
                       dtype=np.uint8)
        for wi, w in enumerate(wanted):
            if w < self.d:
                out[:, wi, :half] = a_rows[:, w]
            else:
                out[:, wi, :half] = a_rows[:, self.d + want_a[self.d:].index(w)]
            brow = b_rows[:, wi]
            if w > self.d:
                brow = brow ^ xg[w - self.d]
            out[:, wi, half:] = brow
        return out[0] if squeeze else out

    # -- ranged repair -------------------------------------------------------
    def repair_plan(self, present: tuple[int, ...], wanted: tuple[int, ...],
                    shard_size: int):
        """Byte ranges of survivors needed to rebuild `wanted`, or None
        when no plan beats reading d full shards (multi-loss, parity
        loss, p = 2, or a required survivor itself missing)."""
        present = set(present)
        if len(wanted) != 1 or shard_size % 2:
            return None
        f = wanted[0]
        if not 0 <= f < self.d:
            return None
        g, grp = self.group_of(f)
        if len(grp) >= self.d:  # p == 2: the "plan" would read d full shards
            return None
        need_b = [i for i in range(self.d) if i != f] + [self.d, self.d + g]
        need_a = [i for i in grp if i != f]
        if any(s not in present for s in need_b + need_a):
            return None
        half = shard_size // 2
        return ([(s, half, half) for s in need_b]
                + [(s, 0, half) for s in need_a])


def _register():
    register_coder("piggyback", PiggybackCoder)


_register()
