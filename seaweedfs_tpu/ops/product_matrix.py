"""Product-matrix MSR regenerating codec: bandwidth-optimal repair for
ANY single shard loss, data or parity.

The piggybacked codec (ops/piggyback.py) buys ~0.65x repair bytes but
only for single-*data*-shard loss, and degenerates to plain RS at p = 2
— the fork's RS(14,2) default gets nothing. A minimum-storage
regenerating (MSR) code reaches the information-theoretic cut-set bound
for every single loss: with all n-1 survivors helping, repair moves

    (n - 1) / p   shard-equivalents        (vs d for plain RS)

i.e. 7.5 vs 14 at RS(14,2) and 3.25 vs 10 at RS(10,4), at the SAME
storage overhead and fault tolerance (the code stays MDS: any d shards
recover everything).

Construction (product-matrix pairwise coupling over layered RS — the
coupled-layer realization of regenerating codes; PAPERS.md
arXiv:1412.3022 lineage):

* every shard file splits into alpha = q^t sub-symbols ("layers"),
  q = p, t = ceil(n / q); grid node i sits at coordinate
  (x, y) = (i % q, i // q) and layers are addressed by a base-q word
  z = (z_0 .. z_{t-1}), z_0 most significant in the linear index — so
  fixing a high-column digit selects CONTIGUOUS runs of the shard file;
* per layer, the *uncoupled* symbols U(i; z) across the q*t grid nodes
  form one codeword of a single scalar systematic RS code with q
  parities (the ops/gf8.py machinery every other codec rides);
* the *stored* symbols C come from U via an invertible 2x2 product
  matrix applied across symbol pairs: for x != z_y the symbols at
  (x, y; z) and (z_y, y; z') with z' = z(y -> x) couple as

      [C ]   [1      gamma] [U ]
      [C*] = [gamma  1    ] [U*]          gamma^2 != 1

  while diagonal symbols (x == z_y) store uncoupled (C = U).

Systematic layout: data nodes 0..d-1 store their coupled symbols AS the
raw striped volume bytes — data shard files are byte-identical to plain
RS / piggyback, so needle reads and the stripe locator (ec/locate.py)
cannot tell the codecs apart. When n does not fill the q x t grid the
trailing grid nodes are virtual all-zero shards (code shortening).

Repair of node (x0, y0) reads, from each of the n-1 survivors, only the
alpha/q layers with z_{y0} = x0 (the "repair planes"): each survivor's
contribution is a beta-sized computed fragment — the volume server's
ranged-compute shard read gathers the scattered layer slices into ONE
wire fragment (and can GF-combine them server-side). Per repair plane
the failed node's q fiber unknowns satisfy a q x q product-matrix
system whose right-hand side is a GF inner product of survivor symbols,
batched across planes through the same bit-matmul kernels as encode
(ops/rs_jax.apply_bitmatrix on device backends).

Everything — encode, d-survivor decode, repair, degraded interval reads
— reduces to two algorithms below: `decode_coupled` (score-ordered
layered decode, optionally restricted to a closure layer set) and
`repair_decode` (fiber systems over repair planes).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import numpy as np

from . import gf8
from .coder import ErasureCoder, get_coder, register_coder

# coupling coefficient: any gamma with gamma^2 != 1 keeps the 2x2
# product matrix invertible over GF(2^8)
GAMMA = 2


@functools.lru_cache(maxsize=32)
def _grid(d: int, p: int) -> "_Grid":
    return _Grid(d, p)


class _Grid:
    """Geometry + index precomputation shared by every (d, p) instance."""

    def __init__(self, d: int, p: int):
        self.d = d
        self.p = p
        self.n = d + p
        self.q = q = max(1, p)
        self.t = t = -(-self.n // q)  # ceil
        self.nbar = q * t
        self.alpha = q ** t
        g2 = gf8.gf_mul(GAMMA, GAMMA)
        self.inv_1g2 = gf8.gf_inv(1 ^ g2)
        # 256-entry multiply LUTs: scalar-by-vector in one fancy index
        self.mul_gamma = gf8.GF_MUL[GAMMA]
        self.mul_inv = gf8.GF_MUL[self.inv_1g2]
        self.mul_1g2 = gf8.GF_MUL[1 ^ g2]
        # digits[y, Z] = column-y (most-significant-first) base-q digit
        zs = np.arange(self.alpha)
        self.digits = np.stack(
            [(zs // q ** (t - 1 - y)) % q for y in range(t)])
        self.xs = np.arange(self.nbar) % q
        self.ys = np.arange(self.nbar) // q
        # pairing tables [nbar, alpha]
        zy = self.digits[self.ys]                     # own-column digit
        self.unpaired = zy == self.xs[:, None]
        self.pair_node = self.ys[:, None] * q + zy    # grid node (z_y, y)
        step = (q ** (t - 1 - self.ys))[:, None]
        self.pair_layer = zs[None, :] + (self.xs[:, None] - zy) * step
        # per-layer scalar code: parity-check H = [P | I_q] of the
        # systematic RS [nbar, nbar-q] code (any q columns of an MDS
        # parity-check matrix are invertible)
        kbar = self.nbar - q
        self.H = np.concatenate(
            [gf8.parity_matrix(kbar, q), np.eye(q, dtype=np.uint8)], axis=1)

    def coords(self, i: int) -> tuple[int, int]:
        return i % self.q, i // self.q

    def col_step(self, y: int) -> int:
        """Linear-index stride of column y's digit."""
        return self.q ** (self.t - 1 - y)

    def repair_planes(self, f: int) -> np.ndarray:
        """Ascending layer ids with digit y0 fixed at x0 (alpha/q)."""
        x0, y0 = self.coords(f)
        return np.nonzero(self.digits[y0] == x0)[0]

    def fiber(self, f: int, planes: np.ndarray) -> np.ndarray:
        """fiber[x, j] = plane j with digit y0 replaced by x."""
        x0, y0 = self.coords(f)
        step = self.col_step(y0)
        base = planes - x0 * step
        return base[None, :] + np.arange(self.q)[:, None] * step

    def plane_of(self, f: int, layers: np.ndarray) -> np.ndarray:
        """Each layer's fiber representative (digit y0 set to x0)."""
        x0, y0 = self.coords(f)
        step = self.col_step(y0)
        return layers + (x0 - self.digits[y0][layers]) * step

    @functools.lru_cache(maxsize=64)
    def solve_matrices(self, used: tuple) -> tuple:
        """(erased ids, known ids, M) with U_erased = M (x) U_known per
        layer: M = inv(H[:, erased]) (x) H[:, known]."""
        known = sorted(set(used) | set(range(self.n, self.nbar)))
        erased = tuple(i for i in range(self.nbar) if i not in known)
        inv = gf8.gf_mat_inv(self.H[:, list(erased)])
        m = gf8.gf_matmul(inv, self.H[:, known])
        m.setflags(write=False)
        return erased, tuple(known), m

    @functools.lru_cache(maxsize=64)
    def repair_matrices(self, f: int) -> tuple:
        """Single-loss fiber system (col0 real helpers, off-column grid
        ids, M = inv(A) (x) B).

        Per repair plane z the parity checks reduce to A U_fiber = B r:
        column x0 of A is H[:, f] and column x != x0 is gamma-scaled
        H[:, (x, y0)] (their U substitutes C + gamma U_fiber through the
        product matrix, virtual col0 nodes contributing C = 0); r stacks
        the off-column nodes' uncoupled U's then the real col0 helpers'
        raw C's.
        """
        x0, y0 = self.coords(f)
        col0 = [y0 * self.q + x for x in range(self.q)]
        col0_real = tuple(i for i in col0 if i < self.n and i != f)
        others = tuple(i for i in range(self.nbar) if i not in col0)
        a = np.zeros((self.q, self.q), dtype=np.uint8)
        for x in range(self.q):
            i = y0 * self.q + x
            a[:, x] = self.H[:, f] if i == f else self.mul_gamma[self.H[:, i]]
        b = np.concatenate(
            [self.H[:, list(others)], self.H[:, list(col0_real)]], axis=1)
        m = gf8.gf_matmul(gf8.gf_mat_inv(a), b)
        m.setflags(write=False)
        return col0_real, others, m


@dataclass
class IntervalPlan:
    """Fetch spec for a degraded read of [offset, offset+length) of one
    lost shard: per-survivor layer lists at a common inner window."""
    mode: str                            # "repair" | "general"
    f: int
    offset: int
    length: int
    shard_size: int
    alpha: int
    inner: tuple[int, int]               # [u0, u1) within each layer
    fetch: "dict[int, list[int]]"        # sid -> ascending layer ids
    planes: "np.ndarray | None" = None   # repair mode: fiber representatives
    used: tuple = ()                     # general mode: d survivors decoded
    closure: "np.ndarray | None" = None  # general mode: processed layers

    def byte_ranges(self, sid: int) -> "list[tuple[int, int]]":
        """(file offset, length) reads realizing this plan for `sid`."""
        s = self.shard_size // self.alpha
        u0, u1 = self.inner
        return [(z * s + u0, u1 - u0) for z in self.fetch.get(sid, ())]

    def bytes_total(self) -> int:
        u0, u1 = self.inner
        return sum(len(v) for v in self.fetch.values()) * (u1 - u0)


class ProductMatrixCoder(ErasureCoder):
    """MSR product-matrix regenerating code over a pluggable GF backend.

    Array semantics: the last axis is one shard's FULL byte range (or a
    same-width slice of every sub-symbol — any length divisible by
    alpha); sub-symbol ell of a row occupies bytes [ell*S, (ell+1)*S).
    encode / reconstruct accept [d, L] and batched [B, d, L] like every
    other coder.
    """

    codec = "msr"
    async_dispatch = False  # host-orchestrated; GF matmuls batch on device

    def __init__(self, d: int, p: int, backend: str = "numpy"):
        super().__init__(d, p)
        self.backend = backend
        self.inner = get_coder(backend, d, p)
        self.grid = _grid(d, p)

    @property
    def alpha(self) -> int:
        return self.grid.alpha

    @property
    def beta_layers(self) -> int:
        """Sub-symbols each survivor ships for a single-loss repair."""
        return self.grid.alpha // self.grid.q

    def _check_len(self, length: int) -> int:
        if length % self.alpha:
            raise ValueError(
                f"msr needs a length divisible by alpha={self.alpha} "
                f"(q^t for q={self.grid.q}, t={self.grid.t}), got {length}; "
                "shard files are block multiples, so pick a power-of-two p "
                "or a small_block divisible by alpha")
        return length // self.alpha

    # -- GF matrix application (device-batched when the backend allows) ----
    def _apply(self, mat: np.ndarray, rows: np.ndarray) -> np.ndarray:
        """mat [m, k] (x) rows [k, L] -> [m, L] on the backend kernels."""
        if rows.shape[-1] == 0 or mat.shape[0] == 0 or mat.shape[1] == 0:
            return np.zeros((mat.shape[0], rows.shape[-1]), dtype=np.uint8)
        if self.backend not in ("numpy", "native"):
            try:
                import jax.numpy as jnp

                from . import rs_jax
                bmat = gf8.expand_to_bits(np.asarray(mat)).astype(np.int8)
                out = rs_jax.apply_bitmatrix(jnp.asarray(bmat),
                                             jnp.asarray(rows))
                return np.asarray(out, dtype=np.uint8)
            except Exception:  # noqa: BLE001  # swtpu-lint: disable=silent-except (device path is an optimization; numpy below is the correctness path)
                pass
        return gf8.np_gf_apply(mat, rows)

    # -- core: score-ordered layered decode --------------------------------
    def decode_coupled(self, c: np.ndarray, used: tuple,
                       layers: "np.ndarray | None" = None) -> np.ndarray:
        """Fill the erased rows of c [nbar, alpha, W] in place.

        `c` carries coupled symbols for the `used` real nodes (the first
        d of them decide) and zeros for virtual nodes; the other q real
        nodes are recovered. `layers` restricts processing to a closure
        set (degraded interval reads): the set must be closed under
        digit substitution at the erased nodes' columns, and c must also
        be populated at the pair slices read_closure() lists.
        """
        g = self.grid
        used = tuple(sorted(used))[: self.d]
        erased, known, m = g.solve_matrices(used)
        known_a = np.asarray(known)
        erased_a = np.asarray(erased)
        ls = np.arange(g.alpha) if layers is None else np.asarray(layers)
        if len(ls) == 0:
            return c
        score = np.zeros(len(ls), dtype=np.int64)
        for e in erased:
            score += g.digits[g.ys[e]][ls] == g.xs[e]
        erased_mask = np.zeros(g.nbar, dtype=bool)
        erased_mask[erased_a] = True
        u = np.zeros_like(c)
        # survivor U where the symbol is uncoupled or its pair is known:
        # one vectorized 2x2 product-matrix inversion
        kn = known_a[:, None]
        unp = g.unpaired[kn, ls]
        pn, pl = g.pair_node[kn, ls], g.pair_layer[kn, ls]
        uk = np.where(unp[..., None], c[kn, ls],
                      g.mul_inv[c[kn, ls] ^ g.mul_gamma[c[pn, pl]]])
        pair_known = ~erased_mask[pn]
        u[kn, ls] = np.where((unp | pair_known)[..., None], uk, 0)
        rule3 = ~unp & ~pair_known  # survivor coupled with an erased node
        w = c.shape[-1]
        for s in range(int(score.max()) + 1):
            sel = score == s
            if not sel.any():
                continue
            zsel = ls[sel]
            r3 = rule3[:, sel]
            if r3.any():
                # pair is erased: its U at the score-(s-1) pair layer is
                # already solved, so U = C + gamma U_pair
                ki, li = np.nonzero(r3)
                nodes, lz = known_a[ki], zsel[li]
                u[nodes, lz] = (c[nodes, lz]
                                ^ g.mul_gamma[u[g.pair_node[nodes, lz],
                                                g.pair_layer[nodes, lz]]])
            rhs = u[known_a[:, None], zsel].reshape(len(known), -1)
            sol = self._apply(m, rhs)
            u[erased_a[:, None], zsel] = sol.reshape(len(erased),
                                                     len(zsel), w)
        # stored symbols of the erased nodes from the now-complete U
        en = erased_a[:, None]
        unp_e = g.unpaired[en, ls]
        pn_e, pl_e = g.pair_node[en, ls], g.pair_layer[en, ls]
        c[en, ls] = np.where(unp_e[..., None], u[en, ls],
                             u[en, ls] ^ g.mul_gamma[u[pn_e, pl_e]])
        return c

    def read_closure(self, used: tuple, wanted_layers: np.ndarray,
                     ) -> "tuple[np.ndarray, dict[int, np.ndarray]]":
        """(closure, fetch) for a restricted decode_coupled: closure is
        wanted_layers closed under digit substitution at the erased
        columns; fetch[sid] adds each known node's pair slices."""
        g = self.grid
        used = tuple(sorted(used))[: self.d]
        erased, known, _ = g.solve_matrices(used)
        closure = np.unique(np.asarray(wanted_layers))
        for yc in sorted({int(g.ys[e]) for e in erased}):
            step = g.col_step(yc)
            base = closure - g.digits[yc][closure] * step
            closure = np.unique(
                (base[None, :] + np.arange(g.q)[:, None] * step).ravel())
        fetch: dict[int, set] = {i: set(closure.tolist())
                                 for i in used}
        # pair slices: every known node's U (virtual grid nodes included
        # — their own C is zero but their coupling partner's is not)
        for i in known:
            paired = ~g.unpaired[i, closure]
            for z in closure[paired]:
                pnode = int(g.pair_node[i, z])
                if pnode < self.n and pnode not in erased:
                    fetch.setdefault(pnode, set()).add(int(g.pair_layer[i, z]))
        return closure, {i: np.asarray(sorted(v)) for i, v in fetch.items()}

    def encode_subsymbols(self, data_sub: np.ndarray) -> np.ndarray:
        """data_sub [d, alpha, W] -> parity [p, alpha, W]."""
        g = self.grid
        c = np.zeros((g.nbar, g.alpha, data_sub.shape[-1]), dtype=np.uint8)
        c[: self.d] = data_sub
        self.decode_coupled(c, tuple(range(self.d)))
        return c[self.d: self.n].copy()

    # -- ErasureCoder contract ---------------------------------------------
    def encode(self, data: np.ndarray) -> np.ndarray:
        data = np.asarray(data, dtype=np.uint8)
        squeeze = data.ndim == 2
        if squeeze:
            data = data[None]
        b, k, L = data.shape
        if L == 0:
            out = np.zeros((b, self.p, 0), dtype=np.uint8)
            return out[0] if squeeze else out
        s = self._check_len(L)
        # batch elements are independent stripes and every relation is
        # elementwise along the inner axis, so fold B into it
        sub = data.reshape(b, k, self.alpha, s).transpose(1, 2, 0, 3)
        par = self.encode_subsymbols(sub.reshape(k, self.alpha, b * s))
        par = par.reshape(self.p, self.alpha, b, s).transpose(2, 0, 1, 3)
        par = par.reshape(b, self.p, L)
        return par[0] if squeeze else par

    def reconstruct(self, survivors: np.ndarray, present: tuple,
                    wanted: tuple) -> np.ndarray:
        survivors = np.asarray(survivors, dtype=np.uint8)
        squeeze = survivors.ndim == 2
        if squeeze:
            survivors = survivors[None]
        b, k, L = survivors.shape
        if k < self.d:
            raise ValueError(f"need {self.d} survivors, got {k}")
        wanted = tuple(wanted)
        if L == 0:
            out = np.zeros((b, len(wanted), 0), dtype=np.uint8)
            return out[0] if squeeze else out
        s = self._check_len(L)
        used = tuple(sorted(present))[: self.d]
        g = self.grid
        sub = survivors[:, : self.d].reshape(b, self.d, self.alpha, s)
        sub = sub.transpose(1, 2, 0, 3).reshape(self.d, self.alpha, b * s)
        c = np.zeros((g.nbar, g.alpha, b * s), dtype=np.uint8)
        c[np.asarray(used)] = sub
        self.decode_coupled(c, used)
        out = c[np.asarray(wanted, dtype=np.int64)]
        out = out.reshape(len(wanted), self.alpha, b, s).transpose(2, 0, 1, 3)
        out = out.reshape(b, len(wanted), L)
        return out[0] if squeeze else out

    # -- single-loss repair: the MSR fast path -----------------------------
    def repair_supported(self, present: tuple, wanted: tuple,
                         shard_size: int) -> bool:
        """True when the (n-1)-helper repair-plane path applies."""
        if len(wanted) != 1 or self.grid.q < 2:
            return False
        if shard_size <= 0 or shard_size % self.alpha:
            return False
        f = wanted[0]
        if not 0 <= f < self.n:
            return False
        return (set(range(self.n)) - {f}) <= set(present)

    def repair_fragment_ranges(self, f: int, shard_size: int,
                               ) -> "list[tuple[int, int]]":
        """Coalesced (offset, length) byte runs of the repair planes —
        identical for every helper. Runs are maximal: consecutive layer
        ids merge, so a failed node at a high grid column costs one
        contiguous range and a low column alpha/q of them."""
        s = shard_size // self.alpha
        runs: list[tuple[int, int]] = []
        for z in self.grid.repair_planes(f):
            off = int(z) * s
            if runs and runs[-1][0] + runs[-1][1] == off:
                runs[-1] = (runs[-1][0], runs[-1][1] + s)
            else:
                runs.append((off, s))
        return runs

    def repair_plan(self, present: tuple, wanted: tuple, shard_size: int):
        """Byte-range view of the fragment plan (the coder-seam contract
        and the planner's byte costing): every helper contributes its
        repair planes — (n-1)/p shard-equivalents total, for data AND
        parity losses alike. None when the repair-plane path cannot run
        (multi-loss, a missing helper, q < 2, alpha-unaligned shard);
        the executor then streams the general coupled decode over d
        full survivors, reading each exactly once."""
        if not self.repair_supported(present, wanted, shard_size):
            return None
        f = wanted[0]
        runs = self.repair_fragment_ranges(f, shard_size)
        return [(sid, off, ln)
                for sid in range(self.n) if sid != f
                for off, ln in runs]

    def repair_decode(self, c: np.ndarray, f: int,
                      planes: "np.ndarray | None" = None) -> np.ndarray:
        """Recover the failed node from repair-plane symbols.

        c [nbar, alpha, W] carries helper symbols at the repair planes
        (plus, when `planes` restricts to a subset, the off-column pair
        slices interval_plan lists); virtual rows are zeros. Returns the
        failed node's [alpha, W] — only the processed fibers are
        populated when restricted.
        """
        g = self.grid
        x0, y0 = g.coords(f)
        if planes is None:
            planes = g.repair_planes(f)
        planes = np.asarray(planes)
        col0_real, others, m = g.repair_matrices(f)
        others_a = np.asarray(others)
        w = c.shape[-1]
        # off-column U at the repair planes: both product-matrix inputs
        # are helper (or virtual zero) symbols at repair planes
        on = others_a[:, None]
        unp = g.unpaired[on, planes]
        pn, pl = g.pair_node[on, planes], g.pair_layer[on, planes]
        u_oth = np.where(unp[..., None], c[on, planes],
                         g.mul_inv[c[on, planes] ^ g.mul_gamma[c[pn, pl]]])
        rows = [u_oth]
        if col0_real:
            rows.append(c[np.asarray(col0_real)[:, None], planes])
        rhs = np.concatenate(rows, axis=0).reshape(-1, len(planes) * w)
        u_fiber = self._apply(m, rhs).reshape(g.q, len(planes), w)
        fib = g.fiber(f, planes)              # [q, planes] layer ids
        u_f = np.zeros((g.alpha, w), dtype=np.uint8)
        u_f[fib.reshape(-1)] = u_fiber.reshape(-1, w)
        out = np.zeros((g.alpha, w), dtype=np.uint8)
        out[planes] = u_f[planes]             # diagonal: stored uncoupled
        for x in range(g.q):
            if x == x0:
                continue
            zs = fib[x]                       # non-repair fiber layers:
            i = y0 * g.q + x                  # C = (1+g^2) U + g C_pair
            pair_c = c[i, planes] if i < self.n else np.uint8(0)
            out[zs] = g.mul_1g2[u_f[zs]] ^ g.mul_gamma[pair_c]
        return out

    # -- degraded interval reads -------------------------------------------
    def interval_plan(self, present: tuple, f: int, offset: int,
                      length: int, shard_size: int) -> IntervalPlan:
        """Cheapest correct fetch spec for a degraded read of
        [offset, offset+length) of lost shard f: the repair-plane path
        when every other shard is reachable (~2(n-1) layer slices vs
        plain RS's d), else a closure-restricted general decode over d
        survivors."""
        g = self.grid
        s = shard_size // self.alpha
        if shard_size % self.alpha or length <= 0:
            raise ValueError(f"bad msr interval (shard {shard_size}, "
                             f"alpha {self.alpha}, len {length})")
        lo, hi = offset // s, (offset + length - 1) // s
        inner = (offset - lo * s, offset + length - hi * s) if lo == hi \
            else (0, s)
        want = np.arange(lo, hi + 1)
        helpers = set(range(self.n)) - {f}
        if g.q >= 2 and helpers <= set(present):
            reps = np.unique(g.plane_of(f, want))
            fetch: dict[int, set] = {i: set(reps.tolist()) for i in helpers}
            x0, y0 = g.coords(f)
            for i in helpers | set(range(self.n, g.nbar)):
                if g.ys[i] == y0:
                    continue
                paired = ~g.unpaired[i, reps]
                for z in reps[paired]:
                    pnode = int(g.pair_node[i, z])
                    if pnode < self.n:
                        fetch[pnode].add(int(g.pair_layer[i, z]))
            return IntervalPlan("repair", f, offset, length, shard_size,
                                self.alpha, inner,
                                {i: sorted(v) for i, v in fetch.items()},
                                planes=reps)
        used = tuple(sorted(set(present) - {f}))[: self.d]
        if len(used) < self.d:
            raise ValueError(
                f"need {self.d} survivors for a degraded msr read, "
                f"have {len(used)}")
        closure, fetch_a = self.read_closure(used, want)
        return IntervalPlan("general", f, offset, length, shard_size,
                            self.alpha, inner,
                            {i: v.tolist() for i, v in fetch_a.items()},
                            used=used, closure=closure)

    def interval_decode(self, plan: IntervalPlan,
                        fetched: "dict[int, bytes]") -> bytes:
        """fetched[sid] = the plan's layer slices for that survivor,
        concatenated in plan.fetch[sid] order (each slice u1-u0 wide).
        Returns the lost shard's [offset, offset+length) bytes.

        The dense decode state is [nbar, alpha, window]: the inner span
        is processed in chunks that cap it near 8 MB (every relation is
        elementwise along the inner axis, so chunking is exact)."""
        g = self.grid
        u0, u1 = plan.inner
        w = u1 - u0
        s = plan.shard_size // self.alpha
        wmax = max(1, (8 << 20) // (g.nbar * g.alpha))
        end = plan.offset + plan.length
        lo, hi = plan.offset // s, (end - 1) // s
        res = np.empty(plan.length, dtype=np.uint8)
        for c0 in range(0, w, wmax):
            cw = min(wmax, w - c0)
            c = np.zeros((g.nbar, g.alpha, cw), dtype=np.uint8)
            for sid, layer_ids in plan.fetch.items():
                buf = np.frombuffer(fetched[sid], dtype=np.uint8)
                if len(buf) != len(layer_ids) * w:
                    raise ValueError(f"short fragment from shard {sid}")
                sl = buf.reshape(len(layer_ids), w)[:, c0:c0 + cw]
                c[sid, np.asarray(layer_ids, dtype=np.int64)] = sl
            if plan.mode == "repair":
                row = self.repair_decode(c, plan.f, planes=plan.planes)
            else:
                self.decode_coupled(c, plan.used, layers=plan.closure)
                row = c[plan.f]
            # copy each wanted layer's overlap with this inner chunk —
            # O(layers) slice arithmetic, no per-byte index arrays
            for z in range(lo, hi + 1):
                a = max(max(plan.offset, z * s) - z * s, u0 + c0)
                b = min(min(end, (z + 1) * s) - z * s, u0 + c0 + cw)
                if a < b:
                    res[z * s + a - plan.offset:
                        z * s + b - plan.offset] = \
                        row[z, a - (u0 + c0):b - (u0 + c0)]
        return res.tobytes()


def _register():
    register_coder("msr", ProductMatrixCoder)


_register()
