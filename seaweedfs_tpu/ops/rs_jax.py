"""Reed-Solomon encode / reconstruct on device (pure JAX; XLA-fused).

Replaces the reference's CPU hot loop — klauspost/reedsolomon's AVX2
``Encode``/``Reconstruct`` called per 256 KB batch from
weed/storage/erasure_coding/ec_encoder.go:166-196 (`encodeDataOneBatch`) and
weed/storage/store_ec.go:402 (`ReconstructData`) — with one batched device
matmul over thousands of stripes.

Formulation (see ops/gf8.py): GF(2^8) shard arithmetic expands over GF(2) to

    out_bits[8m, N] = B[8m, 8k] @ in_bits[8k, N]   (mod 2)

where in_bits is the LSB-first bit-unpacking of the shard bytes. On TPU the
matmul runs on the MXU in int8 with int32 accumulation (sums <= 8k < 2^31, so
``& 1`` after accumulation is exact). The unpack (shift+and) and repack
(weighted sum over the bit axis, itself a tiny matmul) are elementwise VPU ops
XLA fuses around the dot. HBM traffic stays at (d+p)/d bytes per data byte —
the 8x bit expansion lives only in registers/VMEM.

All functions are shape-polymorphic in the batch/length axes and jitted by the
caller; matrices are compile-time constants baked in as literals.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import gf8

_BIT_SHIFTS = tuple(range(8))


def unpack_bits(data: jax.Array) -> jax.Array:
    """[..., k, L] uint8 -> [..., 8k, L] int8 bits, LSB-first per byte."""
    shifts = jnp.asarray(_BIT_SHIFTS, dtype=jnp.uint8).reshape(8, 1)
    bits = (data[..., :, None, :] >> shifts) & jnp.uint8(1)
    shape = (*data.shape[:-2], data.shape[-2] * 8, data.shape[-1])
    return bits.astype(jnp.int8).reshape(shape)


def pack_bits(bits: jax.Array) -> jax.Array:
    """[..., 8m, L] int{8,32} bits -> [..., m, L] uint8, LSB-first."""
    shape = (*bits.shape[:-2], bits.shape[-2] // 8, 8, bits.shape[-1])
    b = bits.reshape(shape).astype(jnp.uint8)
    weights = jnp.asarray([1 << s for s in _BIT_SHIFTS], dtype=jnp.uint8)
    return jnp.einsum("...bl,b->...l", b, weights)


def apply_bitmatrix(bmat: jax.Array, data: jax.Array) -> jax.Array:
    """GF(2^8) matrix application via GF(2) matmul.

    bmat: [8m, 8k] int8 (from gf8.expand_to_bits); data: [..., k, L] uint8.
    Returns [..., m, L] uint8.
    """
    bits = unpack_bits(data)  # [..., 8k, L]
    acc = jnp.einsum(
        "pk,...kl->...pl", bmat, bits, preferred_element_type=jnp.int32
    )
    return pack_bits(acc & 1)


@functools.lru_cache(maxsize=128)
def _parity_bitmatrix(d: int, p: int) -> np.ndarray:
    m = gf8.expand_to_bits(gf8.parity_matrix(d, p)).astype(np.int8)
    m.setflags(write=False)
    return m


@functools.lru_cache(maxsize=512)
def _decode_bitmatrix(d: int, p: int, present: tuple[int, ...], wanted: tuple[int, ...]) -> np.ndarray:
    rec = gf8.decode_matrix(d, p, list(present))  # [n, d]
    m = gf8.expand_to_bits(rec[list(wanted), :]).astype(np.int8)
    m.setflags(write=False)
    return m


def encode(data: jax.Array, d: int, p: int) -> jax.Array:
    """data [..., d, L] uint8 -> parity [..., p, L] uint8."""
    if data.shape[-2] != d:
        raise ValueError(f"data shard axis {data.shape[-2]} != d={d}")
    return apply_bitmatrix(jnp.asarray(_parity_bitmatrix(d, p)), data)


def reconstruct(
    survivors: jax.Array,
    present: tuple[int, ...],
    wanted: tuple[int, ...],
    d: int,
    p: int,
) -> jax.Array:
    """Rebuild shards `wanted` from the first d surviving shards.

    survivors: [..., d, L] uint8 — rows are shards sorted(present)[:d].
    present/wanted are static (baked into the compiled matrix), matching how
    the reference inverts the matrix once per shard-loss pattern.
    """
    bmat = _decode_bitmatrix(d, p, tuple(sorted(present)[:d]), tuple(wanted))
    return apply_bitmatrix(jnp.asarray(bmat), survivors)


@functools.partial(jax.jit, static_argnums=(1, 2))
def encode_jit(data: jax.Array, d: int, p: int) -> jax.Array:
    return encode(data, d, p)


@functools.partial(jax.jit, static_argnums=(1, 2, 3, 4))
def reconstruct_jit(survivors, present, wanted, d, p):
    return reconstruct(survivors, present, wanted, d, p)
