"""Pallas TPU kernel for GF(2^8) Reed-Solomon encode / reconstruct.

The XLA einsum path (ops/rs_jax.py) expresses the GF(2) bit-matmul as
unpack -> einsum -> pack and trusts the compiler to fuse; measured on a v5e
it sustains ~40 GB/s. This kernel pins the whole pipeline in VMEM per tile
and reformulates the two elementwise stages so they vectorize:

* **Plane-major bitcast unpack.** `pltpu.bitcast` reinterprets groups of 4
  sublanes (rows) as one int32 row, so `(x32 >> s) & 0x01010101` extracts
  bit s of FOUR bytes per lane-op. Eight shift/mask passes produce the bit
  planes at ~1/6 the VPU cost of per-element int32 unpacking. The planes
  concatenate plane-major (row s*dp + r = bit s of data row r), and the
  encode matrix's columns are permuted once on the host to match.
* **MXU bit-matmul.** int8 x int8 -> int32 dot of the permuted bit-matrix
  [8m, 8*dp] with the bit planes [8*dp, T]; sums <= 8d < 2^31 so `& 1`
  recovers the GF(2) product exactly.
* **Pack via a second tiny dot.** Recombining 8 parity-bit rows into bytes
  is itself a matmul with a constant [m, 8m] weight matrix (1 << s at
  column 8j+s) — cheaper on the MXU than a cross-sublane shift/sum on the
  VPU (measured: 0.5 ms vs 1.0 ms per 160 MB).

HBM sees the input bytes once and the output bytes once: (d+m)/d bytes per
data byte. Measured end to end (chained-marginal, 160 MB batches, RS 10+4):
~118 GB/s vs ~40 GB/s for the einsum path on the same harness — ~3x.

Replaces: klauspost/reedsolomon's AVX2 galMulSlicesAvx2 loops invoked from
reference weed/storage/erasure_coding/ec_encoder.go:183 (`enc.Encode`) and
weed/storage/store_ec.go:402 (`ReconstructData`).

Availability: the compiled path needs a real TPU; `available()` gates it and
ops/coder.JaxCoder falls back to rs_jax elsewhere. Tests run the kernel in
interpreter mode on CPU so its logic is covered everywhere.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import gf8

DEFAULT_TILE = 1 << 15  # lane-dim tile; best measured on v5e (sweep 2K-32K)


@functools.lru_cache(maxsize=1)
def available() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # noqa: BLE001 — no backend at all
        return False


@functools.lru_cache(maxsize=512)
def _plane_major_bitmatrix(key: tuple) -> np.ndarray:
    """Permute a [8m, 8d] byte-major bit-matrix to plane-major padded cols.

    key = (kind, d, p, present, wanted); column s*dp + r takes byte-major
    column r*8 + s (dp = d rounded up to 4 for the sublane bitcast).
    """
    kind, d, p, present, wanted = key
    if kind == "enc":
        bm = gf8.expand_to_bits(gf8.parity_matrix(d, p)).astype(np.int8)
    else:
        rec = gf8.decode_matrix(d, p, list(present))
        bm = gf8.expand_to_bits(rec[list(wanted), :]).astype(np.int8)
    m8 = bm.shape[0]
    dp = (d + 3) // 4 * 4
    out = np.zeros((m8, 8 * dp), dtype=np.int8)
    for r in range(d):
        for s in range(8):
            out[:, s * dp + r] = bm[:, r * 8 + s]
    out.setflags(write=False)
    return out


@functools.lru_cache(maxsize=64)
def _pack_matrix(m: int) -> np.ndarray:
    """[m, 8m] int8 weights recombining LSB-first bit rows into bytes.

    1 << 7 wraps to -128 in int8; the final uint8 cast of the int32
    accumulator makes the sign irrelevant (mod-256 arithmetic).
    """
    pm = np.zeros((m, 8 * m), dtype=np.int16)
    for j in range(m):
        for s in range(8):
            pm[j, 8 * j + s] = 1 << s
    out = pm.astype(np.int8)
    out.setflags(write=False)
    return out


def _make_kernel(d: int, dp: int, tile: int):
    def kernel(bmat_ref, packm_ref, seed_ref, data_ref, out_ref):
        data = data_ref[0] ^ seed_ref[0].astype(jnp.uint8)
        if dp != d:
            data = jnp.concatenate(
                [data, jnp.zeros((dp - d, tile), jnp.uint8)], axis=0)
        x32 = pltpu.bitcast(data, jnp.int32)              # [dp/4, T]
        planes = [
            pltpu.bitcast(((x32 >> s) & 0x01010101).astype(jnp.int32),
                          jnp.uint8)
            for s in range(8)
        ]
        bits = jnp.concatenate(planes, axis=0).astype(jnp.int8)  # [8dp, T]
        acc = lax.dot_general(bmat_ref[:], bits, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.int32)
        pb = (acc & 1).astype(jnp.int8)                   # [8m, T] 0/1
        packed = lax.dot_general(packm_ref[:], pb, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.int32)
        out_ref[0] = packed.astype(jnp.uint8)
    return kernel


def _pick_tile(c: int, tile: int) -> int:
    if c % tile == 0:
        return tile
    # largest 128-aligned divisor of c no bigger than the requested tile;
    # Mosaic requires the lane block be 128-divisible or the full dim
    return next((t for t in range(tile - tile % 128, 0, -128)
                 if c % t == 0), c)


def _apply(bmat_key: tuple, data: jax.Array, seed: jax.Array, tile: int,
           interpret: bool) -> jax.Array:
    b, d, c = data.shape
    bmat = _plane_major_bitmatrix(bmat_key)
    m = bmat.shape[0] // 8
    packm = _pack_matrix(m)
    dp = (d + 3) // 4 * 4
    tile = _pick_tile(c, tile)
    return pl.pallas_call(
        _make_kernel(d, dp, tile),
        grid=(b, c // tile),
        in_specs=[
            pl.BlockSpec(bmat.shape, lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(packm.shape, lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, d, tile), lambda i, j: (i, 0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, m, tile), lambda i, j: (i, 0, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, m, c), jnp.uint8),
        interpret=interpret,
    )(jnp.asarray(bmat), jnp.asarray(packm), seed, data)


@functools.partial(jax.jit, static_argnums=(1, 2, 3, 4))
def encode_jit(data: jax.Array, d: int, p: int, tile: int = DEFAULT_TILE,
               interpret: bool = False) -> jax.Array:
    """data [B, d, C] uint8 -> parity [B, p, C] uint8 (Pallas kernel)."""
    return _apply(("enc", d, p, (), ()), data, jnp.zeros(1, jnp.int32),
                  tile, interpret)


@functools.partial(jax.jit, static_argnums=(2, 3, 4, 5))
def encode_seeded_jit(data: jax.Array, seed: jax.Array, d: int, p: int,
                      tile: int = DEFAULT_TILE,
                      interpret: bool = False) -> jax.Array:
    """Benchmark entry: xors `seed` into the data INSIDE the kernel so
    repeated timing loops can defeat CSE without an extra HBM pass."""
    return _apply(("enc", d, p, (), ()), data, seed, tile, interpret)


@functools.partial(jax.jit, static_argnums=(1, 2, 3, 4, 5, 6))
def reconstruct_jit(survivors: jax.Array, present: tuple, wanted: tuple,
                    d: int, p: int, tile: int = DEFAULT_TILE,
                    interpret: bool = False) -> jax.Array:
    """survivors [B, d, C] (rows = sorted(present)[:d]) -> [B, |wanted|, C]."""
    key = ("rec", d, p, tuple(sorted(present)[:d]), tuple(wanted))
    return _apply(key, survivors, jnp.zeros(1, jnp.int32), tile, interpret)


@functools.partial(jax.jit, static_argnums=(2, 3, 4, 5, 6, 7))
def reconstruct_seeded_jit(survivors: jax.Array, seed: jax.Array,
                           present: tuple, wanted: tuple, d: int, p: int,
                           tile: int = DEFAULT_TILE,
                           interpret: bool = False) -> jax.Array:
    """Benchmark entry: like encode_seeded_jit, xors `seed` in-kernel so a
    timing fori_loop cannot hoist the reconstruction as loop-invariant."""
    key = ("rec", d, p, tuple(sorted(present)[:d]), tuple(wanted))
    return _apply(key, survivors, seed, tile, interpret)
