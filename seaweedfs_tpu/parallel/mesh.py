"""Device mesh construction for the EC compute plane.

Mesh axes:
* ``data``  — stripe-batch data parallelism: different volumes/rows on
  different chips (the analogue of the reference spreading ec.encode jobs
  across volume servers, command_ec_encode.go:113-126).
* ``shard`` — shard parallelism: the n=d+p output shards are partitioned
  across chips, mirroring how shards live on distinct servers
  (balancedEcDistribution, command_ec_encode.go:333). Rebuild all_gathers
  survivors along this axis over ICI — the device-side analogue of the
  cross-host shard fetch in store_ec.go:367-400.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh


def build_mesh(n_devices: int | None = None, shard_axis: int | None = None,
               devices=None) -> Mesh:
    """2-D ('data', 'shard') mesh over the first n devices.

    shard_axis defaults to min(n, 4) rounded down to a divisor of n, so a
    single chip yields a 1x1 mesh and 8 virtual devices a 2x4 mesh.
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise RuntimeError(
                f"requested {n_devices} devices, only {len(devices)} available "
                f"(for virtual CPU devices, XLA_FLAGS="
                f"--xla_force_host_platform_device_count must be set at "
                f"process start)")
        devices = devices[:n_devices]
    n = len(devices)
    if shard_axis is None:
        shard_axis = 1
        for cand in (4, 2):
            if n % cand == 0 and cand <= n:
                shard_axis = cand
                break
    if n % shard_axis:
        raise ValueError(f"shard axis {shard_axis} does not divide {n} devices")
    arr = np.asarray(devices).reshape(n // shard_axis, shard_axis)
    return Mesh(arr, ("data", "shard"))
