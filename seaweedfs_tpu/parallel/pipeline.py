"""Sharded EC compute steps over a ('data', 'shard') mesh via shard_map.

The multi-chip execution plan (SURVEY.md §5): stripe batches ride the
``data`` axis (pure data parallelism — volumes are independent), the n
output shards are partitioned along the ``shard`` axis (each device computes
and "owns" a subset of shards, like servers own shards in the reference), and
rebuild all_gathers survivors along ``shard`` over ICI before the masked
inverse matmul — the device-side analogue of store_ec.go:367-400's fan-out
shard fetch. Scrub reduces mismatch counts with a psum over the whole mesh.

All entry points take/return global arrays with NamedShardings; shapes are
static per (geometry, batch) so XLA compiles each once.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import gf8
from ..ops.crc32c import device_crc_states
from ..ops.rs_jax import pack_bits, unpack_bits

try:  # jax >= 0.4.31 exports it at top level; older trees ship experimental
    _shard_map = jax.shard_map
except AttributeError:  # pragma: no cover - version-dependent import
    from jax.experimental.shard_map import shard_map as _shard_map


def _ceil_to(x: int, m: int) -> int:
    return (x + m - 1) // m * m


# -- encode -----------------------------------------------------------------

@functools.lru_cache(maxsize=32)
def _padded_parity_bitmatrix(d: int, p: int, p_pad: int) -> np.ndarray:
    full = gf8.expand_to_bits(gf8.parity_matrix(d, p)).astype(np.int8)
    out = np.zeros((8 * p_pad, 8 * d), dtype=np.int8)
    out[: 8 * p, :] = full
    out.setflags(write=False)
    return out


def encode_sharded(mesh: Mesh, data: jax.Array, d: int, p: int) -> jax.Array:
    """data [B, d, L] -> parity [B, p_pad, L]; B over 'data', parity rows
    partitioned over 'shard' (p padded up to the shard-axis size)."""
    n_shard = mesh.shape["shard"]
    p_pad = _ceil_to(p, n_shard)
    rows_per = p_pad // n_shard
    bmat = jnp.asarray(_padded_parity_bitmatrix(d, p, p_pad))

    def kernel(x):  # x: [B_loc, d, L] replicated over 'shard'
        idx = jax.lax.axis_index("shard")
        sub = jax.lax.dynamic_slice_in_dim(bmat, idx * rows_per * 8, rows_per * 8, 0)
        bits = unpack_bits(x)  # [B_loc, 8d, L]
        acc = jnp.einsum("pk,bkl->bpl", sub, bits,
                         preferred_element_type=jnp.int32)
        return pack_bits(acc & 1)  # [B_loc, rows_per, L]

    fn = _shard_map(kernel, mesh=mesh,
                       in_specs=P("data", None, None),
                       out_specs=P("data", "shard", None))
    return fn(data)


# -- rebuild ----------------------------------------------------------------

@functools.lru_cache(maxsize=64)
def _padded_decode_bitmatrix(d: int, p: int, present: tuple[int, ...],
                             n_pad: int) -> np.ndarray:
    """Decode matrix producing ALL n_pad shard slots from d survivors."""
    rec = gf8.decode_matrix(d, p, list(present))  # [n, d]
    full = gf8.expand_to_bits(rec).astype(np.int8)
    out = np.zeros((8 * n_pad, 8 * d), dtype=np.int8)
    out[: 8 * (d + p), :] = full
    out.setflags(write=False)
    return out


def rebuild_sharded(mesh: Mesh, shards: jax.Array,
                    present: tuple[int, ...], d: int, p: int) -> jax.Array:
    """shards [B, n_pad, L] (shard axis partitioned over 'shard'; lost rows
    are garbage) -> all n_pad shards recomputed, same layout.

    Each device all_gathers the survivor rows along 'shard' (ICI) and then
    reconstructs only the shard rows it owns.
    """
    n = d + p
    n_shard = mesh.shape["shard"]
    n_pad = shards.shape[1]
    assert n_pad % n_shard == 0 and n_pad >= n
    rows_per = n_pad // n_shard
    use = tuple(sorted(present)[:d])
    bmat = jnp.asarray(_padded_decode_bitmatrix(d, p, use, n_pad))
    sel = jnp.asarray(np.array(use, dtype=np.int32))

    def kernel(x):  # x: [B_loc, rows_per, L] — this device's shard rows
        allsh = jax.lax.all_gather(x, "shard", axis=1, tiled=True)  # [B, n_pad, L]
        survivors = jnp.take(allsh, sel, axis=1)  # [B, d, L]
        idx = jax.lax.axis_index("shard")
        sub = jax.lax.dynamic_slice_in_dim(bmat, idx * rows_per * 8, rows_per * 8, 0)
        bits = unpack_bits(survivors)
        acc = jnp.einsum("pk,bkl->bpl", sub, bits,
                         preferred_element_type=jnp.int32)
        return pack_bits(acc & 1)

    fn = _shard_map(kernel, mesh=mesh,
                       in_specs=P("data", "shard", None),
                       out_specs=P("data", "shard", None))
    return fn(shards)


# -- scrub ------------------------------------------------------------------

def scrub_sharded(mesh: Mesh, blocks: jax.Array, expected_states: jax.Array,
                  chunk: int = 256) -> jax.Array:
    """Batched CRC scrub: blocks [B, L] (left-zero-padded needles), expected
    raw CRC states [B] uint32. Returns global mismatch count (replicated).

    B is sharded across the entire mesh (both axes) — scrub is pure dp; the
    reduction is one psum. Reference analogue: volume_checking.go:91 per
    needle, volume.check.disk over replicas.
    """

    def kernel(x, exp):
        states = device_crc_states(x, chunk)
        bad = jnp.sum((states != exp).astype(jnp.int32))
        return jax.lax.psum(bad, ("data", "shard"))

    fn = _shard_map(kernel, mesh=mesh,
                       in_specs=(P(("data", "shard"), None), P(("data", "shard"))),
                       out_specs=P())
    return fn(blocks, expected_states)


# -- helpers ----------------------------------------------------------------

def shard_put(mesh: Mesh, arr: np.ndarray, spec: P) -> jax.Array:
    return jax.device_put(arr, NamedSharding(mesh, spec))


class MeshCoder:
    """ErasureCoder facade over the mesh-sharded encode: the seam that lets
    the disk-fed streaming pipeline (ec/stream.encode_volumes) batch host
    slabs straight onto a multi-chip mesh. Batches ride the 'data' axis,
    parity rows the 'shard' axis — the same layout dryrun_multichip
    validates, now fed from real volume files (SURVEY §5 'sharded stripe
    pipelines over ICI with DCN fan-in')."""

    async_dispatch = True  # device arrays materialize on np.asarray

    def __init__(self, mesh: Mesh, d: int, p: int):
        self.mesh = mesh
        self.d = d
        self.p = p
        self.n = d + p

    def encode(self, data) -> jax.Array:
        b = data.shape[0]
        n_data = self.mesh.shape["data"]
        if b % n_data:  # pad batch to the data-axis multiple
            pad = _ceil_to(b, n_data) - b
            data = np.concatenate(
                [data, np.zeros((pad,) + data.shape[1:], np.uint8)])
            return encode_sharded(self.mesh, self._put(data),
                                  self.d, self.p)[:b, :self.p, :]
        return encode_sharded(self.mesh, self._put(data),
                              self.d, self.p)[:, :self.p, :]

    def _put(self, data) -> jax.Array:
        """Host batch -> mesh, split along 'data' at transfer time.

        An explicit NamedSharding device_put sends each device only its
        B/n_data batch rows (parallel host->device DMA); a plain
        jnp.asarray would land the whole array on one device and reshard
        over the interconnect inside the jit."""
        if isinstance(data, jax.Array):
            return data
        return jax.device_put(
            data, NamedSharding(self.mesh, P("data", None, None)))

    def reconstruct(self, survivors, present, wanted):
        """survivors [B, d, L] = shard rows sorted(present)[:d]."""
        present = tuple(sorted(present))[:self.d]
        b, _, l = survivors.shape
        n_shard = self.mesh.shape["shard"]
        n_pad = _ceil_to(self.n, n_shard)
        wiped = np.zeros((b, n_pad, l), dtype=np.uint8)
        wiped[:, list(present), :] = np.asarray(survivors)
        rebuilt = rebuild_sharded(self.mesh, jnp.asarray(wiped), present,
                                  self.d, self.p)
        return rebuilt[:, list(wanted), :]


def _all_device_mesh_coder(d: int, p: int) -> MeshCoder:
    """Registry factory: MeshCoder over every visible device, so the volume
    server CLI can ask for multi-chip encode with `-coder mesh` exactly like
    any other coder name (ops.coder.get_coder lazily imports this module)."""
    from .mesh import build_mesh
    return MeshCoder(build_mesh(), d, p)


from ..ops.coder import register_coder  # noqa: E402 — avoid cycle at import

register_coder("mesh", _all_device_mesh_coder)
