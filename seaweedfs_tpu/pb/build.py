"""Generate *_pb2.py from the .proto files (no grpcio-tools in the image;
plain protoc message codegen + hand-rolled generic gRPC registration in
utils/rpc.py). Run: python -m seaweedfs_tpu.pb.build"""

from __future__ import annotations

import pathlib
import subprocess

PB_DIR = pathlib.Path(__file__).parent


def build() -> None:
    protos = sorted(PB_DIR.glob("*.proto"))
    subprocess.run(
        ["protoc", f"-I{PB_DIR}", f"--python_out={PB_DIR}",
         *[str(p) for p in protos]],
        check=True)
    print(f"generated {len(protos)} proto modules in {PB_DIR}")


if __name__ == "__main__":
    build()
