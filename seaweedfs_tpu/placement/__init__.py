"""Scale-out placement & rebalance plane (ISSUE 13).

One scoring core (engine.py) shared by admission-time placement
(VolumeGrowth replica picks, VolumeLayout.pick_for_write, ec.encode's
rack-capped shard spread) and the rebalance planner (plan.py), executed
byte-costed and maintenance-class-tagged by executor.py. The shell's
volume.balance / ec.balance are thin shells over this package.
"""

from .engine import (NodeView, Snapshot, pick_best, rank, score,
                     snapshot_from_servers, snapshot_from_topology,
                     spread_ec_shards)
from .executor import BalanceExecutor
from .plan import (DEFAULT_CROSS_RACK_LIMIT, DEFAULT_TARGET_SKEW,
                   MOVE_EC, MOVE_VOLUME, Move, MovePlan,
                   build_ec_balance_plan, build_volume_balance_plan)

__all__ = [
    "NodeView", "Snapshot", "score", "rank", "pick_best",
    "snapshot_from_servers", "snapshot_from_topology",
    "spread_ec_shards",
    "Move", "MovePlan", "MOVE_VOLUME", "MOVE_EC",
    "DEFAULT_TARGET_SKEW", "DEFAULT_CROSS_RACK_LIMIT",
    "build_volume_balance_plan", "build_ec_balance_plan",
    "BalanceExecutor",
]
