"""Placement engine: ONE scoring core for admission-time placement and
rebalance.

The scale-out plane's first principle (ROADMAP; the Facebook
warehouse-cluster study, PAPERS arXiv:1309.0186) is that placement is a
*cost* decision, not a count decision: repair and rebalance traffic
dominate cross-rack links at scale, so where a replica / EC shard / new
volume lands must weigh

  * free capacity (free volume slots as the byte-capacity proxy the
    heartbeat actually carries),
  * current BYTE load — live volume bytes plus EC shard bytes, so a
    shard-heavy server stops masquerading as empty (the old
    volume.balance counted only volume_infos and kept piling volumes
    onto EC-loaded nodes),
  * failure-domain spread (rack, then DC), and
  * live circuit-breaker state (a half-dead node must not win a
    placement just because it is empty — it is empty *because* it is
    half-dead).

Every consumer — VolumeGrowth replica picks, VolumeLayout's
pick_for_write, ec.encode's shard spread, and the rebalance planner
(placement/plan.py) — scores candidates through `score()` so placement
and balance can never disagree about what "loaded" means.

The scoring formula (documented in README "Placement & rebalance"):

    score(node) =  W_FREE    * free_slots / max_slots
                 - W_LOAD    * load_bytes / max(load_bytes over cohort)
                 - W_RACK    * [node.rack in avoid_racks]
                 - W_DC      * [node.dc   in avoid_dcs]
                 - W_BREAKER * breaker_penalty(node)    # open=1, half=¼

Higher is better; exact ties break randomly through the caller's seeded
RNG so placement is reproducible under test and spreads under load.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field

from ..utils.log import logger

log = logger("placement")

# scoring weights — spread beats load beats free space beats breaker
# nuance; a fully-open breaker is close to disqualifying
W_FREE = 1.0
W_LOAD = 0.5
W_RACK = 1.5
W_DC = 0.75
W_BREAKER = 2.0
# geo link-cost term (PR 19): kept strictly below W_DC so failure-
# domain spread still beats cheapness — geo only ORDERS candidates that
# spread equally (the cheapest other-DC wins, never the same DC twice)
W_GEO = 0.6

# fallback per-shard byte estimate divisor when no geometry probe
# reached a stripe: a shard of RS(d,p) holds ~1/d of the volume, and
# the reference default d=10 makes a conservative (small) estimate —
# better than the zero the old balance code effectively used
DEFAULT_SHARD_DIVISOR = 10


@dataclass
class NodeView:
    """One volume server as the engine scores it — buildable from a live
    master Topology (snapshot_from_topology) or a shell VolumeList dump
    (snapshot_from_servers), so master-side placement and shell-side
    rebalance run the same arithmetic."""
    id: str
    rack: str = ""
    dc: str = ""
    grpc_port: int = 0
    max_slots: int = 0
    free_slots: int = 0
    # vid -> {"size": int, "collection": str}
    volumes: dict = field(default_factory=dict)
    # vid -> {"collection": str, "shard_ids": [int], "shard_bytes": int}
    ec_shards: dict = field(default_factory=dict)

    @property
    def volume_bytes(self) -> int:
        return sum(v["size"] for v in self.volumes.values())

    @property
    def ec_bytes(self) -> int:
        return sum(len(s["shard_ids"]) * s["shard_bytes"]
                   for s in self.ec_shards.values())

    @property
    def load_bytes(self) -> int:
        """The honest load: volume bytes AND EC shard bytes (the
        satellite fix — an EC-shard-heavy server is not empty)."""
        return self.volume_bytes + self.ec_bytes

    @property
    def free_ratio(self) -> float:
        return self.free_slots / self.max_slots if self.max_slots else 0.0


@dataclass
class Snapshot:
    """One topology snapshot the planner/engine works against. Built
    once per operation; callers update it locally as moves land instead
    of re-collecting (re-collecting mid-plan races heartbeats)."""
    nodes: list

    def by_id(self) -> dict:
        return {n.id: n for n in self.nodes}

    def racks(self) -> dict:
        out: dict[str, list] = {}
        for n in self.nodes:
            out.setdefault(n.rack, []).append(n)
        return out

    def max_load(self) -> int:
        return max((n.load_bytes for n in self.nodes), default=0)


def _breaker_penalty(node_id: str) -> float:
    """0 = healthy, ¼ = half-open (probing), 1 = open (failing)."""
    try:
        from ..utils import retry
        state = retry.breaker(node_id).state
        if state == retry.OPEN:
            return 1.0
        if state == retry.HALF_OPEN:
            return 0.25
    except Exception:  # noqa: BLE001  # swtpu-lint: disable=silent-except (breaker registry is best-effort advice)
        pass
    return 0.0


def geo_penalty(costs, origin, node: NodeView) -> float:
    """Normalized [0, 1] link-cost penalty of reaching `node` from
    `origin` = (dc, rack): 0 on the cheapest link class, 1 on the
    priciest known (cross-DC incl. overrides). None costs/origin -> 0,
    so geo-blind callers pay nothing."""
    if costs is None or origin is None:
        return 0.0
    c = costs.cost(origin[0], origin[1], node.dc, node.rack)
    worst = max([costs.cross_dc, *costs.overrides.values()])
    span = worst - costs.intra_rack
    return (c - costs.intra_rack) / span if span > 0 else 0.0


def score(node: NodeView, cohort_max_load: int = 0,
          avoid_racks=(), avoid_dcs=(), costs=None, origin=None) -> float:
    """The one scoring formula (module docstring). `cohort_max_load`
    normalizes the byte-load term across the candidate set; `costs` (a
    geo LinkCostModel) + `origin` (dc, rack) add the W_GEO-weighted
    link-cost term for placements that copy bytes from somewhere."""
    s = W_FREE * node.free_ratio
    if cohort_max_load > 0:
        s -= W_LOAD * (node.load_bytes / cohort_max_load)
    if node.rack and node.rack in avoid_racks:
        s -= W_RACK
    if node.dc and node.dc in avoid_dcs:
        s -= W_DC
    s -= W_GEO * geo_penalty(costs, origin, node)
    s -= W_BREAKER * _breaker_penalty(node.id)
    return s


def rank(nodes: list, rng: "random.Random | None" = None,
         avoid_racks=(), avoid_dcs=(), costs=None, origin=None) -> list:
    """Candidates best-first; exact-score ties shuffled by `rng` (seeded
    by tests, module-global `random` otherwise) then id-ordered so a
    seeded run is fully deterministic."""
    if not nodes:
        return []
    rng = rng or random
    cohort_max = max(n.load_bytes for n in nodes)
    jitter = {n.id: rng.random() for n in nodes}
    return sorted(nodes, key=lambda n: (
        -score(n, cohort_max, avoid_racks, avoid_dcs, costs, origin),
        jitter[n.id], n.id))


def pick_best(nodes: list, rng: "random.Random | None" = None,
              avoid_racks=(), avoid_dcs=(), costs=None, origin=None):
    """The single best candidate (ties random through rng), or None."""
    ranked = rank(nodes, rng, avoid_racks, avoid_dcs, costs, origin)
    return ranked[0] if ranked else None


# -- snapshot builders -------------------------------------------------------

def snapshot_from_servers(servers: list, shard_bytes_of=None,
                          default_shard_bytes: int = 0) -> Snapshot:
    """Build a Snapshot from `CommandEnv.collect_volume_servers()` dicts
    (the shell/VolumeList side). `shard_bytes_of(vid, collection) ->
    int|None` is an optional read-only probe (maintenance's
    VolumeEcShardsInfo sweep) for real per-shard bytes; without an
    answer the per-shard size falls back to `default_shard_bytes`."""
    from .. import ec as ec_accounting
    shard_bytes_memo: dict[int, int] = {}

    def _shard_bytes(vid: int, collection: str) -> int:
        if vid in shard_bytes_memo:
            return shard_bytes_memo[vid]
        size = None
        if shard_bytes_of is not None:
            try:
                size = shard_bytes_of(vid, collection)
            except Exception as e:  # noqa: BLE001 — probe is best-effort
                log.debug("shard byte probe for %s failed: %s", vid, e)
        shard_bytes_memo[vid] = size or default_shard_bytes
        return shard_bytes_memo[vid]

    nodes = []
    for srv in servers:
        view = NodeView(id=srv["id"], rack=srv.get("rack", ""),
                        dc=srv.get("dc", ""),
                        grpc_port=srv.get("grpc_port", 0))
        for disk in srv["disks"].values():
            view.max_slots += disk.max_volume_count
            view.free_slots += disk.free_volume_count
            for v in disk.volume_infos:
                view.volumes[v.id] = {"size": v.size,
                                      "collection": v.collection}
            for s in disk.ec_shard_infos:
                sids = ec_accounting.shard_ids(s.ec_index_bits)
                if not sids:
                    continue
                view.ec_shards[s.id] = {
                    "collection": s.collection, "shard_ids": sids,
                    "shard_bytes": _shard_bytes(s.id, s.collection)}
        nodes.append(view)
    return Snapshot(nodes=sorted(nodes, key=lambda n: n.id))


def view_of_data_node(n, volume_size_limit: int,
                      disk_type: str = "") -> NodeView:
    """ONE NodeView builder for master-side DataNodes — VolumeGrowth
    picks and snapshot_from_topology both call this, so the two can't
    drift on what a node's load means. Slots count only `disk_type`
    disks when given (placement targets a tier); BYTES count every
    disk — load is load wherever it sits. EC shard bytes are estimated
    from the volume size limit (heartbeats don't carry shard sizes)."""
    from .. import ec as ec_accounting
    est_shard = volume_size_limit // DEFAULT_SHARD_DIVISOR
    view = NodeView(
        id=n.id,
        rack=n.rack.id if n.rack else "",
        dc=n.rack.dc.id if n.rack else "",
        grpc_port=n.grpc_port)
    for dtype, d in n.disks.items():
        if not disk_type or dtype == disk_type:
            view.max_slots += d.max_volume_count
            view.free_slots += d.free_slots()
        for vid, v in d.volumes.items():
            view.volumes[vid] = {"size": v.size,
                                 "collection": v.collection}
        for vid, s in d.ec_shards.items():
            sids = ec_accounting.shard_ids(s.shard_bits)
            if sids:
                view.ec_shards[vid] = {
                    "collection": s.collection,
                    "shard_ids": sids,
                    "shard_bytes": est_shard}
    return view


def snapshot_from_topology(topo, disk_type: str = "") -> Snapshot:
    """Build a Snapshot from the master's live Topology (the
    VolumeGrowth / pick_for_write side)."""
    with topo.lock:
        nodes = [view_of_data_node(n, topo.volume_size_limit, disk_type)
                 for n in topo.nodes.values()]
    return Snapshot(nodes=sorted(nodes, key=lambda n: n.id))


# -- EC shard spread ---------------------------------------------------------

def spread_ec_shards(snapshot: Snapshot, n_shards: int, parity: int,
                     rng: "random.Random | None" = None,
                     vid: int = 0, costs=None, origin=None) -> list:
    """Assign each of a stripe's `n_shards` shards to a NodeView such
    that NO RACK holds more than `parity` shards — rack loss then costs
    at most p shards, which RS(d,p) reconstructs: rack loss ≠ data
    loss, for RS(14,2) (16 shards: needs ≥8 racks) and RS(10,4)
    (needs ≥4) alike.

    When the topology simply cannot honor the cap (fewer than
    ceil(n/p) racks — the single-rack dev box), the spread degrades
    gracefully: racks stay as even as possible (minimal max-per-rack)
    and the shortfall is logged once, not raised — encoding must not
    fail because the fleet is small.

    Within the rack constraint, shards go to the best-scoring node
    (shared `score()` core) that holds the fewest shards of this stripe
    so far, so node loss also costs the fewest shards. Returns a list
    of length `n_shards` (node per shard id)."""
    if not snapshot.nodes:
        raise RuntimeError("no volume servers to spread ec shards onto")
    rng = rng or random
    parity = max(1, parity)
    n_racks = len({n.rack for n in snapshot.nodes})
    feasible = n_racks * parity >= n_shards
    if not feasible and n_racks > 1:
        log.warning(
            "ec spread vid=%s: %d racks cannot cap %d shards at %d/rack; "
            "falling back to most-even rack spread", vid, n_racks,
            n_shards, parity)
    rack_count: dict[str, int] = {}
    node_count: dict[str, int] = {}
    cohort_max = snapshot.max_load()
    jitter = {n.id: rng.random() for n in snapshot.nodes}
    out = []
    # even fallback cap when infeasible: ceil(n_shards / n_racks)
    cap = parity if feasible else -(-n_shards // max(1, n_racks))
    for _sid in range(n_shards):
        cands = [n for n in snapshot.nodes
                 if rack_count.get(n.rack, 0) < cap]
        if not cands:
            cands = list(snapshot.nodes)  # cap exhausted: stay even
        best = min(cands, key=lambda n: (
            node_count.get(n.id, 0), rack_count.get(n.rack, 0),
            -score(n, cohort_max, costs=costs, origin=origin),
            jitter[n.id], n.id))
        out.append(best)
        node_count[best.id] = node_count.get(best.id, 0) + 1
        rack_count[best.rack] = rack_count.get(best.rack, 0) + 1
    return out
