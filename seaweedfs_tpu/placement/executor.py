"""Balance executor: run a MovePlan against a live cluster.

The throttling half of the rebalance plane (plan.py orders, this
bounds) — the same shape as maintenance/executor.py because rebalance
traffic IS maintenance traffic:

  * every hop is tagged `qos.CLASS_MAINTENANCE` at the source, so the
    copy/move RPCs admit maintenance-class on the nodes that serve
    them (CopyFile / VolumeEcShardsCopy are already enforcement
    points) and yield to queued foreground work;
  * `max_concurrent` moves in flight (defaults conservative — balance
    is never urgent) and `max_moves` admitted per run, the rest journal
    `balance.skipped` reason=budget and wait for the next sweep;
  * EC moves arrive pre-grouped per (volume, src, dst) pair — ONE
    VolumeEcShardsMove RPC per pair;
  * every move journals `balance.move` with its byte cost and rack
    locality, and feeds SeaweedFS_balance_moves_total{kind} /
    SeaweedFS_balance_bytes_moved_total{cross_rack};
  * dry-run journals `balance.plan` (dry_run=true) and returns without
    creating a single stub: zero RPCs, mutating or otherwise —
    `volume.balance -dryRun` / `ec.balance -dryRun` ride this.
"""

from __future__ import annotations

import contextvars
import threading
from concurrent.futures import ThreadPoolExecutor

from ..utils.log import logger
from .plan import MOVE_EC, MOVE_VOLUME, Move, MovePlan

log = logger("placement.executor")

SKIP_BUDGET = "budget"


class BalanceExecutor:
    """Executes MovePlans through a shell CommandEnv. One instance per
    balance run — the admin lock serializes runs, so unlike the repair
    executor no cross-run cooldown state is needed."""

    def __init__(self, env, max_concurrent: int = 2, max_moves: int = 64):
        self.env = env
        self.max_concurrent = max(1, int(max_concurrent))
        self.max_moves = max(1, int(max_moves))

    def execute(self, plan: MovePlan, dry_run: bool = False) -> dict:
        """Run the plan; returns {done: [...], failed: [...],
        skipped: [...]} summaries (each entry a move dict + outcome)."""
        from ..ops import events
        events.emit("balance.plan", moves=len(plan.moves),
                    total_bytes=plan.total_bytes,
                    cross_rack_bytes=plan.cross_rack_bytes,
                    skew_before=round(plan.skew_before, 3),
                    skew_after=round(plan.skew_after, 3),
                    dry_run=dry_run,
                    order=[{"kind": m.kind, "vid": m.vid, "src": m.src,
                            "dst": m.dst, "bytes": m.bytes_moved}
                           for m in plan.moves])
        summary: dict = {"done": [], "failed": [], "skipped": []}
        if dry_run or not plan.moves:
            return summary
        admitted = plan.moves[:self.max_moves]
        for m in plan.moves[self.max_moves:]:
            events.emit("balance.skipped", severity=events.WARN,
                        reason=SKIP_BUDGET, kind=m.kind, vid=m.vid)
            summary["skipped"].append({**m.to_dict(),
                                       "reason": SKIP_BUDGET})
        # the volume planner moves each vid at most once per plan, but
        # EC plans legitimately carry several (src, dst) groups of ONE
        # stripe — those touch the same sidecars/mount path, so moves
        # sharing a (kind, vid) run back-to-back in plan order while
        # distinct volumes parallelize
        lock = threading.Lock()
        groups: dict[tuple, list[Move]] = {}
        for m in admitted:
            groups.setdefault((m.kind, m.vid), []).append(m)

        def run_group(ms: "list[Move]") -> None:
            for m in ms:
                self._run_move(m, summary, lock)

        if self.max_concurrent == 1 or len(groups) == 1:
            for ms in groups.values():
                run_group(ms)
        else:
            with ThreadPoolExecutor(
                    max_workers=self.max_concurrent,
                    thread_name_prefix="balance") as pool:
                futs = [pool.submit(contextvars.copy_context().run,
                                    run_group, ms)
                        for ms in groups.values()]
                for f in futs:
                    f.result()
        return summary

    def _run_move(self, m: Move, summary: dict,
                  lock: threading.Lock) -> None:
        from .. import qos, tracing
        from ..ops import events
        # rebalance traffic is maintenance-class AT THE SOURCE: the tag
        # rides the gRPC metadata of every hop below, so the file pulls
        # it triggers on src/dst admit behind foreground work
        with qos.tagged(qos.CLASS_MAINTENANCE), tracing.start_span(
                f"balance.{m.kind}", component="balance",
                attrs={"vid": m.vid, "src": m.src, "dst": m.dst,
                       "bytes": m.bytes_moved}) as sp:
            try:
                if m.kind == MOVE_VOLUME:
                    self._move_volume(m)
                elif m.kind == MOVE_EC:
                    self._move_ec(m)
                else:
                    raise ValueError(f"unknown move kind {m.kind!r}")
            except Exception as e:  # noqa: BLE001 — one move, one verdict
                sp.set_error(str(e))
                events.emit("balance.failed", severity=events.ERROR,
                            kind=m.kind, vid=m.vid, src=m.src, dst=m.dst,
                            error=str(e)[:200])
                log.warning("balance %s vid %s %s->%s failed: %s",
                            m.kind, m.vid, m.src, m.dst, e)
                with lock:
                    summary["failed"].append({**m.to_dict(),
                                              "error": str(e)})
                return
            events.emit("balance.move", kind=m.kind, vid=m.vid,
                        src=m.src, dst=m.dst,
                        bytes_moved=m.bytes_moved,
                        cross_rack=m.cross_rack,
                        shard_ids=list(m.shard_ids) or None)
            self._count(m)
            with lock:
                summary["done"].append(m.to_dict())

    # -- moves ---------------------------------------------------------------
    def _servers(self) -> dict:
        return {s["id"]: s for s in self.env.collect_volume_servers()}

    def _move_volume(self, m: Move) -> None:
        from ..shell.volume_commands import _safe_copy_volume
        servers = self._servers()
        src, dst = servers.get(m.src), servers.get(m.dst)
        if src is None or dst is None:
            raise RuntimeError(
                f"move endpoints gone: src={m.src} dst={m.dst}")
        _safe_copy_volume(self.env, m.vid, m.collection, src, dst,
                          delete_source=True)

    def _move_ec(self, m: Move) -> None:
        from ..pb import volume_server_pb2 as vpb
        from ..utils.rpc import Stub, VOLUME_SERVICE
        servers = self._servers()
        src, dst = servers.get(m.src), servers.get(m.dst)
        if src is None or dst is None:
            raise RuntimeError(
                f"move endpoints gone: src={m.src} dst={m.dst}")
        # ONE RPC for the whole (src, dst) shard group — the fork's
        # VolumeEcShardsMove does copy + source delete, driven from
        # the destination
        Stub(self.env.grpc_addr(dst["id"], dst["grpc_port"]),
             VOLUME_SERVICE).call(
            "VolumeEcShardsMove",
            vpb.VolumeEcShardsMoveRequest(
                volume_id=m.vid, collection=m.collection,
                shard_ids=sorted(m.shard_ids),
                source_data_node=self.env.grpc_addr(
                    src["id"], src["grpc_port"])),
            vpb.VolumeEcShardsMoveResponse, timeout=3600)

    # -- metrics --------------------------------------------------------------
    @staticmethod
    def _count(m: Move) -> None:
        try:
            from ..stats import BALANCE_BYTES_MOVED, BALANCE_MOVES
            BALANCE_MOVES.inc(m.kind)
            BALANCE_BYTES_MOVED.inc("true" if m.cross_rack else "false",
                                    amount=m.bytes_moved)
        except Exception:  # noqa: BLE001  # swtpu-lint: disable=silent-except (metrics must never break a move)
            pass
