"""Rebalance planner: one topology snapshot in, one deterministic
byte-costed MovePlan out.

The planning mirror of maintenance/planner.py: a pure function over a
Snapshot (no RPCs — `volume.balance -dryRun` prints the exact plan the
executor would run), costed in BYTES like the repair planner's
`bytes_moved`, because the warehouse-cluster study's lesson is that
rebalance traffic competes with repair and foreground reads for the
same cross-rack links:

  * volume balance moves bytes from the most-loaded server toward the
    least-loaded until max/min byte skew converges, counting EC shard
    bytes in the load (an EC-heavy server is NOT an attractive
    destination — the bug the old count-based balancer had);
  * each step moves the single volume whose size best closes the gap
    (moving s bytes closes 2s of spread), cheapest first on ties;
  * intra-rack destinations win over cross-rack ones, and cross-rack
    traffic is CAPPED per run (`cross_rack_limit_bytes`) so a balance
    pass cannot saturate the inter-rack fabric — the remainder waits
    for the next sweep;
  * EC balance evens each stripe's per-server shard counts without ever
    violating the rack-safety cap (≤ parity shards of a stripe per
    rack) and GROUPS shard ids per (volume, src, dst) pair into one
    move — one VolumeEcShardsMove RPC per pair instead of one per
    shard re-collecting the cluster in between.

Plans are deterministic: same snapshot (and probes) in, byte-identical
plan out — the property tests replan and compare.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from ..utils.log import logger
from .engine import Snapshot

log = logger("placement.plan")

MOVE_VOLUME = "volume"
MOVE_EC = "ec"

# stop when max/min per-server byte load is at or under this (the bench
# gate asserts 1.3; planning a little tighter leaves convergence slack
# for in-flight writes between plan and execution)
DEFAULT_TARGET_SKEW = 1.15
DEFAULT_MAX_MOVES = 64
# per-run cross-rack budget: one default volume (30 GB) worth of bytes;
# shell flag -crossRackLimitMB overrides
DEFAULT_CROSS_RACK_LIMIT = 30 << 30


@dataclass
class Move:
    """One rebalance move: a whole volume, or a group of EC shards of
    one stripe between one (src, dst) pair. `link` is the geo link
    class the bytes cross (policy.LINK_CLASSES) and
    `cost_weighted_bytes` = bytes_moved * that link's cost multiplier —
    the currency plans are ordered and budgeted in (PR 19)."""
    kind: str                # "volume" | "ec"
    vid: int
    collection: str
    src: str                 # node ids
    dst: str
    bytes_moved: int
    cross_rack: bool = False
    shard_ids: list[int] = field(default_factory=list)  # ec only
    link: str = "intra_rack"
    cost_weighted_bytes: int = 0

    def describe(self) -> str:
        what = (f"volume {self.vid}" if self.kind == MOVE_VOLUME
                else f"ec {self.vid} shards {self.shard_ids}")
        hop = self.link.replace("_", "-") if self.link else (
            "cross-rack" if self.cross_rack else "intra-rack")
        return (f"{what} {self.src} -> {self.dst} "
                f"(~{self.bytes_moved:,} B, {hop})")

    def to_dict(self) -> dict:
        return {"kind": self.kind, "vid": self.vid,
                "collection": self.collection, "src": self.src,
                "dst": self.dst, "bytes_moved": self.bytes_moved,
                "cross_rack": self.cross_rack, "link": self.link,
                "cost_weighted_bytes": self.cost_weighted_bytes,
                "shard_ids": list(self.shard_ids)}


@dataclass
class MovePlan:
    moves: list
    skew_before: float
    skew_after: float        # planned (post-simulation) skew
    notes: list = field(default_factory=list)
    generated_ms: int = 0

    def __post_init__(self):
        if not self.generated_ms:
            self.generated_ms = int(time.time() * 1000)

    def __bool__(self) -> bool:
        return bool(self.moves)

    @property
    def total_bytes(self) -> int:
        return sum(m.bytes_moved for m in self.moves)

    @property
    def cross_rack_bytes(self) -> int:
        return sum(m.bytes_moved for m in self.moves if m.cross_rack)

    @property
    def cross_dc_bytes(self) -> int:
        return sum(m.bytes_moved for m in self.moves
                   if m.link == "cross_dc")

    @property
    def cost_weighted_bytes(self) -> int:
        return sum(m.cost_weighted_bytes for m in self.moves)

    def to_dict(self) -> dict:
        return {"moves": [m.to_dict() for m in self.moves],
                "skew_before": round(self.skew_before, 3),
                "skew_after": round(self.skew_after, 3),
                "total_bytes": self.total_bytes,
                "cross_rack_bytes": self.cross_rack_bytes,
                "cross_dc_bytes": self.cross_dc_bytes,
                "cost_weighted_bytes": self.cost_weighted_bytes,
                "notes": list(self.notes),
                "generated_ms": self.generated_ms}

    def render(self, println) -> None:
        println(f"balance plan: {len(self.moves)} move(s), "
                f"{self.total_bytes:,} B total "
                f"({self.cross_rack_bytes:,} B cross-rack, "
                f"{self.cross_dc_bytes:,} B cross-dc, "
                f"{self.cost_weighted_bytes:,} cost-weighted), "
                f"byte skew {self.skew_before:.2f} -> "
                f"{self.skew_after:.2f} (planned)")
        for i, m in enumerate(self.moves, 1):
            println(f"  {i}. {m.describe()}")
        for note in self.notes:
            println(f"  !! {note}")


def _skew(loads: dict) -> float:
    """max/min per-server byte load; empty servers count at 1 byte so
    a fresh node reads as infinitely attractive without dividing by
    zero. 1.0 = perfectly even."""
    if not loads:
        return 1.0
    mx = max(loads.values())
    mn = min(loads.values())
    return mx / max(1, mn)


def build_volume_balance_plan(
        snap: Snapshot, collection: "str | None" = None,
        target_skew: float = DEFAULT_TARGET_SKEW,
        max_moves: int = DEFAULT_MAX_MOVES,
        cross_rack_limit_bytes: int = DEFAULT_CROSS_RACK_LIMIT,
        costs=None) -> MovePlan:
    """Greedy byte balance over one snapshot. Only volumes (optionally
    of one collection) move; EC shard bytes still weigh the load on
    both ends, so a shard-heavy server neither donates volumes it
    doesn't have nor attracts volumes it can't afford.

    `costs` (geo LinkCostModel; default price list when None) prices
    every candidate hop: the greedy key prefers the cheapest link that
    closes a gap — a cross-DC move only plans when no intra-DC fix
    exists — and cross-DC traffic is separately capped by the policy's
    `cross_dc_budget` (0 = unlimited)."""
    from ..geo.policy import LinkCostModel
    costs = costs or LinkCostModel()
    nodes = {n.id: n for n in snap.nodes}
    if len(nodes) < 2:
        return MovePlan([], 1.0, 1.0)
    loads = {nid: n.load_bytes for nid, n in nodes.items()}
    # local holder map for replica-safety (never land a vid on a server
    # already holding it), updated as planned moves land
    holders: dict[int, set] = {}
    vol_state: dict[str, dict] = {}
    # destination slots are debited as planned moves land — the static
    # snapshot alone would let the greedy loop pile more volumes onto a
    # nearly-full node than it has slots, failing at execution time
    free = {nid: n.free_slots for nid, n in nodes.items()}
    # a vid moves AT MOST ONCE per plan: chained A->B then B->C moves
    # of one volume would race under the executor's concurrency (and
    # waste a full copy); the second-best donor volume converges the
    # same bytes in one hop next run
    moved_vids: set[int] = set()
    for nid, n in nodes.items():
        vol_state[nid] = dict(n.volumes)
        for vid in n.volumes:
            holders.setdefault(vid, set()).add(nid)
    skew_before = _skew(loads)
    moves: list[Move] = []
    notes: list[str] = []
    cross_budget = cross_rack_limit_bytes
    dc_budget = costs.cross_dc_budget or float("inf")
    capped = False
    # moves conserve bytes, so the convergence target is fixed up front
    mean = sum(loads.values()) / len(loads)
    while len(moves) < max_moves and _skew(loads) > target_skew:
        order = sorted(loads, key=lambda i: (-loads[i], i))
        # donors most-loaded-first: a node whose load is all EC shards
        # (nothing movable here — ec.balance owns shard moves) must not
        # stall the whole plan, so the search falls through to the next
        # donor that CAN shed
        best = None  # (rank tuple, src_id, vid, v, dst_id, cross)
        for src_id in order[:-1]:
            movable = [
                (vid, v) for vid, v in vol_state[src_id].items()
                if (collection is None or v["collection"] == collection)
                and v["size"] > 0 and vid not in moved_vids]
            if not movable:
                continue
            # pick (volume, dst): moves that keep the destination at or
            # under the fleet mean rank first (no churn — a volume
            # lands once instead of cascading through an overfed
            # neighbor), then intra-rack before cross-rack, then the
            # size that best halves the src->dst gap, cheapest on ties
            for dst_id in order:
                dgap = loads[src_id] - loads[dst_id]
                if dgap <= 0:
                    continue
                s_n, d_n = nodes[src_id], nodes[dst_id]
                link = costs.classify(s_n.dc, s_n.rack, d_n.dc, d_n.rack)
                mult = costs.cost(s_n.dc, s_n.rack, d_n.dc, d_n.rack)
                cross = link != "intra_rack"
                if cross and cross_budget <= 0:
                    capped = True
                    continue
                if link == "cross_dc" and dc_budget <= 0:
                    capped = True
                    continue
                if free[dst_id] <= 0:
                    continue
                for vid, v in movable:
                    if dst_id in holders.get(vid, ()):
                        continue
                    if v["size"] >= dgap:
                        continue  # would overshoot: roles just swap
                    if cross and v["size"] > cross_budget:
                        capped = True
                        continue
                    if link == "cross_dc" and v["size"] > dc_budget:
                        capped = True
                        continue
                    overshoots = loads[dst_id] + v["size"] > mean
                    # link-cost multiplier where the old key held the
                    # cross-rack boolean: identical ordering on a
                    # single-DC fleet (1 < 4 iff False < True), and the
                    # cheapest link wins whenever one closes a gap
                    key = (overshoots, mult,
                           abs(dgap / 2 - v["size"]),
                           v["size"], vid, dst_id)
                    if best is None or key < best[0]:
                        best = (key, src_id, vid, v, dst_id, cross, link,
                                mult)
            if best is not None:
                break
        if best is None:
            if capped:
                notes.append("cross-rack/cross-dc byte budget exhausted; "
                             "remaining skew waits for the next run")
            break
        _, src_id, vid, v, dst_id, cross, link, mult = best
        moves.append(Move(kind=MOVE_VOLUME, vid=vid,
                          collection=v["collection"], src=src_id,
                          dst=dst_id, bytes_moved=v["size"],
                          cross_rack=cross, link=link,
                          cost_weighted_bytes=int(v["size"] * mult)))
        if cross:
            cross_budget -= v["size"]
        if link == "cross_dc":
            dc_budget -= v["size"]
        del vol_state[src_id][vid]
        vol_state[dst_id][vid] = v
        holders[vid].discard(src_id)
        holders[vid].add(dst_id)
        moved_vids.add(vid)
        free[dst_id] -= 1
        free[src_id] += 1
        loads[src_id] -= v["size"]
        loads[dst_id] += v["size"]
    if len(moves) >= max_moves and _skew(loads) > target_skew:
        notes.append(f"move budget ({max_moves}) exhausted at skew "
                     f"{_skew(loads):.2f}")
    return MovePlan(moves, skew_before, _skew(loads), notes=notes)


def build_ec_balance_plan(
        snap: Snapshot, collection: "str | None" = None,
        parity_of=None, default_parity: int = 2,
        max_moves: int = DEFAULT_MAX_MOVES, costs=None) -> MovePlan:
    """Even each EC stripe's per-server shard counts from ONE snapshot,
    honoring the rack-safety cap (≤ p shards of a stripe per rack).
    `parity_of(vid, collection) -> int|None` probes the sealed
    geometry; no answer falls back to `default_parity`.

    All moves of one stripe between one (src, dst) pair are grouped
    into a single Move — the executor issues one VolumeEcShardsMove per
    pair (the satellite fix: the old loop re-ran the settled-holder
    poll and a full topology collect per single shard).

    `costs` (geo LinkCostModel; defaults when None) orders candidate
    destinations cheapest-link-first within the evenness/rack caps, so
    a shard never crosses a DC when an intra-DC destination fixes the
    same imbalance."""
    from ..geo.policy import LinkCostModel
    costs = costs or LinkCostModel()
    nodes = {n.id: n for n in snap.nodes}
    if len(nodes) < 2:
        return MovePlan([], 1.0, 1.0)
    loads = {nid: n.load_bytes for nid, n in nodes.items()}
    skew_before = _skew(loads)
    rack_of = {nid: n.rack for nid, n in nodes.items()}
    dc_of = {nid: n.dc for nid, n in nodes.items()}

    def _mult(a: str, b: str) -> float:
        return costs.cost(dc_of[a], rack_of[a], dc_of[b], rack_of[b])
    # stripe state: vid -> {node_id: set(shard_ids)}
    stripes: dict[int, dict[str, set]] = {}
    meta: dict[int, dict] = {}
    for nid, n in nodes.items():
        for vid, s in n.ec_shards.items():
            if collection is not None and s["collection"] != collection:
                continue
            stripes.setdefault(vid, {}).setdefault(
                nid, set()).update(s["shard_ids"])
            meta.setdefault(vid, {"collection": s["collection"],
                                  "shard_bytes": s["shard_bytes"]})
    moves: list[Move] = []
    notes: list[str] = []
    # (vid, src, dst) -> Move, so per-pair groups accrete shard ids
    grouped: dict[tuple, Move] = {}
    for vid in sorted(stripes):
        by_node = stripes[vid]
        total = sum(len(s) for s in by_node.values())
        if not total:
            continue
        parity = default_parity
        if parity_of is not None:
            try:
                parity = parity_of(vid, meta[vid]["collection"]) \
                    or default_parity
            except Exception as e:  # noqa: BLE001 — probe is best-effort
                log.debug("parity probe for ec %s failed: %s", vid, e)
        cap = -(-total // len(nodes))  # ceil: per-node evenness target
        rack_counts: dict[str, int] = {}
        for nid, sids in by_node.items():
            rack_counts[rack_of[nid]] = \
                rack_counts.get(rack_of[nid], 0) + len(sids)
        n_racks = len({n.rack for n in snap.nodes})
        rack_cap = max(1, parity) if n_racks * max(1, parity) >= total \
            else -(-total // max(1, n_racks))
        moved_any = True
        while moved_any and len(moves) + len(grouped) < max_moves:
            moved_any = False
            counts = {nid: len(by_node.get(nid, ())) for nid in nodes}
            over = sorted((nid for nid, c in counts.items() if c > cap),
                          key=lambda i: (-counts[i], i))
            if not over:
                # evenness ok; still fix rack-safety violations (a
                # whole rack over cap must shed to another rack)
                over = sorted(
                    (nid for nid in counts
                     if counts[nid]
                     and rack_counts.get(rack_of[nid], 0) > rack_cap),
                    key=lambda i: (-counts[i], i))
            for src_id in over:
                # cost multiplier ranks AFTER the evenness/rack terms
                # (spread is safety, cheapness is preference) but
                # BEFORE load — an intra-DC destination beats a
                # cross-DC one whenever both fix the imbalance
                dsts = sorted(
                    (nid for nid in nodes
                     if nid != src_id and counts[nid] < cap
                     and vid not in nodes[nid].ec_shards
                     and nid not in by_node
                     and rack_counts.get(rack_of[nid], 0) < rack_cap),
                    key=lambda i: (counts[i],
                                   rack_counts.get(rack_of[i], 0),
                                   _mult(src_id, i), loads[i], i))
                # a node that already holds other shards of the stripe
                # may still take more if it stays under the caps
                if not dsts:
                    dsts = sorted(
                        (nid for nid in nodes
                         if nid != src_id and counts[nid] < cap
                         and (rack_of[nid] == rack_of[src_id]
                              or rack_counts.get(rack_of[nid], 0)
                              < rack_cap)),
                        key=lambda i: (counts[i],
                                       rack_counts.get(rack_of[i], 0),
                                       _mult(src_id, i), loads[i], i))
                if not dsts:
                    continue
                dst_id = dsts[0]
                sid = min(by_node[src_id])
                by_node[src_id].discard(sid)
                if not by_node[src_id]:
                    by_node.pop(src_id)
                by_node.setdefault(dst_id, set()).add(sid)
                if rack_of[dst_id] != rack_of[src_id]:
                    rack_counts[rack_of[src_id]] -= 1
                    rack_counts[rack_of[dst_id]] = \
                        rack_counts.get(rack_of[dst_id], 0) + 1
                sz = meta[vid]["shard_bytes"]
                loads[src_id] -= sz
                loads[dst_id] += sz
                key = (vid, src_id, dst_id)
                mv = grouped.get(key)
                if mv is None:
                    link = costs.classify(
                        dc_of[src_id], rack_of[src_id],
                        dc_of[dst_id], rack_of[dst_id])
                    grouped[key] = Move(
                        kind=MOVE_EC, vid=vid,
                        collection=meta[vid]["collection"],
                        src=src_id, dst=dst_id, bytes_moved=sz,
                        cross_rack=link != "intra_rack",
                        link=link,
                        cost_weighted_bytes=int(
                            sz * _mult(src_id, dst_id)),
                        shard_ids=[sid])
                else:
                    mv.shard_ids.append(sid)
                    mv.bytes_moved += sz
                    mv.cost_weighted_bytes += int(
                        sz * _mult(src_id, dst_id))
                moved_any = True
                break
    moves.extend(sorted(grouped.values(),
                        key=lambda m: (m.bytes_moved, m.vid, m.src)))
    if len(moves) >= max_moves:
        notes.append(f"move budget ({max_moves}) exhausted")
    for m in moves:
        m.shard_ids.sort()
    return MovePlan(moves, skew_before, _skew(loads), notes=notes)
