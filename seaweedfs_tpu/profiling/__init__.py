"""Continuous profiling & flight-recorder plane.

Three cooperating instruments, wired into every daemon (master, volume,
filer, S3):

* `sampler.ContinuousSampler` — always-on 19 Hz folded-stack sampler
  with thread-class attribution (event_loop/read_pool/writer_pool/
  grpc/raft/other) and an on-CPU vs waiting split, served at
  `/debug/profile?mode=continuous|summary`;
* `lag.LoopLagMonitor` / `lag.MonitoredPool` — event-loop lag probing
  and executor queue accounting, feeding the volume server's
  `queue_wait` stage and the flight recorder's at-admit context;
* `flight.FlightRecorder` — a bounded ring of the slowest/errored
  requests with stage timelines and trace correlation, served at
  `/debug/flight`.

`handle_profile_query()` below is the ONE implementation of the
`/debug/profile` HTTP contract all four daemons share (the four
hand-rolled copies diverged until the volume server shipped the
endpoint unguarded): query validation, the seconds clamp, mode
dispatch, and the runtime hz control. Each daemon keeps its own
transport + operator gate and delegates everything else here.
"""

from __future__ import annotations

import json as _json

from .flight import FLIGHT, FlightRecorder, debug_flight_payload
from .flight import record as record_flight
from .lag import LoopLagMonitor, MonitoredPool
from .sampler import (THREAD_CLASSES, ContinuousSampler, acquire_sampler,
                      classify_thread, default_sampler, release_sampler)

__all__ = [
    "THREAD_CLASSES", "ContinuousSampler", "acquire_sampler",
    "classify_thread", "default_sampler", "release_sampler",
    "LoopLagMonitor", "MonitoredPool",
    "FLIGHT", "FlightRecorder", "record_flight", "debug_flight_payload",
    "handle_profile_query",
]

DEFAULT_MAX_SECONDS = 30.0

_TEXT = "text/plain; charset=utf-8"
_JSON = "application/json"


def _err(msg: str) -> tuple[int, str, str]:
    return 400, _JSON, _json.dumps({"error": msg})


def handle_profile_query(query: dict) -> tuple[int, str, str]:
    """Shared /debug/profile implementation -> (status, content_type,
    body). Callers gate it behind their operator auth and run it OFF
    the event loop (the capture mode blocks for `seconds`).

    Modes:
      (none)            N-second capture (utils/profiling.cpu_profile);
                        `seconds` validated — malformed/NaN/<=0 -> 400,
                        clamped at SWTPU_PROFILE_MAX_SECONDS (a typo'd
                        seconds=1e9 must not pin an executor thread for
                        the daemon's lifetime)
      mode=continuous   the always-on sampler's collapsed-flamegraph text
      mode=summary      the sampler's JSON summary (telemetry collector)
      hz=N              retune the sampler's rate (0 pauses); combines
                        with any mode, alone returns a JSON ack
    """
    import math

    from ..utils.env import env_float

    mode = (query.get("mode") or "").strip()
    hz_ack = None
    if "hz" in query:
        try:
            hz = float(query["hz"])
        except (TypeError, ValueError):
            return _err("hz must be a number")
        if not math.isfinite(hz) or hz < 0:
            return _err("hz must be finite and >= 0")
        s = default_sampler() or acquire_sampler()
        s.set_hz(hz)
        if hz > 0 and not s.running:
            s.start()
        hz_ack = s.hz

    if mode == "continuous":
        s = default_sampler()
        if s is None:
            return (200, _TEXT,
                    "# continuous sampler not running "
                    "(SWTPU_PROFILE_HZ=0 or daemon not started)\n")
        return 200, _TEXT, s.collapsed()

    if mode == "summary":
        try:
            top = int(query.get("top", "200") or 200)
        except (TypeError, ValueError):
            return _err("top must be an integer")
        s = default_sampler()
        if s is None:
            payload = {"hz": 0.0, "ticks": 0, "samples": 0,
                       "classes": {}, "stacks": []}
        else:
            payload = s.summary(top=min(max(1, top), 2000))
        return 200, _JSON, _json.dumps(payload)

    if mode not in ("", "capture"):
        return _err(f"unknown mode {mode!r}")

    if hz_ack is not None and "seconds" not in query:
        # a pure rate retune must not also trigger a 5 s capture
        return 200, _JSON, _json.dumps({"ok": True, "hz": hz_ack})

    raw = query.get("seconds", "5")
    try:
        secs = float(raw)
    except (TypeError, ValueError):
        return _err(f"seconds must be a number, got {raw!r}")
    if not math.isfinite(secs) or secs <= 0:
        # NaN slips through min/max comparisons — reject it explicitly
        return _err("seconds must be finite and > 0")
    secs = min(secs, env_float("SWTPU_PROFILE_MAX_SECONDS",
                               DEFAULT_MAX_SECONDS))
    from ..utils import profiling as capture
    return 200, _TEXT, capture.cpu_profile(secs)
