"""Flight recorder: a bounded per-node ring of the requests worth
explaining — the slowest and the errored.

Metrics say "p99 regressed"; traces say "this one request did X" but
only if someone was tracing it. The flight recorder closes the gap the
way ops/events.py does for control-plane transitions: every request
envelope offers its outcome, and the recorder keeps the ones that were
slow (>= SWTPU_FLIGHT_SLOW_MS wire-to-wire) or errored (5xx) in a
deque(maxlen=SWTPU_FLIGHT_BUFFER). Each entry carries everything the
postmortem needs without reproduction:

* the stage timeline (recv_parse/queue_wait/auth_admit/store/
  serialize_flush, milliseconds),
* trace_id/span_id — resolve the full span tree at /debug/traces,
* qos class, cache hit/miss, and the *conditions at admit*: event-loop
  lag and executor queue depths (was THIS request slow, or was the node
  drowning?).

Correlation runs both ways, exactly like the event journal: entries
capture the active trace ids, and record() mirrors a `flight.recorded`
event into the active span so a trace read shows "this request was
captured". Served at `/debug/flight?min_ms=&type=&limit=`, slowest
first.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from ..utils.env import env_float, env_int

DEFAULT_CAPACITY = 256
DEFAULT_SLOW_MS = 5.0


class FlightRecorder:
    def __init__(self, capacity: "int | None" = None,
                 slow_ms: "float | None" = None):
        self.capacity = (env_int("SWTPU_FLIGHT_BUFFER", DEFAULT_CAPACITY)
                         if capacity is None else int(capacity))
        self.slow_ms = (env_float("SWTPU_FLIGHT_SLOW_MS", DEFAULT_SLOW_MS)
                        if slow_ms is None else float(slow_ms))
        self._ring: deque = deque(maxlen=max(1, self.capacity))
        self._lock = threading.Lock()
        self._seq = 0

    def record(self, kind: str, duration_s: float, status: int = 200,
               path: str = "", stages: "dict | None" = None,
               qos_class: str = "", cache=None,
               loop_lag_s: "float | None" = None,
               queue_depths: "dict | None" = None,
               node: str = "") -> "dict | None":
        """Offer one finished request; returns the entry if admitted.
        Cheap on the fast path: everything below the threshold returns
        after two float compares."""
        duration_ms = duration_s * 1e3
        errored = status >= 500
        if duration_ms < self.slow_ms and not errored:
            return None
        from .. import tracing
        trace_id, span_id = tracing.current_ids()
        entry = {
            "ts": time.time(),  # display timestamp only, never math
            "kind": kind, "path": path, "status": int(status),
            "duration_ms": round(duration_ms, 3),
            "why": "error" if errored else "slow",
            "stages_ms": {k: round(v * 1e3, 3)
                          for k, v in (stages or {}).items()},
            "trace_id": trace_id, "span_id": span_id,
            "qos_class": qos_class, "cache": cache,
            "loop_lag_ms": (round(loop_lag_s * 1e3, 3)
                            if loop_lag_s is not None else None),
            "queue_depths": dict(queue_depths or {}),
            "node": node,
        }
        with self._lock:
            self._seq += 1
            entry["seq"] = self._seq
            self._ring.append(entry)
        try:
            from ..stats import FLIGHT_RECORDS
            FLIGHT_RECORDS.inc(entry["why"])
            # the other direction of the correlation: the active span
            # learns it was captured (same pattern as events.emit)
            tracing.add_event("flight.recorded", seq=entry["seq"],
                              kind=kind, duration_ms=entry["duration_ms"])
        except Exception:  # noqa: BLE001  # swtpu-lint: disable=silent-except (accounting must never fail the request)
            pass
        return entry

    def snapshot(self, min_ms: float = 0.0, kind: str = "",
                 limit: int = 50) -> list[dict]:
        """Matching entries, slowest first."""
        with self._lock:
            entries = list(self._ring)
        if min_ms > 0:
            entries = [e for e in entries if e["duration_ms"] >= min_ms]
        if kind:
            entries = [e for e in entries
                       if e["kind"] == kind or e["kind"].startswith(kind)]
        entries.sort(key=lambda e: (-e["duration_ms"], -e["seq"]))
        return entries[:max(0, int(limit))]

    def recorded(self) -> int:
        with self._lock:
            return self._seq

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


# process-wide recorder, mirroring ops/events.JOURNAL: per-node in real
# deployments (one daemon per process), shared in in-process tests
FLIGHT = FlightRecorder()


def record(kind: str, duration_s: float, **kw) -> None:
    """Swallowing wrapper for request envelopes: flight recording must
    never fail or slow the request being recorded."""
    try:
        FLIGHT.record(kind, duration_s, **kw)
    except Exception:  # noqa: BLE001  # swtpu-lint: disable=silent-except (observability must not break the data path)
        pass


def debug_flight_payload(query: dict) -> tuple[int, dict]:
    """The /debug/flight payload: (http_status, body). Malformed
    filters are a 400, not a stack trace."""
    import math
    try:
        min_ms = float(query.get("min_ms", "0") or 0)
        limit = int(query.get("limit", "50") or 50)
    except (TypeError, ValueError) as e:
        return 400, {"error": f"bad query: {e}"}
    if not math.isfinite(min_ms) or min_ms < 0:
        return 400, {"error": "min_ms must be finite and >= 0"}
    limit = min(max(0, limit), 1000)
    kind = (query.get("type") or "").strip()
    return 200, {
        "capacity": FLIGHT.capacity,
        "slow_ms": FLIGHT.slow_ms,
        "recorded": FLIGHT.recorded(),
        "entries": FLIGHT.snapshot(min_ms=min_ms, kind=kind, limit=limit),
    }
