"""Event-loop lag probes and queue-accounted executor pools.

Two instruments that turn "the loop felt slow" into numbers:

* `LoopLagMonitor` — a periodic `loop.call_later` probe: schedule a
  callback `interval` out, measure how late it actually fires. That
  lateness IS event-loop queueing — every handler admitted while the
  loop is `lag` behind waited roughly that long between parse and
  handler entry. Feeds `SeaweedFS_event_loop_lag_seconds{loop}` and
  exposes `last_lag_s` so the volume server can stamp loop-lag-at-admit
  into stage accounting and flight-recorder entries.

* `MonitoredPool` — a ThreadPoolExecutor whose submit() accounts queue
  depth (submitted-not-yet-started, `SeaweedFS_pool_queue_depth{pool}`)
  and queue wait (submit -> worker pickup,
  `SeaweedFS_pool_queue_wait_seconds{pool}`). The volume server's read
  pools ride on it; depth-at-admit lands in flight entries.

Label values are fixed small sets ("volume"/"master"/"filer"/"s3",
"read"/"ec_read"/...) — NEVER per-port — so several servers in one test
process share series via delta accounting, and stats/expo_lint.py can
hold a tier-style cardinality ceiling over both labels.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor

from ..utils.env import env_float

DEFAULT_PROBE_INTERVAL_S = 0.25


class LoopLagMonitor:
    def __init__(self, loop_name: str, interval_s: "float | None" = None):
        self.name = loop_name
        self.interval_s = (env_float("SWTPU_LOOP_PROBE_S",
                                     DEFAULT_PROBE_INTERVAL_S)
                           if interval_s is None else float(interval_s))
        self._loop = None
        self._handle = None
        self._expected = 0.0
        self._last_lag_s = 0.0
        self._probes = 0
        self._closed = False

    def attach(self, loop) -> None:
        """Install the probe on `loop` (call from the loop's thread —
        the serve loops' on_loop hook does)."""
        self._loop = loop
        self._closed = False
        self._expected = loop.time() + self.interval_s
        self._handle = loop.call_later(self.interval_s, self._tick)

    def _tick(self) -> None:
        loop = self._loop
        if loop is None or self._closed:
            return
        # lateness beyond the asked-for delay = time the loop spent on
        # other callbacks before reaching this one = queueing
        lag = max(0.0, loop.time() - self._expected)
        self._last_lag_s = lag
        self._probes += 1
        try:
            from ..stats import EVENT_LOOP_LAG
            EVENT_LOOP_LAG.observe(self.name, value=lag)
        except Exception:  # noqa: BLE001  # swtpu-lint: disable=silent-except (metrics must never stall the loop)
            pass
        if not loop.is_closed():
            self._expected = loop.time() + self.interval_s
            self._handle = loop.call_later(self.interval_s, self._tick)

    @property
    def last_lag_s(self) -> float:
        """Most recent probe's lag — 'how far behind was the loop just
        now': stamped into stage accounting / flight entries at admit."""
        return self._last_lag_s

    @property
    def probes(self) -> int:
        return self._probes

    def close(self) -> None:
        self._closed = True
        h, self._handle = self._handle, None
        if h is not None:
            try:
                h.cancel()
            except Exception:  # noqa: BLE001  # swtpu-lint: disable=silent-except (loop may already be torn down)
                pass


class MonitoredPool(ThreadPoolExecutor):
    """ThreadPoolExecutor with queue-depth and queue-wait accounting.

    `pool_label` is the {pool} metric label (closed set); depth uses
    gauge deltas so same-labelled pools in one process compose."""

    def __init__(self, pool_label: str, max_workers: "int | None" = None,
                 thread_name_prefix: str = ""):
        super().__init__(max_workers=max_workers,
                         thread_name_prefix=thread_name_prefix)
        self.pool_label = pool_label
        self._queued = 0
        self._qlock = threading.Lock()

    def queued(self) -> int:
        """Tasks submitted but not yet picked up by a worker."""
        return self._queued

    def submit(self, fn, /, *args, **kwargs):
        t_q = time.perf_counter()
        with self._qlock:
            self._queued += 1
        try:
            from ..stats import POOL_QUEUE_DEPTH
            POOL_QUEUE_DEPTH.add(self.pool_label, amount=1)
        except Exception:  # noqa: BLE001  # swtpu-lint: disable=silent-except (metrics must never fail a submit)
            pass

        def run():
            with self._qlock:
                self._queued -= 1
            try:
                from ..stats import POOL_QUEUE_DEPTH, POOL_QUEUE_WAIT
                POOL_QUEUE_DEPTH.add(self.pool_label, amount=-1)
                POOL_QUEUE_WAIT.observe(self.pool_label,
                                        value=time.perf_counter() - t_q)
            except Exception:  # noqa: BLE001  # swtpu-lint: disable=silent-except (accounting must never fail the task)
                pass
            return fn(*args, **kwargs)

        try:
            return super().submit(run)
        except BaseException:
            # submit refused (shutdown): roll the depth accounting back
            with self._qlock:
                self._queued -= 1
            try:
                from ..stats import POOL_QUEUE_DEPTH
                POOL_QUEUE_DEPTH.add(self.pool_label, amount=-1)
            except Exception:  # noqa: BLE001  # swtpu-lint: disable=silent-except
                pass
            raise
