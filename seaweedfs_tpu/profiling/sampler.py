"""Continuous sampling profiler: always-on fleet flamegraphs.

The reference answers "where is the CPU going?" with `net/http/pprof`
on -debug.port (command/imports.go:4) — a continuous, low-overhead
sampler every Go daemon carries. The Python analogue here is a
background thread that walks `sys._current_frames()` at
`SWTPU_PROFILE_HZ` (default 19 Hz — prime, so the sampler cannot
lockstep with the 2 s heartbeat, 15 s telemetry scrape or any other
round-interval periodic work) into a bounded folded-stack aggregate.

Each sampled thread is attributed twice before its stack is folded:

* a **thread class** from a closed set (event_loop / read_pool /
  writer_pool / grpc / raft / other), derived from the thread-name
  conventions every pool in this tree already follows (`vs-read-*`,
  `swtpu-ec-writer-*`, `grpc-worker*`, `raft-*`, `*-http*`);
* an **on-CPU vs waiting** split from a leaf-frame heuristic: a thread
  whose innermost Python frame is a known blocking primitive
  (threading.Event.wait, selectors.select, queue.get, ssl read, ...)
  is parked, not burning CPU — exactly the distinction the ROADMAP's
  queueing-inflated recv_parse number was missing.

The aggregate is served at `/debug/profile?mode=continuous` as
collapsed-flamegraph text (`class;state;frame;frame;... count` — feed
it straight to flamegraph.pl / speedscope), and as JSON at
`?mode=summary` for the telemetry collector's fleet merge. Memory is
bounded: at most SWTPU_PROFILE_MAX_STACKS distinct stacks; overflow
collapses into a per-class `~other` bucket so total sample counts stay
exact (the fleet merge sums counts — silent truncation would lie).

Daemons share one process-wide sampler via acquire_sampler() /
release_sampler() refcounting (tests start several servers in one
process; N servers must not mean N sampling threads).
"""

from __future__ import annotations

import os
import sys
import threading
import time

from ..utils.env import env_float, env_int

THREAD_CLASSES = ("event_loop", "read_pool", "writer_pool", "grpc",
                  "raft", "other")

DEFAULT_HZ = 19.0  # prime: cannot lockstep with round periodic work

# thread-name substring -> class, first match wins; every pool in the
# tree names its threads (vs-read-, swtpu-ec-writer-, grpc-worker,
# raft-rpc/raft-<addr>, vs-http-/master-http/filer-http-/s3-http-)
_NAME_RULES = (
    ("vs-read-", "read_pool"),
    ("ec-degraded-read", "read_pool"),
    ("swtpu-ec-writer", "writer_pool"),
    ("chunk-upload-", "writer_pool"),
    ("stream-write-", "writer_pool"),
    ("grpc-worker", "grpc"),
    ("raft", "raft"),
    ("-http", "event_loop"),
    ("asyncio_", "event_loop"),  # the loops' default run_in_executor pool
)

# leaf-frame heuristic for "parked, not running": the innermost Python
# frame of a blocked thread is the stdlib wrapper around the C-level
# wait (Event.wait ends in threading.py:wait, an idle executor worker
# in queue.py:get, a selector loop in selectors.py:select, ...)
_WAIT_FILES = {"threading.py", "selectors.py", "socket.py", "queue.py",
               "ssl.py", "subprocess.py", "connection.py",
               "synchronize.py", "popen_fork.py"}
_WAIT_FUNCS = {"wait", "acquire", "select", "poll", "accept", "recv",
               "recv_into", "recvfrom", "read", "readinto", "get",
               "join", "_wait_for_tstate_lock", "flush", "sleep"}


def classify_thread(name: str) -> str:
    for needle, cls in _NAME_RULES:
        if needle in name:
            return cls
    return "other"


def _is_waiting(frame) -> bool:
    code = frame.f_code
    return (code.co_name in _WAIT_FUNCS
            and os.path.basename(code.co_filename) in _WAIT_FILES)


def _fold(frame, max_depth: int) -> str:
    """Innermost frame -> `file.py:func;...` root-to-leaf folded stack."""
    parts: list[str] = []
    f = frame
    while f is not None and len(parts) < max_depth:
        code = f.f_code
        parts.append(f"{os.path.basename(code.co_filename)}:{code.co_name}")
        f = f.f_back
    parts.reverse()
    return ";".join(parts)


class ContinuousSampler:
    def __init__(self, hz: "float | None" = None,
                 max_stacks: "int | None" = None, max_depth: int = 48):
        self._hz = (env_float("SWTPU_PROFILE_HZ", DEFAULT_HZ)
                    if hz is None else float(hz))
        self._max_stacks = (env_int("SWTPU_PROFILE_MAX_STACKS", 4000)
                            if max_stacks is None else int(max_stacks))
        self._max_depth = max_depth
        self._agg: dict[str, int] = {}
        self._samples = 0          # total thread-samples in the aggregate
        self._ticks = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None
        self._names: dict[int, str] = {}  # tid -> name, refreshed lazily

    # -- lifecycle -------------------------------------------------------
    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    @property
    def hz(self) -> float:
        return self._hz

    def set_hz(self, hz: float) -> None:
        """Runtime rate control: 0 pauses sampling (the bench's A/B
        overhead phases toggle this on a live cluster), capped well
        below anything that could matter for overhead."""
        self._hz = min(max(0.0, float(hz)), 250.0)

    def start(self) -> None:
        if self.running:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="swtpu-profiler")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=2.0)
        self._thread = None

    # -- sampling loop ---------------------------------------------------
    def _run(self) -> None:
        next_t = time.monotonic()
        while not self._stop.is_set():
            hz = self._hz
            if hz <= 0:
                # paused: park cheaply, re-anchor the schedule on resume
                self._stop.wait(0.25)
                next_t = time.monotonic()
                continue
            self._sample_once()
            next_t += 1.0 / hz
            delay = next_t - time.monotonic()
            if delay <= 0:
                # fell behind (GIL-starved under load): skip, don't burst
                next_t = time.monotonic()
            else:
                self._stop.wait(delay)

    def _thread_names(self, tids) -> dict[int, str]:
        names = self._names
        if any(tid not in names for tid in tids):
            names = {t.ident: t.name for t in threading.enumerate()
                     if t.ident is not None}
            self._names = names
        return names

    def _sample_once(self) -> None:
        me = threading.get_ident()
        frames = sys._current_frames()
        names = self._thread_names(frames.keys())
        per_cs: dict[tuple[str, str], int] = {}
        with self._lock:
            self._ticks += 1
            for tid, frame in frames.items():
                if tid == me:
                    continue
                cls = classify_thread(names.get(tid, ""))
                state = "waiting" if _is_waiting(frame) else "on_cpu"
                key = f"{cls};{state};{_fold(frame, self._max_depth)}"
                if key not in self._agg and \
                        len(self._agg) >= self._max_stacks:
                    # bounded aggregate: overflow collapses per class so
                    # totals stay exact for the fleet merge
                    key = f"{cls};{state};~other"
                self._agg[key] = self._agg.get(key, 0) + 1
                self._samples += 1
                ck = (cls, state)
                per_cs[ck] = per_cs.get(ck, 0) + 1
        try:
            from ..stats import PROFILE_SAMPLES
            for (cls, state), n in per_cs.items():
                PROFILE_SAMPLES.inc(cls, state, amount=n)
        except Exception:  # noqa: BLE001  # swtpu-lint: disable=silent-except (metrics must never kill the sampler)
            pass

    # -- read API --------------------------------------------------------
    def reset(self) -> None:
        with self._lock:
            self._agg.clear()
            self._samples = 0
            self._ticks = 0

    def collapsed(self, min_count: int = 1) -> str:
        """Collapsed-flamegraph text: one `stack count` line per folded
        stack, prefixed by the class;state attribution frames."""
        with self._lock:
            items = sorted(self._agg.items(),
                           key=lambda kv: (-kv[1], kv[0]))
            hz, ticks, samples = self._hz, self._ticks, self._samples
        lines = [f"# swtpu continuous profile: {samples} thread-samples "
                 f"over {ticks} ticks at {hz:g} Hz "
                 f"(folded: class;state;frames... count)"]
        lines += [f"{k} {n}" for k, n in items if n >= min_count]
        return "\n".join(lines) + "\n"

    def summary(self, top: int = 200) -> dict:
        """JSON summary for the telemetry collector's fleet merge.
        Stacks beyond `top` roll into their class's `~other` line so
        per-node counts still sum exactly cluster-wide."""
        with self._lock:
            items = sorted(self._agg.items(),
                           key=lambda kv: (-kv[1], kv[0]))
            hz, ticks, samples = self._hz, self._ticks, self._samples
        classes: dict[str, dict[str, int]] = {}
        for key, n in items:
            cls, state, _, = key.split(";", 2)
            c = classes.setdefault(cls, {"on_cpu": 0, "waiting": 0})
            c[state] = c.get(state, 0) + n
        stacks: dict[str, int] = {}
        for key, n in items:
            if len(stacks) < top or key in stacks:
                stacks[key] = stacks.get(key, 0) + n
            else:
                cls, state, _ = key.split(";", 2)
                okey = f"{cls};{state};~other"
                stacks[okey] = stacks.get(okey, 0) + n
        return {"hz": hz, "ticks": ticks, "samples": samples,
                "classes": classes,
                "stacks": [{"stack": k, "count": n}
                           for k, n in stacks.items()]}


# -- process-wide default sampler (refcounted across daemons) ------------
_default: "ContinuousSampler | None" = None
_refs = 0
_ref_lock = threading.Lock()


def acquire_sampler() -> ContinuousSampler:
    """Daemon start(): share one sampling thread per process no matter
    how many servers a test or combo binary runs in it."""
    global _default, _refs
    with _ref_lock:
        if _default is None:
            _default = ContinuousSampler()
        _refs += 1
        if not _default.running and _default.hz > 0:
            _default.start()
        return _default


def release_sampler() -> None:
    """Daemon stop(): the last daemon out joins the sampler thread (the
    aggregate is kept for postmortem reads)."""
    global _refs
    with _ref_lock:
        _refs = max(0, _refs - 1)
        if _refs == 0 and _default is not None:
            _default.stop()


def default_sampler() -> "ContinuousSampler | None":
    return _default
