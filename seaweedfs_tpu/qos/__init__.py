"""Multi-tenant QoS plane: admission, fairness, and priority classes.

The batched data planes (PRs 7/9/10) made single workloads fast; this
package keeps those wins under ADVERSARIAL mixes. The warehouse-cluster
study (PAPERS.md arXiv:1309.0186) measured repair traffic alone
dominating shared links — noisy neighbors are not hypothetical at
production scale, they are the steady state. Three mechanisms, one
scheduler core (scheduler.py), one policy document (policy.py):

  * hierarchical token buckets — per-tenant request + byte rates with
    burst credit, nested under per-class and node-wide buckets;
  * weighted-fair queueing — deficit round-robin over per-tenant
    queues, weights from the hot-reloadable policy doc;
  * priority classes — interactive reads > ingest > maintenance;
    repair/replication/rebuild traffic is tagged at the source and
    YIELDS to queued foreground work instead of competing for the same
    read pools and volume locks.

Enforcement points live at both tiers: the S3 gateway (tenant = access
key / bucket) and the volume server HTTP plane (tenant = collection),
each answering sheds with 503 + Retry-After like real S3's SlowDown.

This module holds the class-tag plumbing: a contextvar carried across
threads (contextvars.copy_context is already threaded through every
executor hop), injected on outbound HTTP (client/http_util) and gRPC
(utils/rpc) hops as the `x-swtpu-qos` header/metadata so a repair
driven by the maintenance executor stays maintenance-class across every
machine it touches.
"""

from __future__ import annotations

import contextlib
import contextvars

# priority classes, highest first (scheduler serves in this order)
CLASS_INTERACTIVE = "interactive"
CLASS_INGEST = "ingest"
CLASS_MAINTENANCE = "maintenance"
CLASSES = (CLASS_INTERACTIVE, CLASS_INGEST, CLASS_MAINTENANCE)

# the tag a request carries across process hops (HTTP header form; the
# same key travels as gRPC metadata)
QOS_HEADER = "x-swtpu-qos"

# overflow tenant: past the policy's max_tenants ceiling, the long tail
# of tenant ids shares one bucket/label so metrics cardinality and
# scheduler state stay bounded no matter how many tenants exist
OVERFLOW_TENANT = "~other"

_class_var: contextvars.ContextVar[str] = contextvars.ContextVar(
    "swtpu_qos_class", default="")


def current_class() -> str:
    """The traffic class tagged on the current execution flow
    ('' = untagged: the enforcement point picks from the verb)."""
    return _class_var.get()


@contextlib.contextmanager
def tagged(klass: str):
    """Tag everything inside (and every copy_context hop below) with a
    traffic class — the maintenance executor wraps repair dispatch in
    `tagged(CLASS_MAINTENANCE)` so its reads yield to foreground."""
    token = _class_var.set(klass)
    try:
        yield
    finally:
        _class_var.reset(token)


def set_class(klass: str):
    """Imperative form for server-side extraction (gRPC handler threads
    set the inbound tag, then reset with the returned token)."""
    return _class_var.set(klass)


def reset_class(token) -> None:
    _class_var.reset(token)


def injectable() -> str:
    """Header value to attach to an outbound hop ('' = nothing)."""
    return _class_var.get()


def inject(headers: dict) -> dict:
    """Attach the current class tag to an outbound header dict (mirrors
    tracing.inject; mutates AND returns `headers`)."""
    klass = _class_var.get()
    if klass:
        headers[QOS_HEADER] = klass
    return headers


def class_from_headers(headers, default: str) -> str:
    """The effective class of an inbound request. An explicit tag is
    honored only as a DOWNGRADE from the verb-derived default: internal
    maintenance flows legitimately demote themselves, but a client must
    never self-classify UP (an antagonist stamping its bulk PUTs
    `interactive` would jump the priority queues and escape its ingest
    caps — the exact traffic the classes exist to contain). Unknown tag
    values can't mint scheduler state either."""
    try:
        tag = headers.get(QOS_HEADER, "")
    except Exception:  # noqa: BLE001 — headers-like of any shape
        tag = ""
    if tag in CLASSES and default in CLASSES and \
            CLASSES.index(tag) >= CLASSES.index(default):
        return tag
    return default


from .policy import QosPolicy, parse_policy  # noqa: E402
from .scheduler import Grant, QosScheduler, QosShed  # noqa: E402

__all__ = [
    "CLASS_INTERACTIVE", "CLASS_INGEST", "CLASS_MAINTENANCE", "CLASSES",
    "QOS_HEADER", "OVERFLOW_TENANT",
    "current_class", "tagged", "set_class", "reset_class",
    "injectable", "inject", "class_from_headers",
    "QosPolicy", "parse_policy", "QosScheduler", "QosShed", "Grant",
]
