"""QoS policy document: the operator-owned knob surface.

One JSON document configures both tiers, hot-reloadable exactly like
the S3 circuit breaker's config (stored at /etc/qos/policy.json in the
filer for gateways, passed as `-qosPolicy <file>` to volume servers,
POSTable to /debug/qos for live retuning). Shape:

    {
      "enabled": true,
      "node":    {"rps": 0, "bytes_per_s": "64MB", "max_inflight": 0},
      "classes": {
        "interactive": {"max_wait_s": 1.0},
        "ingest":      {"max_wait_s": 5.0},
        "maintenance": {"max_wait_s": 30.0, "rps": 0,
                        "bytes_per_s": "8MB", "max_inflight": 2}
      },
      "default": {"weight": 10, "rps": 0, "burst": 0,
                  "bytes_per_s": 0, "burst_bytes": 0, "max_queue": 64},
      "tenants": {
        "victim": {"weight": 100},
        "antag":  {"weight": 10, "bytes_per_s": "2MB",
                   "burst_bytes": "4MB"}
      },
      "max_tenants": 64,
      "quantum_bytes": 65536
    }

Semantics:
  * 0 / absent = unlimited for every rate/cap knob;
  * byte knobs accept ints or "4MB"/"512KB"/"1GB" strings;
  * `default` is the profile a tenant NOT named in `tenants` gets;
  * `max_tenants` bounds distinct tenant states (and the metric label
    space) — the long tail past it shares the "~other" overflow bucket;
  * burst defaults to one second of rate when left 0 alongside a rate.

`parse_policy` validates hard (ValueError with the offending key) so a
typo'd document is rejected at load instead of silently admitting
everything.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

from . import CLASSES

_SIZE_RE = re.compile(r"^\s*(\d+(?:\.\d+)?)\s*([KMGT]?)I?B?\s*$",
                      re.IGNORECASE)
_UNITS = {"": 1, "K": 1 << 10, "M": 1 << 20, "G": 1 << 30, "T": 1 << 40}

# class-level defaults: how long a request may queue before it sheds
_DEFAULT_MAX_WAIT_S = {"interactive": 1.0, "ingest": 5.0,
                       "maintenance": 30.0}


def parse_size(v, key: str = "") -> float:
    """Int/float pass through; "4MB"-style strings parse; anything else
    raises. 0 means unlimited by convention."""
    if isinstance(v, bool):
        raise ValueError(f"qos policy: {key or 'size'} must be a number "
                         f"or size string, got {v!r}")
    if isinstance(v, (int, float)):
        if v < 0:
            raise ValueError(f"qos policy: {key or 'size'} must be >= 0")
        return float(v)
    if isinstance(v, str):
        m = _SIZE_RE.match(v)
        if m:
            return float(m.group(1)) * _UNITS[m.group(2).upper()]
    raise ValueError(f"qos policy: bad size {v!r} for {key or 'value'}")


def _num(section: dict, key: str, default: float = 0.0,
         where: str = "") -> float:
    v = section.get(key, default)
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise ValueError(f"qos policy: {where}{key} must be a number, "
                         f"got {v!r}")
    if v < 0:
        raise ValueError(f"qos policy: {where}{key} must be >= 0")
    return float(v)


@dataclass(frozen=True)
class BucketSpec:
    """One token-bucket pair spec: request rate + byte rate (0 = off)."""
    rps: float = 0.0
    burst: float = 0.0
    bytes_per_s: float = 0.0
    burst_bytes: float = 0.0
    max_inflight: int = 0


@dataclass(frozen=True)
class TenantSpec(BucketSpec):
    weight: int = 10
    max_queue: int = 64


@dataclass(frozen=True)
class ClassSpec(BucketSpec):
    max_wait_s: float = 5.0


@dataclass(frozen=True)
class QosPolicy:
    enabled: bool = False
    node: BucketSpec = field(default_factory=BucketSpec)
    classes: "dict[str, ClassSpec]" = field(default_factory=dict)
    default: TenantSpec = field(default_factory=TenantSpec)
    tenants: "dict[str, TenantSpec]" = field(default_factory=dict)
    max_tenants: int = 64
    quantum_bytes: int = 65536

    def tenant_spec(self, name: str) -> TenantSpec:
        return self.tenants.get(name, self.default)

    def class_spec(self, klass: str) -> ClassSpec:
        spec = self.classes.get(klass)
        if spec is None:
            spec = ClassSpec(
                max_wait_s=_DEFAULT_MAX_WAIT_S.get(klass, 5.0))
        return spec


def _bucket_fields(section: dict, where: str) -> dict:
    out = {
        "rps": _num(section, "rps", 0.0, where),
        "burst": _num(section, "burst", 0.0, where),
        "bytes_per_s": parse_size(section.get("bytes_per_s", 0),
                                  where + "bytes_per_s"),
        "burst_bytes": parse_size(section.get("burst_bytes", 0),
                                  where + "burst_bytes"),
        "max_inflight": int(_num(section, "max_inflight", 0, where)),
    }
    # burst credit defaults to one second of the configured rate — a
    # bucket with rate but zero burst could never admit anything
    if out["rps"] and not out["burst"]:
        out["burst"] = max(1.0, out["rps"])
    if out["bytes_per_s"] and not out["burst_bytes"]:
        out["burst_bytes"] = out["bytes_per_s"]
    return out


_TENANT_KEYS = {"rps", "burst", "bytes_per_s", "burst_bytes",
                "max_inflight", "weight", "max_queue"}
_CLASS_KEYS = {"rps", "burst", "bytes_per_s", "burst_bytes",
               "max_inflight", "max_wait_s"}
_NODE_KEYS = {"rps", "burst", "bytes_per_s", "burst_bytes",
              "max_inflight"}
_TOP_KEYS = {"enabled", "node", "classes", "default", "tenants",
             "max_tenants", "quantum_bytes"}


def _check_keys(section: dict, allowed: set, where: str) -> None:
    unknown = set(section) - allowed
    if unknown:
        raise ValueError(
            f"qos policy: unknown key(s) {sorted(unknown)} in {where}")


def _tenant_spec(section: dict, where: str) -> TenantSpec:
    if not isinstance(section, dict):
        raise ValueError(f"qos policy: {where} must be an object")
    _check_keys(section, _TENANT_KEYS, where)
    weight = int(_num(section, "weight", 10, where))
    if weight <= 0:
        raise ValueError(f"qos policy: {where}weight must be >= 1")
    return TenantSpec(weight=weight,
                      max_queue=int(_num(section, "max_queue", 64, where)),
                      **_bucket_fields(section, where))


def parse_policy(doc: "dict | None") -> QosPolicy:
    """Validate + freeze one policy document. None/{} (or enabled:false)
    parses to a DISABLED policy — the scheduler short-circuits."""
    if not doc:
        return QosPolicy(enabled=False)
    if not isinstance(doc, dict):
        raise ValueError("qos policy: document must be a JSON object")
    _check_keys(doc, _TOP_KEYS, "top level")
    enabled = doc.get("enabled", True)
    if not isinstance(enabled, bool):
        raise ValueError("qos policy: enabled must be true/false")

    node_sec = doc.get("node") or {}
    if not isinstance(node_sec, dict):
        raise ValueError("qos policy: node must be an object")
    _check_keys(node_sec, _NODE_KEYS, "node.")
    node = BucketSpec(**_bucket_fields(node_sec, "node."))

    classes: dict[str, ClassSpec] = {}
    for klass, sec in (doc.get("classes") or {}).items():
        if klass not in CLASSES:
            raise ValueError(f"qos policy: unknown class {klass!r} "
                             f"(know {list(CLASSES)})")
        if not isinstance(sec, dict):
            raise ValueError(f"qos policy: classes.{klass} must be an "
                             "object")
        _check_keys(sec, _CLASS_KEYS, f"classes.{klass}.")
        classes[klass] = ClassSpec(
            max_wait_s=_num(sec, "max_wait_s",
                            _DEFAULT_MAX_WAIT_S.get(klass, 5.0),
                            f"classes.{klass}."),
            **_bucket_fields(sec, f"classes.{klass}."))

    default = _tenant_spec(doc.get("default") or {}, "default.")
    tenants = {}
    for name, sec in (doc.get("tenants") or {}).items():
        if not isinstance(name, str) or not name:
            raise ValueError(f"qos policy: bad tenant name {name!r}")
        tenants[name] = _tenant_spec(sec, f"tenants.{name}.")

    max_tenants = int(_num(doc, "max_tenants", 64))
    if max_tenants < 1:
        raise ValueError("qos policy: max_tenants must be >= 1")
    quantum = int(_num(doc, "quantum_bytes", 65536))
    if quantum < 1:
        raise ValueError("qos policy: quantum_bytes must be >= 1")
    return QosPolicy(enabled=enabled, node=node, classes=classes,
                     default=default, tenants=tenants,
                     max_tenants=max_tenants, quantum_bytes=quantum)
