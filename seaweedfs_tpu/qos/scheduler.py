"""The shared QoS scheduler core both tiers enforce through.

One `QosScheduler` instance lives on each enforcement point (volume
server, S3 gateway). Admission of one request walks three mechanisms:

  1. hierarchical token buckets — the tenant's request+byte buckets,
     nested under the class's and the node's. All-or-nothing: a grant
     debits every level, a miss refunds what it took and yields an ETA;
  2. weighted-fair queueing — a request that can't be granted NOW
     queues per (tenant, class); a pump thread drains queues with
     deficit round-robin (byte-costed quanta scaled by tenant weight)
     so a tenant's share under contention tracks its policy weight,
     not its offered load;
  3. priority classes — queues are served interactive > ingest >
     maintenance, and maintenance is only served at all when no
     foreground work is queued (plus a starvation grace so a repair
     can't be parked forever).

Sheds are explicit and costed: a request whose wait would exceed its
class's max_wait_s (or whose tenant queue is full) fails fast with
`QosShed` carrying a Retry-After estimate from the blocking bucket —
the enforcement points turn that into 503 + Retry-After, matching real
S3's SlowDown contract.

The scheduler is loop-agnostic and thread-safe: async handlers await
`admit()`, gRPC handler threads call `admit_sync()`, and internal
replica hops use `no_shed=True` (charge the buckets, never block —
the primary hop already paid, and shedding a replica write would turn
throttling into data-loss risk).

Everything observable: per-tenant request/byte/shed counters (bounded
tenant label via the policy's max_tenants + "~other" overflow), queue
depth gauges, a wait histogram with trace exemplars, `qos.shed` /
`qos.throttle` journal events, and a full live dump for /debug/qos.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque

from ..utils.log import logger
from . import CLASS_INGEST, CLASS_INTERACTIVE, CLASS_MAINTENANCE, CLASSES, \
    OVERFLOW_TENANT
from .policy import BucketSpec, QosPolicy, TenantSpec, parse_policy

log = logger("qos")

_FOREGROUND = (CLASS_INTERACTIVE, CLASS_INGEST)
# pump idle tick: bounds how stale a time-based grant can go even if a
# notify is lost, and doubles as the policy-file mtime poll period
_IDLE_TICK_S = 0.5
# journal rate limit: at most one qos.shed / qos.throttle event per
# tenant per second (a shed storm is exactly when the ring must not be
# 100% qos events; the counters carry the true rate)
_EVENT_INTERVAL_S = 1.0


class QosShed(Exception):
    """Request refused by admission control. `retry_after_s` is the
    bucket ETA the 503's Retry-After header advertises."""

    def __init__(self, tenant: str, klass: str, reason: str,
                 retry_after_s: float = 1.0):
        super().__init__(
            f"qos shed tenant={tenant} class={klass}: {reason} "
            f"(retry in ~{retry_after_s:.1f}s)")
        self.tenant = tenant
        self.klass = klass
        self.reason = reason
        self.retry_after_s = max(0.1, retry_after_s)

    @property
    def retry_after_header(self) -> str:
        return str(max(1, math.ceil(self.retry_after_s)))


class TokenBucket:
    """Monotonic-clock token bucket. rate 0 = unlimited (no state).
    NOT self-locking — the scheduler's lock covers every access."""

    __slots__ = ("rate", "burst", "tokens", "_last")

    def __init__(self, rate: float, burst: float, now: float):
        self.rate = float(rate)
        self.burst = float(burst) if burst else max(1.0, float(rate))
        self.tokens = self.burst
        self._last = now

    def _refill(self, now: float) -> None:
        if now > self._last:
            self.tokens = min(self.burst,
                              self.tokens + (now - self._last) * self.rate)
            self._last = now

    def eta(self, n: float, now: float) -> float:
        """Seconds until n tokens are available (0 = now). A cost larger
        than the whole burst is grantable at full bucket (the classic
        oversized-packet rule), so eta targets min(n, burst)."""
        if self.rate <= 0:
            return 0.0
        self._refill(now)
        need = min(n, self.burst) - self.tokens
        # float refill arithmetic leaves ~1e-15 residues; a "wait" that
        # small is a rounding artifact, not a throttle decision
        return need / self.rate if need > 1e-9 else 0.0

    def take(self, n: float, now: float) -> float:
        """Debit n if available; returns 0.0 on success else the ETA
        (nothing debited)."""
        wait = self.eta(n, now)
        if wait > 0:
            return wait
        if self.rate > 0:
            self.tokens -= n  # may go negative on an oversized cost
        return 0.0

    def force(self, n: float, now: float) -> None:
        """Unconditional debit (post-facto byte charges, no_shed hops):
        tokens may go negative, pushing future ETAs out — long-term
        rate stays honest even when the cost is only known after."""
        if self.rate > 0:
            self._refill(now)
            self.tokens -= n

    def refund(self, n: float) -> None:
        if self.rate > 0:
            self.tokens = min(self.burst, self.tokens + n)


class _BucketPair:
    """Request-count + byte buckets for one level of the hierarchy,
    plus that level's inflight cap."""

    __slots__ = ("req", "byt", "max_inflight", "inflight")

    def __init__(self, spec: BucketSpec, now: float, inflight: int = 0):
        self.req = (TokenBucket(spec.rps, spec.burst, now)
                    if spec.rps else None)
        self.byt = (TokenBucket(spec.bytes_per_s, spec.burst_bytes, now)
                    if spec.bytes_per_s else None)
        self.max_inflight = spec.max_inflight
        self.inflight = inflight

    def at_cap(self) -> bool:
        return bool(self.max_inflight) and \
            self.inflight >= self.max_inflight

    def eta(self, cost: float, now: float) -> float:
        wait = self.req.eta(1, now) if self.req else 0.0
        if self.byt is not None:
            if cost > 0:
                wait = max(wait, self.byt.eta(cost, now))
            else:
                # size-unknown requests (reads post-charge their
                # response) still honor byte DEBT: once post-facto
                # charges drove the bucket negative, nothing more runs
                # until the debt repays at the configured rate
                self.byt._refill(now)
                if self.byt.tokens < 0:
                    wait = max(wait, -self.byt.tokens / self.byt.rate)
        return wait

    def take(self, cost: float, now: float) -> None:
        if self.req:
            self.req.tokens -= 1
        if self.byt and cost > 0:
            self.byt.tokens -= cost

    def refund(self, cost: float) -> None:
        if self.req:
            self.req.refund(1)
        if self.byt and cost > 0:
            self.byt.refund(cost)

    def force(self, cost: float, now: float) -> None:
        if self.req:
            self.req.force(1, now)
        if self.byt and cost > 0:
            self.byt.force(cost, now)


class _Tenant:
    __slots__ = ("name", "spec", "pair", "deficit", "admitted", "shed",
                 "bytes")

    def __init__(self, name: str, spec: TenantSpec, now: float,
                 inflight: int = 0):
        self.name = name
        self.spec = spec
        self.pair = _BucketPair(spec, now, inflight)
        self.deficit: dict[str, float] = {k: 0.0 for k in CLASSES}
        self.admitted = 0
        self.shed = 0
        self.bytes = 0


class _Waiter:
    __slots__ = ("tenant", "klass", "cost", "enq", "deadline", "notify",
                 "done")

    def __init__(self, tenant: str, klass: str, cost: float, enq: float,
                 deadline: float, notify):
        self.tenant = tenant
        self.klass = klass
        self.cost = cost
        self.enq = enq
        self.deadline = deadline
        self.notify = notify  # called with a Grant or a QosShed
        self.done = False


class Grant:
    """One admitted request. Hold for the request's lifetime; `charge`
    debits bytes discovered after admission (GET response sizes);
    `release` frees the inflight slots and wakes the pump. Usable as a
    context manager. A disabled scheduler hands out inert grants."""

    __slots__ = ("_sched", "tenant", "klass", "_released")

    def __init__(self, sched: "QosScheduler | None", tenant: str = "",
                 klass: str = ""):
        self._sched = sched
        self.tenant = tenant
        self.klass = klass
        self._released = False

    def charge(self, nbytes: int) -> None:
        if self._sched is not None and nbytes > 0:
            self._sched._charge(self.tenant, self.klass, nbytes)

    def release(self) -> None:
        if self._sched is not None and not self._released:
            self._released = True
            self._sched._release(self.tenant, self.klass)

    def __enter__(self) -> "Grant":
        return self

    def __exit__(self, *exc) -> None:
        self.release()


_NOOP_GRANT = Grant(None)


class QosScheduler:
    """See module docstring. One instance per enforcement point."""

    def __init__(self, policy: "dict | QosPolicy | None" = None,
                 clock=time.monotonic, name: str = "qos"):
        self._clock = clock
        self.name = name
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._tenants: dict[str, _Tenant] = {}
        self._queues: dict[tuple[str, str], deque] = {}  # (tenant, class)
        self._rr: dict[str, deque] = {k: deque() for k in CLASSES}
        # tenant currently mid-service per class: a shared-bucket stall
        # resumes HERE next pass without re-crediting its deficit, so a
        # rate-limited round still walks the whole rotation instead of
        # re-serving whoever happens to sit at the head on every refill
        self._mid: dict[str, "str | None"] = {k: None for k in CLASSES}
        self._classes: dict[str, _BucketPair] = {}
        self._node: _BucketPair | None = None
        self._policy = QosPolicy(enabled=False)
        self._pump: threading.Thread | None = None
        self._stopping = False
        self._file: str | None = None
        self._file_mtime = 0.0
        self._last_event: dict[tuple[str, str], float] = {}
        self.shed_total = 0
        self.admitted_total = 0
        if policy is not None:
            self.load(policy)

    # -- policy lifecycle ----------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._policy.enabled

    def load(self, policy: "dict | QosPolicy | None") -> None:
        """(Re)apply a policy document — the hot-reload entry point
        (POST /debug/qos, the /etc/qos watcher, -qosPolicy mtime poll).
        Queued waiters survive; bucket levels reset to full burst;
        inflight counts carry over so caps stay accurate across a
        reload."""
        pol = (policy if isinstance(policy, QosPolicy)
               else parse_policy(policy))
        now = self._clock()
        start_pump = False
        with self._lock:
            inflight = {n: t.pair.inflight for n, t in self._tenants.items()}
            cls_inflight = {k: p.inflight for k, p in self._classes.items()}
            node_inflight = self._node.inflight if self._node else 0
            self._policy = pol
            self._tenants = {}
            self._classes = {
                k: _BucketPair(pol.class_spec(k), now,
                               cls_inflight.get(k, 0))
                for k in CLASSES}
            self._node = _BucketPair(pol.node, now, node_inflight)
            for name in list(inflight) + list(pol.tenants):
                if name not in self._tenants:
                    self._tenants[name] = _Tenant(
                        name, pol.tenant_spec(name), now,
                        inflight.get(name, 0))
            if (pol.enabled or self._file) and self._pump is None \
                    and not self._stopping:
                start_pump = True
            self._cond.notify_all()
        if start_pump:
            self._start_pump()
        log.info("%s: policy %s (%d named tenants)", self.name,
                 "enabled" if pol.enabled else "disabled",
                 len(pol.tenants))

    def attach_file(self, path: str) -> None:
        """Load policy from a JSON file and hot-reload it whenever the
        file's mtime moves (checked on the pump's idle tick)."""
        self._file = path
        self._reload_file(initial=True)
        with self._lock:
            need = self._pump is None and not self._stopping
        if need:
            self._start_pump()

    def _reload_file(self, initial: bool = False) -> None:
        import json
        import os
        path = self._file
        if not path:
            return
        try:
            mtime = os.stat(path).st_mtime
        except OSError as e:
            if initial:
                log.warning("%s: policy file %s unreadable (%s); "
                            "qos disabled", self.name, path, e)
                self.load(None)
            return
        if not initial and mtime == self._file_mtime:
            return
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
            self.load(doc)
            self._file_mtime = mtime
            if not initial:
                log.info("%s: policy reloaded from %s", self.name, path)
        except (ValueError, OSError) as e:
            # a broken edit must not tear down the running policy
            log.error("%s: policy file %s rejected: %s", self.name, path, e)
            self._file_mtime = mtime

    def close(self) -> None:
        """Stop the pump and shed every queued waiter (shutdown)."""
        with self._lock:
            self._stopping = True
            waiters = [w for q in self._queues.values() for w in q
                       if not w.done]
            for q in self._queues.values():
                q.clear()
            self._cond.notify_all()
        for w in waiters:
            w.done = True
            w.notify(QosShed(w.tenant, w.klass, "scheduler shutdown", 1.0))
        pump = self._pump
        if pump is not None:
            pump.join(timeout=5.0)
            self._pump = None

    # -- admission -----------------------------------------------------------
    async def admit(self, tenant: str, klass: str, cost: int = 0,
                    no_shed: bool = False) -> Grant:
        """Async admission (the HTTP handlers' entry point). Returns a
        Grant, raising QosShed when refused. `no_shed` charges the
        buckets but never queues or refuses (internal replica hops)."""
        if not self._policy.enabled:
            return _NOOP_GRANT
        if no_shed:
            return self._admit_forced(tenant, klass, cost)
        import asyncio
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()

        def notify(res):
            def _set():
                if fut.done():
                    # the awaiting task was cancelled (client gone
                    # while throttled): the granted slots must go back
                    # or the inflight caps leak shut one by one
                    if isinstance(res, Grant):
                        res.release()
                    return
                if isinstance(res, BaseException):
                    fut.set_exception(res)
                else:
                    fut.set_result(res)
            try:
                loop.call_soon_threadsafe(_set)
            except RuntimeError:  # loop already closed
                if isinstance(res, Grant):
                    res.release()

        self._submit(tenant, klass, cost, notify)
        return await fut

    def admit_sync(self, tenant: str, klass: str, cost: int = 0,
                   timeout: "float | None" = None) -> Grant:
        """Blocking admission for thread-based callers (gRPC handlers
        serving maintenance-tagged survivor reads)."""
        if not self._policy.enabled:
            return _NOOP_GRANT
        box: list = []
        ev = threading.Event()
        abandoned = [False]
        nlock = threading.Lock()

        def notify(res):
            with nlock:
                if abandoned[0]:
                    # caller timed out and left: hand the slots back
                    if isinstance(res, Grant):
                        res.release()
                    return
                box.append(res)
                ev.set()

        self._submit(tenant, klass, cost, notify)
        cap = (timeout if timeout is not None
               else self._policy.class_spec(klass).max_wait_s + 10.0)
        if not ev.wait(cap):
            with nlock:
                if not box:
                    abandoned[0] = True
                    raise QosShed(tenant, klass,
                                  "admission wait timed out", 1.0)
        res = box[0]
        if isinstance(res, BaseException):
            raise res
        return res

    def _submit(self, tenant: str, klass: str, cost: float, notify) -> None:
        """Shared admission entry: fast-path grant, immediate shed, or
        enqueue. `notify` fires exactly once with a Grant or QosShed."""
        if klass not in CLASSES:
            klass = CLASS_INGEST
        now = self._clock()
        result = None
        with self._lock:
            if self._stopping or not self._policy.enabled:
                result = _NOOP_GRANT
            else:
                t = self._resolve_locked(tenant, now)
                key = (t.name, klass)
                own_q = self._queues.get(key)
                # fast path only when nothing of same-or-higher priority
                # is queued ANYWHERE: a tenant must not sneak tokens past
                # competitors already waiting in its class (that is the
                # WFQ bypass the DRR exists to prevent), and a lower
                # class must not sneak past queued foreground work —
                # but interactive may fast-path past queued ingest
                fast_ok = not self._queued_at_or_above_locked(klass)
                if fast_ok and t.pair.at_cap() is False:
                    wait, inflight_blocked = self._eta_locked(t, klass,
                                                              cost, now)
                    if wait == 0.0 and not inflight_blocked:
                        self._take_locked(t, klass, cost, now)
                        self._count(t.name, klass, "admitted", cost)
                        result = Grant(self, t.name, klass)
                if result is None:
                    spec = self._policy.class_spec(klass)
                    wait, inflight_blocked = self._eta_locked(t, klass,
                                                              cost, now)
                    depth = len(own_q) if own_q else 0
                    if t.spec.max_queue and depth >= t.spec.max_queue:
                        result = self._shed_locked(
                            t, klass, "queue full", max(wait, 1.0))
                    elif wait > spec.max_wait_s and not inflight_blocked:
                        # can't possibly be served in time: fail fast
                        # with an honest Retry-After instead of parking
                        result = self._shed_locked(
                            t, klass, "rate limited", wait)
                    else:
                        w = _Waiter(t.name, klass, cost, now,
                                    now + spec.max_wait_s, notify)
                        self._queues.setdefault(key, deque()).append(w)
                        if t.name not in self._rr[klass]:
                            self._rr[klass].append(t.name)
                        self._gauge_depth(t.name)
                        self._cond.notify_all()
        if result is not None:
            notify(result)

    def _admit_forced(self, tenant: str, klass: str, cost: float) -> Grant:
        """Charge-and-go: debit every bucket level (tokens may go
        negative, delaying FUTURE admissions) and take the inflight
        slots, but never wait and never refuse."""
        now = self._clock()
        with self._lock:
            if not self._policy.enabled:
                return _NOOP_GRANT
            t = self._resolve_locked(tenant, now)
            t.pair.force(cost, now)
            cls = self._classes.get(klass)
            if cls is not None:
                cls.force(cost, now)
            if self._node is not None:
                self._node.force(cost, now)
            t.pair.inflight += 1
            if cls is not None:
                cls.inflight += 1
            if self._node is not None:
                self._node.inflight += 1
            self._count(t.name, klass, "admitted", cost)
            return Grant(self, t.name, klass)

    # -- bucket walk (all under self._lock) ----------------------------------
    def _resolve_locked(self, name: str, now: float) -> _Tenant:
        name = name or "default"
        t = self._tenants.get(name)
        if t is not None:
            return t
        pol = self._policy
        if name not in pol.tenants and len(self._tenants) >= pol.max_tenants:
            name = OVERFLOW_TENANT
            t = self._tenants.get(name)
            if t is not None:
                return t
        t = self._tenants[name] = _Tenant(name, pol.tenant_spec(name), now)
        return t

    def _eta_locked(self, t: _Tenant, klass: str, cost: float,
                    now: float) -> tuple[float, bool]:
        """(max bucket ETA, blocked-on-inflight?) across the hierarchy."""
        cls = self._classes.get(klass)
        wait = t.pair.eta(cost, now)
        inflight = t.pair.at_cap()
        if cls is not None:
            wait = max(wait, cls.eta(cost, now))
            inflight = inflight or cls.at_cap()
        if self._node is not None:
            wait = max(wait, self._node.eta(cost, now))
            inflight = inflight or self._node.at_cap()
        return wait, inflight

    def _take_locked(self, t: _Tenant, klass: str, cost: float,
                     now: float) -> None:
        """Debit every level + take the inflight slots (caller verified
        availability via _eta_locked under the same lock hold)."""
        cls = self._classes.get(klass)
        t.pair.take(cost, now)
        t.pair.inflight += 1
        if cls is not None:
            cls.take(cost, now)
            cls.inflight += 1
        if self._node is not None:
            self._node.take(cost, now)
            self._node.inflight += 1
        t.admitted += 1
        t.bytes += int(cost)
        self.admitted_total += 1

    def _shed_locked(self, t: _Tenant, klass: str, reason: str,
                     wait: float) -> QosShed:
        t.shed += 1
        self.shed_total += 1
        self._count(t.name, klass, "shed", 0)
        shed = QosShed(t.name, klass, reason, wait)
        self._event_locked("qos.shed", t.name, klass, reason=reason,
                           retry_after_s=round(shed.retry_after_s, 2))
        return shed

    def _foreground_queued_locked(self) -> bool:
        return any(q for (name, klass), q in self._queues.items()
                   if klass in _FOREGROUND)

    def _queued_at_or_above_locked(self, klass: str) -> bool:
        cutoff = CLASSES.index(klass) if klass in CLASSES else len(CLASSES)
        return any(q for (name, k), q in self._queues.items()
                   if k in CLASSES and CLASSES.index(k) <= cutoff)

    # -- release / post-charge ----------------------------------------------
    def _release(self, tenant: str, klass: str) -> None:
        with self._lock:
            t = self._tenants.get(tenant)
            if t is not None and t.pair.inflight > 0:
                t.pair.inflight -= 1
            cls = self._classes.get(klass)
            if cls is not None and cls.inflight > 0:
                cls.inflight -= 1
            if self._node is not None and self._node.inflight > 0:
                self._node.inflight -= 1
            self._cond.notify_all()

    def _charge(self, tenant: str, klass: str, nbytes: int) -> None:
        now = self._clock()
        with self._lock:
            t = self._tenants.get(tenant)
            if t is None:
                return
            if t.pair.byt is not None:
                t.pair.byt.force(nbytes, now)
            cls = self._classes.get(klass)
            if cls is not None and cls.byt is not None:
                cls.byt.force(nbytes, now)
            if self._node is not None and self._node.byt is not None:
                self._node.byt.force(nbytes, now)
            t.bytes += nbytes
        try:
            from ..stats import QOS_BYTES
            QOS_BYTES.inc(tenant, klass, amount=nbytes)
        except Exception:  # noqa: BLE001  # swtpu-lint: disable=silent-except (metrics must never break admission)
            pass

    # -- the pump: WFQ drain + deadline sheds --------------------------------
    def _start_pump(self) -> None:
        with self._lock:
            if self._pump is not None or self._stopping:
                return
            self._pump = threading.Thread(
                target=self._pump_loop, daemon=True,
                name=f"qos-pump-{self.name}")
            self._pump.start()

    def _pump_loop(self) -> None:
        while True:
            with self._lock:
                if self._stopping:
                    return
                grants, sheds, next_dl = self._schedule_locked()
                if not grants and not sheds:
                    now = self._clock()
                    wait = _IDLE_TICK_S
                    if next_dl is not None:
                        wait = min(wait, max(0.0, next_dl - now) + 0.001)
                    self._cond.wait(timeout=wait)
            # notify OUTSIDE the lock: grant callbacks hop onto event
            # loops and shed callbacks may log
            for w, grant in grants:
                w.notify(grant)
            for w, shed in sheds:
                w.notify(shed)
            if self._file:
                self._reload_file()

    def _schedule_locked(self):
        """One WFQ pass. Returns ([(waiter, Grant)], [(waiter, QosShed)],
        next_deadline|None)."""
        now = self._clock()
        grants: list = []
        sheds: list = []
        next_dl: "float | None" = None
        quantum = float(self._policy.quantum_bytes)

        # 1) deadline sheds, every class (expired waiters must clear
        #    even in classes the grant pass won't reach)
        for key in list(self._queues):
            q = self._queues[key]
            while q and q[0].deadline <= now:
                w = q.popleft()
                w.done = True
                t = self._tenants.get(w.tenant)
                if t is not None:
                    wait, _ = self._eta_locked(t, w.klass, w.cost, now)
                    sheds.append((w, self._shed_locked(
                        t, w.klass, "queued past max_wait",
                        max(wait, 1.0))))
                else:  # tenant state vanished in a reload
                    sheds.append((w, QosShed(w.tenant, w.klass,
                                             "queued past max_wait", 1.0)))
            if not q:
                del self._queues[key]
                self._gauge_depth(key[0])

        fg_queued = self._foreground_queued_locked()
        for klass in CLASSES:
            rotation = self._rr[klass]
            # prune tenants with nothing queued in this class
            for _ in range(len(rotation)):
                name = rotation[0]
                if self._queues.get((name, klass)):
                    rotation.rotate(-1)
                else:
                    rotation.popleft()
            if not rotation:
                continue
            if klass == CLASS_MAINTENANCE and fg_queued:
                # maintenance yields to queued foreground work — unless
                # its head waiter has aged past the starvation grace
                grace = 0.5 * self._policy.class_spec(klass).max_wait_s
                heads = [self._queues[(n, klass)][0] for n in rotation]
                if not any(now - w.enq >= grace for w in heads):
                    dl = min(w.enq + grace for w in heads)
                    next_dl = dl if next_dl is None else min(next_dl, dl)
                    continue
            # DRR: walk the rotation, each tenant gaining one
            # weight-scaled quantum per visit and draining its head
            # while deficit + buckets allow. A SHARED bucket (class or
            # node level) running dry stalls the whole class — stop and
            # resume at this very tenant with its remaining deficit on
            # the next pass (self._mid), so the refill trickle is split
            # by weight across the rotation instead of feeding whoever
            # sits at the head. Tenant-level stalls just skip that
            # tenant.
            visits = 0
            while rotation and visits <= len(rotation):
                name = rotation[0]
                q = self._queues.get((name, klass))
                if not q:
                    rotation.popleft()
                    if self._mid[klass] == name:
                        self._mid[klass] = None
                    continue
                t = self._tenants.get(name)
                if t is None:
                    t = self._resolve_locked(name, now)
                if self._mid[klass] != name:
                    t.deficit[klass] += quantum * (t.spec.weight / 10.0)
                    self._mid[klass] = name
                stalled_shared = False
                while q:
                    w = q[0]
                    unit = max(float(w.cost), 1.0)
                    if unit > t.deficit[klass]:
                        break
                    wait, inflight_blocked = self._eta_locked(
                        t, klass, w.cost, now)
                    if inflight_blocked or wait > 0:
                        if wait > 0:
                            dl = now + wait
                            next_dl = (dl if next_dl is None
                                       else min(next_dl, dl))
                        # a stall the TENANT's own limits didn't cause
                        # is the shared-capacity stall we must resume at
                        t_wait = t.pair.eta(w.cost, now)
                        stalled_shared = not (t.pair.at_cap()
                                              or t_wait >= wait > 0)
                        break
                    q.popleft()
                    w.done = True
                    t.deficit[klass] -= unit
                    self._take_locked(t, klass, w.cost, now)
                    self._count(name, klass, "queued", w.cost)
                    self._observe_wait(klass, now - w.enq)
                    self._throttle_event_locked(t, klass, now - w.enq)
                    grants.append((w, Grant(self, name, klass)))
                if not q:
                    self._queues.pop((name, klass), None)
                    t.deficit[klass] = 0.0
                self._gauge_depth(name)
                if stalled_shared:
                    break  # resume at this tenant, deficit retained
                # tenant's turn is over (queue drained, deficit spent,
                # or its own limits stalled it): move to the next
                self._mid[klass] = None
                if self._queues.get((name, klass)):
                    rotation.rotate(-1)
                elif rotation and rotation[0] == name:
                    rotation.popleft()
                visits += 1
        return grants, sheds, next_dl

    # -- observability --------------------------------------------------------
    def _count(self, tenant: str, klass: str, outcome: str,
               cost: float) -> None:
        try:
            from ..stats import QOS_BYTES, QOS_REQUESTS
            QOS_REQUESTS.inc(tenant, klass, outcome)
            if cost > 0 and outcome != "shed":
                QOS_BYTES.inc(tenant, klass, amount=cost)
        except Exception:  # noqa: BLE001  # swtpu-lint: disable=silent-except (metrics must never break admission)
            pass

    def _observe_wait(self, klass: str, wait: float) -> None:
        try:
            from ..stats import QOS_WAIT_SECONDS
            QOS_WAIT_SECONDS.observe(klass, value=wait)
        except Exception:  # noqa: BLE001  # swtpu-lint: disable=silent-except (metrics must never break admission)
            pass

    def _gauge_depth(self, tenant: str) -> None:
        try:
            from ..stats import QOS_QUEUE_DEPTH
            depth = sum(len(q) for (n, _k), q in self._queues.items()
                        if n == tenant)
            QOS_QUEUE_DEPTH.set(tenant, value=depth)
        except Exception:  # noqa: BLE001  # swtpu-lint: disable=silent-except (metrics must never break admission)
            pass

    def _event_locked(self, etype: str, tenant: str, klass: str,
                      **attrs) -> None:
        """Rate-limited journal emit (one per tenant per second per
        event type; the counters carry the true rates)."""
        now = self._clock()
        key = (etype, tenant)
        if now - self._last_event.get(key, -_EVENT_INTERVAL_S) \
                < _EVENT_INTERVAL_S:
            return
        self._last_event[key] = now
        try:
            from ..ops import events
            events.emit(etype, severity=events.WARN, tenant=tenant,
                        klass=klass, node=self.name, **attrs)
        except Exception:  # noqa: BLE001  # swtpu-lint: disable=silent-except (journal must never break admission)
            pass

    def _throttle_event_locked(self, t: _Tenant, klass: str,
                               waited: float) -> None:
        spec = self._policy.class_spec(klass)
        if waited >= max(0.25, 0.25 * spec.max_wait_s):
            self._event_locked("qos.throttle", t.name, klass,
                               waited_ms=round(waited * 1e3, 1))

    def debug_payload(self) -> dict:
        """Live scheduler state for /debug/qos: policy summary, node and
        class buckets, per-tenant tokens/inflight/queue/counters."""
        now = self._clock()

        def pair(p: "_BucketPair | None") -> dict:
            if p is None:
                return {}
            out: dict = {"inflight": p.inflight}
            if p.max_inflight:
                out["max_inflight"] = p.max_inflight
            if p.req is not None:
                p.req._refill(now)
                out["req_tokens"] = round(p.req.tokens, 2)
                out["rps"] = p.req.rate
            if p.byt is not None:
                p.byt._refill(now)
                out["byte_tokens"] = round(p.byt.tokens)
                out["bytes_per_s"] = p.byt.rate
            return out

        with self._lock:
            pol = self._policy
            tenants = []
            for name, t in sorted(self._tenants.items()):
                queued = {k: len(self._queues.get((name, k), ()))
                          for k in CLASSES
                          if self._queues.get((name, k))}
                tenants.append({
                    "tenant": name, "weight": t.spec.weight,
                    "admitted": t.admitted, "shed": t.shed,
                    "bytes": t.bytes, "queued": queued,
                    **pair(t.pair)})
            return {
                "enabled": pol.enabled,
                "policy": {"max_tenants": pol.max_tenants,
                           "quantum_bytes": pol.quantum_bytes,
                           "named_tenants": sorted(pol.tenants),
                           "file": self._file or None},
                "node": pair(self._node),
                "classes": {k: {"max_wait_s":
                                pol.class_spec(k).max_wait_s,
                                **pair(self._classes.get(k))}
                            for k in CLASSES},
                "tenants": tenants,
                "totals": {"admitted": self.admitted_total,
                           "shed": self.shed_total},
            }
