"""Query engine: S3-Select-lite scan/filter over JSON and CSV blobs.

Reference: weed/query/json/query_json.go (gjson path filtering +
projections, consumed by the volume server's Query RPC,
volume_grpc_query.go). The reference leaves CSV input as a stub; we
support it.
"""

from .json_query import Query, get_path, query_json, query_json_lines
from .csv_query import query_csv_lines

__all__ = ["Query", "get_path", "query_json", "query_json_lines",
           "query_csv_lines"]
