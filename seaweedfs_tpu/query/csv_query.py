"""CSV scan/filter: the part the reference stubs out
(volume_grpc_query.go:38 `if req.InputSerialization.CsvInput != nil {}`).

Columns are addressed by header name (when has_header) or `_1`, `_2`, …
positional names, like S3 Select.
"""

from __future__ import annotations

import csv
import io
from typing import Any

from .json_query import Query, _compare  # shared predicate semantics


def _coerce(s: str) -> Any:
    try:
        return int(s)
    except ValueError:
        try:
            return float(s)
        except ValueError:
            return s


def query_csv_lines(data: bytes, projections: list[str], query: Query,
                    delimiter: str = ",",
                    has_header: bool = False) -> list[list[Any]]:
    text = data.decode("utf-8", errors="replace")
    reader = csv.reader(io.StringIO(text), delimiter=delimiter or ",")
    rows = list(reader)
    if not rows:
        return []
    if has_header:
        header = rows[0]
        rows = rows[1:]
    else:
        header = []
    results = []
    for row in rows:
        rec = {f"_{i + 1}": v for i, v in enumerate(row)}
        rec.update({h: v for h, v in zip(header, row)})
        if query.field:
            if query.field not in rec:
                continue
            if not _compare(_coerce(rec[query.field]), query.op, query.value):
                continue
        if projections:
            results.append([_coerce(rec[p]) if p in rec else None
                            for p in projections])
        else:
            results.append(row)
    return results
