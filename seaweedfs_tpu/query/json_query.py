"""JSON line filtering with dotted-path lookups.

Behavior mirrors reference weed/query/json/query_json.go:17 (QueryJson:
filter on one (field, op, value) predicate, then project paths), :29
(filterJson), with the gjson path subset we need: dotted keys, numeric
array indices, `#` for array length, and `array.#.key` fan-out.
Comparison semantics follow query_json.go:45-106 — string compares for
string values, numeric compares for numbers, existence when op is "".
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Any


@dataclass
class Query:
    field: str = ""
    op: str = ""  # "", =, !=, <, <=, >, >=
    value: str = ""


_MISSING = object()


def get_path(doc: Any, path: str):
    """Dotted-path getter; returns _MISSING sentinel when absent.
    `arr.#` is the array length; `arr.#.rest` fans out `rest` over the
    elements (gjson semantics), dropping elements where it's absent."""
    if not path:
        return _MISSING
    cur = doc
    parts = path.split(".")
    for i, part in enumerate(parts):
        if isinstance(cur, list):
            if part == "#":
                rest = ".".join(parts[i + 1:])
                if not rest:
                    return len(cur)
                fan = [get_path(el, rest) for el in cur]
                return [v for v in fan if v is not _MISSING]
            try:
                cur = cur[int(part)]
                continue
            except (ValueError, IndexError):
                return _MISSING
        if isinstance(cur, dict):
            if part in cur:
                cur = cur[part]
                continue
            return _MISSING
        return _MISSING
    return cur


def _compare(value: Any, op: str, rhs: str) -> bool:
    if value is _MISSING:
        return False
    if op == "":
        return True  # existence check (query_json.go:39-44)
    if isinstance(value, list):
        # fan-out result: the predicate matches if any element matches
        return any(_compare(v, op, rhs) for v in value)
    if isinstance(value, bool):
        want = rhs.lower() == "true"
        return (value == want) if op == "=" else (
            value != want if op == "!=" else False)
    if isinstance(value, (int, float)):
        try:
            r = float(rhs)
        except ValueError:
            return False
        return {"=": value == r, "!=": value != r, "<": value < r,
                "<=": value <= r, ">": value > r, ">=": value >= r}.get(op, False)
    if isinstance(value, str):
        return {"=": value == rhs, "!=": value != rhs, "<": value < rhs,
                "<=": value <= rhs, ">": value > rhs,
                ">=": value >= rhs}.get(op, False)
    if value is None:
        return op == "=" and rhs.lower() in ("null", "")
    return False


def query_json(line: str, projections: list[str],
               query: Query) -> tuple[bool, list[Any]]:
    """One JSON document: (passed_filter, projected values).
    Reference QueryJson query_json.go:17."""
    try:
        doc = json.loads(line)
    except json.JSONDecodeError:
        return False, []
    if query.field:
        if not _compare(get_path(doc, query.field), query.op, query.value):
            return False, []
    if not projections:
        return True, [doc]
    out = []
    for p in projections:
        v = get_path(doc, p)
        out.append(None if v is _MISSING else v)
    return True, out


def query_json_lines(data: bytes, projections: list[str],
                     query: Query) -> list[list[Any]]:
    """Newline-delimited JSON scan (the volume Query RPC input shape)."""
    results = []
    for raw in data.splitlines():
        raw = raw.strip()
        if not raw:
            continue
        ok, values = query_json(raw.decode("utf-8", errors="replace"),
                                projections, query)
        if ok:
            results.append(values)
    return results
