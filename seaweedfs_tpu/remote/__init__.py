"""Remote storage mounts (reference weed/remote_storage + filer
read_remote.go / remote_mapping.go): graft an external object store's
listing into the filer namespace, read through on demand, cache/uncache
chunks explicitly.
"""

from .remote_mount import (cache_remote, mount_remote, read_remote,
                           uncache_remote, unmount_remote)

__all__ = ["mount_remote", "unmount_remote", "cache_remote",
           "uncache_remote", "read_remote"]
