"""Azure Blob Storage client + replication sink, REST + SharedKey.

Reference: weed/remote_storage/azure/azure_storage_client.go and
weed/replication/sink/azuresink/azure_sink.go use the Azure SDK; this
speaks the Blob service REST API directly (x-ms-version 2020-10-02) with
SharedKey request signing — no SDK, so it works in this image and against
utils/mini_azure.MiniAzure offline; point it at
https://{account}.blob.core.windows.net and the same bytes flow to real
Azure.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import urllib.parse
import xml.etree.ElementTree as ET
from email.utils import formatdate

from ..client import http_util
from ..pb import filer_pb2 as fpb
from ..replication.sink import DataReader, ReplicationSink
from ..storage.backend import RemoteStorageClient
from ..utils.log import logger

log = logger("remote.azure")

X_MS_VERSION = "2020-10-02"


def sign_shared_key(method: str, account: str, key_b64: str, path: str,
                    query: "dict[str, str]", headers: "dict[str, str]",
                    content_length: int) -> str:
    """Authorization header value for the SharedKey scheme
    (learn.microsoft.com 'Authorize with Shared Key', implemented from the
    spec: VERB + standard headers + canonicalized x-ms headers+resource)."""
    canon_headers = "".join(
        f"{k.lower()}:{v}\n" for k, v in sorted(headers.items())
        if k.lower().startswith("x-ms-"))
    canon_resource = f"/{account}{path}"
    for k in sorted(query):
        canon_resource += f"\n{k.lower()}:{query[k]}"
    string_to_sign = "\n".join([
        method,
        headers.get("Content-Encoding", ""),
        headers.get("Content-Language", ""),
        str(content_length) if content_length else "",
        headers.get("Content-MD5", ""),
        headers.get("Content-Type", ""),
        "",  # Date: empty, x-ms-date is authoritative
        headers.get("If-Modified-Since", ""),
        headers.get("If-Match", ""),
        headers.get("If-None-Match", ""),
        headers.get("If-Unmodified-Since", ""),
        headers.get("Range", ""),
    ]) + "\n" + canon_headers + canon_resource
    mac = hmac.new(base64.b64decode(key_b64), string_to_sign.encode("utf-8"),
                   hashlib.sha256)
    return f"SharedKey {account}:{base64.b64encode(mac.digest()).decode()}"


class AzureBlobClient(RemoteStorageClient):
    name = "azure"

    def __init__(self, endpoint: str, account: str, key_b64: str,
                 container: str):
        self.endpoint = endpoint.rstrip("/")
        self.account = account
        self.key_b64 = key_b64
        self.container = container

    # -- signed round trip --------------------------------------------------
    def _request(self, method: str, blob: str = "",
                 query: "dict[str, str] | None" = None, body: bytes = b"",
                 extra_headers: "dict[str, str] | None" = None
                 ) -> http_util.Response:
        query = query or {}
        # sign the PERCENT-ENCODED path — Azure canonicalizes from the
        # request URI, so a raw-name signature 403s on keys needing
        # encoding (spaces, non-ASCII)
        qblob = urllib.parse.quote(blob) if blob else ""
        path = f"/{self.container}" + (f"/{qblob}" if blob else "")
        headers = {
            "x-ms-date": formatdate(usegmt=True),
            "x-ms-version": X_MS_VERSION,
        }
        if extra_headers:
            headers.update(extra_headers)
        headers["Authorization"] = sign_shared_key(
            method, self.account, self.key_b64, path,
            query, headers, len(body))
        # Content-Length itself is added by http_util for PUT/POST
        url = self.endpoint + path
        return http_util.request(method, url, body=body or None,
                                 headers=headers, params=query, timeout=60)

    def ensure_container(self) -> None:
        r = self._request("PUT", query={"restype": "container"})
        if r.status not in (201, 409):  # 409 = already exists
            raise OSError(f"azure create container: HTTP {r.status} "
                          f"{r.content[:200]!r}")

    def put_bytes(self, key: str, data: bytes) -> None:
        r = self._request("PUT", key, body=data,
                          extra_headers={"x-ms-blob-type": "BlockBlob"})
        if r.status >= 300:
            raise OSError(f"azure PUT {key}: HTTP {r.status} "
                          f"{r.content[:200]!r}")

    # -- RemoteStorageClient surface ----------------------------------------
    def write_object(self, key: str, src_path: str) -> int:
        with open(src_path, "rb") as f:
            data = f.read()
        self.put_bytes(key, data)
        return len(data)

    def read_object(self, key: str, offset: int, size: int) -> bytes:
        r = self._request(
            "GET", key,
            extra_headers={"Range": f"bytes={offset}-{offset + size - 1}"})
        if r.status not in (200, 206):
            raise OSError(f"azure GET {key}: HTTP {r.status}")
        return r.content

    def object_size(self, key: str) -> int:
        r = self._request("HEAD", key)
        if r.status >= 300:
            raise OSError(f"azure HEAD {key}: HTTP {r.status}")
        return int(r.headers.get("Content-Length", "0"))

    def delete_object(self, key: str) -> None:
        r = self._request("DELETE", key)
        if r.status not in (202, 404):
            raise OSError(f"azure DELETE {key}: HTTP {r.status}")

    def list_keys(self, prefix: str = "") -> "list[str]":
        keys: list[str] = []
        marker = ""
        while True:
            q = {"restype": "container", "comp": "list"}
            if prefix:
                q["prefix"] = prefix
            if marker:
                q["marker"] = marker
            r = self._request("GET", query=q)
            if r.status >= 300:
                raise OSError(f"azure list: HTTP {r.status}")
            root = ET.fromstring(r.content)
            for name in root.iter("Name"):
                keys.append(name.text or "")
            marker = (root.findtext("NextMarker") or "").strip()
            if not marker:
                return keys


class AzureSink(ReplicationSink):
    """Replicate filer events into an Azure container (reference
    sink/azuresink/azure_sink.go semantics: entries become block blobs,
    directories are skipped, deletes remove the blob)."""

    name = "azure"

    def __init__(self, client: AzureBlobClient, dir_prefix: str = ""):
        self.client = client
        self.prefix = dir_prefix.strip("/")
        client.ensure_container()

    def _key(self, path: str) -> str:
        key = path.lstrip("/")
        return f"{self.prefix}/{key}" if self.prefix else key

    def create_entry(self, path: str, entry: fpb.Entry,
                     read_data: DataReader,
                     signatures: "list[int] | None" = None) -> None:
        if entry.is_directory:
            return
        self.client.put_bytes(self._key(path), read_data(entry))

    def update_entry(self, path: str, entry: fpb.Entry,
                     read_data: DataReader,
                     signatures: "list[int] | None" = None) -> None:
        self.create_entry(path, entry, read_data, signatures)

    def delete_entry(self, path: str, is_directory: bool) -> None:
        if is_directory:
            return  # containers are flat; directory markers don't exist
        self.client.delete_object(self._key(path))


def parse_azure_spec(arg: str) -> AzureBlobClient:
    """'http://host:port/container?account:base64key' (real Azure:
    'https://{account}.blob.core.windows.net/container?account:key')."""
    url, _, cred = arg.partition("?")
    scheme, sep, rest = url.partition("://")
    if not sep:
        raise ValueError(f"azure spec needs an endpoint URL, got {arg!r}")
    host, _, container = rest.partition("/")
    account, _, key = cred.partition(":")
    if not (container and account and key):
        raise ValueError(
            "azure spec: endpoint/container?account:base64key required")
    return AzureBlobClient(f"{scheme}://{host}", account, key, container)
