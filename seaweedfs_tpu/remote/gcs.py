"""Google Cloud Storage client + sink over the native JSON API.

Reference: weed/remote_storage/gcs/gcs_storage_client.go and
weed/replication/sink/gcssink/gcs_sink.go use the GCS SDK; this speaks
the JSON API directly (upload: POST /upload/storage/v1/b/{bucket}/o,
data: GET /storage/v1/b/{bucket}/o/{object}?alt=media, list with
pageToken) authorized by a bearer token — offline it runs against
utils/mini_gcs.MiniGcs; on GCP, pass a token from the metadata server or
`gcloud auth print-access-token`. (HMAC-key users can keep using the
S3-compat path, storage/backend.py S3Remote.)
"""

from __future__ import annotations

import json
import urllib.parse

from ..client import http_util
from ..pb import filer_pb2 as fpb
from ..replication.sink import DataReader, ReplicationSink
from ..storage.backend import RemoteStorageClient
from ..utils.log import logger

log = logger("remote.gcs")


class GcsClient(RemoteStorageClient):
    name = "gcs-json"

    def __init__(self, endpoint: str, bucket: str, token: str):
        self.endpoint = endpoint.rstrip("/")
        self.bucket = bucket
        self.token = token

    def _hdrs(self) -> dict:
        return {"Authorization": f"Bearer {self.token}"}

    def _obj_url(self, key: str) -> str:
        return (f"{self.endpoint}/storage/v1/b/{self.bucket}/o/"
                f"{urllib.parse.quote(key, safe='')}")

    def put_bytes(self, key: str, data: bytes) -> None:
        r = http_util.post(
            f"{self.endpoint}/upload/storage/v1/b/{self.bucket}/o",
            body=data,
            headers={**self._hdrs(),
                     "Content-Type": "application/octet-stream"},
            params={"uploadType": "media", "name": key})
        if r.status >= 300:
            raise OSError(f"gcs upload {key}: HTTP {r.status} "
                          f"{r.content[:200]!r}")

    def write_object(self, key: str, src_path: str) -> int:
        with open(src_path, "rb") as f:
            data = f.read()
        self.put_bytes(key, data)
        return len(data)

    def read_object(self, key: str, offset: int, size: int) -> bytes:
        r = http_util.get(
            self._obj_url(key), params={"alt": "media"},
            headers={**self._hdrs(),
                     "Range": f"bytes={offset}-{offset + size - 1}"})
        if r.status not in (200, 206):
            raise OSError(f"gcs GET {key}: HTTP {r.status}")
        return r.content

    def object_size(self, key: str) -> int:
        r = http_util.get(self._obj_url(key), headers=self._hdrs())
        if r.status >= 300:
            raise OSError(f"gcs stat {key}: HTTP {r.status}")
        return int(r.json().get("size", 0))

    def delete_object(self, key: str) -> None:
        r = http_util.request("DELETE", self._obj_url(key),
                              headers=self._hdrs())
        if r.status not in (204, 404):
            raise OSError(f"gcs DELETE {key}: HTTP {r.status}")

    def list_keys(self, prefix: str = "") -> "list[str]":
        keys: list[str] = []
        token = ""
        while True:
            params = {"prefix": prefix} if prefix else {}
            if token:
                params["pageToken"] = token
            r = http_util.get(
                f"{self.endpoint}/storage/v1/b/{self.bucket}/o",
                params=params or None, headers=self._hdrs())
            if r.status >= 300:
                raise OSError(f"gcs list: HTTP {r.status}")
            doc = r.json()
            keys.extend(item["name"] for item in doc.get("items", []))
            token = doc.get("nextPageToken", "")
            if not token:
                return keys


class GcsSink(ReplicationSink):
    name = "gcs-json"

    def __init__(self, client: GcsClient, dir_prefix: str = ""):
        self.client = client
        self.prefix = dir_prefix.strip("/")

    def _key(self, path: str) -> str:
        key = path.lstrip("/")
        return f"{self.prefix}/{key}" if self.prefix else key

    def create_entry(self, path: str, entry: fpb.Entry,
                     read_data: DataReader,
                     signatures: "list[int] | None" = None) -> None:
        if entry.is_directory:
            return
        self.client.put_bytes(self._key(path), read_data(entry))

    def update_entry(self, path: str, entry: fpb.Entry,
                     read_data: DataReader,
                     signatures: "list[int] | None" = None) -> None:
        self.create_entry(path, entry, read_data, signatures)

    def delete_entry(self, path: str, is_directory: bool) -> None:
        if is_directory:
            return
        self.client.delete_object(self._key(path))


def parse_gcs_spec(arg: str) -> GcsClient:
    """'http://host:port/bucket?token' (real GCS:
    'https://storage.googleapis.com/bucket?<access-token>')."""
    url, _, token = arg.partition("?")
    scheme, sep, rest = url.partition("://")
    if not sep:
        raise ValueError(f"gcs-json spec needs an endpoint URL, got {arg!r}")
    host, _, bucket = rest.partition("/")
    if not (bucket and token):
        raise ValueError("gcs-json spec: endpoint/bucket?token required")
    return GcsClient(f"{scheme}://{host}", bucket, token)
