"""Mount an external object store path into the filer namespace.

Reference behavior (weed/filer/read_remote.go, remote_mapping.go, shell
remote.mount/remote.cache/remote.uncache):
- remote.mount imports the remote listing as entries whose `extended`
  metadata carries the remote ref; no data is copied.
- reads of an uncached entry stream straight from the remote store.
- remote.cache materializes chunks in the blob cluster (after which
  reads are local); remote.uncache drops them again.
- mappings persist at /etc/remote/mount.json (reference stores them in
  the filer the same way).

Remote refs live in entry.extended["remote"] as JSON
{"spec": backend spec, "key": object key, "size": bytes}.
"""

from __future__ import annotations

import json

from ..filer.filer import join_path, split_path
from ..pb import filer_pb2 as fpb
from ..storage.backend import open_remote
from ..utils.log import logger

log = logger("remote")

MOUNT_CONF = "/etc/remote/mount.json"
REMOTE_KEY = b"remote"  # extended map key (bytes per proto)


def _load_mappings(fs) -> dict:
    d, n = split_path(MOUNT_CONF)
    entry = fs.filer.find_entry(d, n)
    if entry is None:
        return {}
    try:
        return json.loads(fs.read_entry_bytes(entry))
    except Exception:  # noqa: BLE001
        return {}


def _save_mappings(fs, mappings: dict) -> None:
    fs.write_file(MOUNT_CONF, json.dumps(mappings, indent=2).encode(),
                  mime="application/json")


def mount_remote(fs, directory: str, spec: str, prefix: str = "") -> int:
    """Import the remote listing under `directory`; returns entry count."""
    client = open_remote(spec)
    count = 0
    for key in client.list_keys(prefix):
        rel = key[len(prefix):].lstrip("/") if prefix else key
        if not rel:
            continue
        path = join_path(directory, rel)
        d, n = split_path(path)
        size = client.object_size(key)
        entry = fpb.Entry(name=n)
        entry.attributes.file_size = size
        entry.attributes.file_mode = 0o644
        entry.extended[REMOTE_KEY.decode()] = json.dumps(
            {"spec": spec, "key": key, "size": size}).encode()
        fs.filer.create_entry(d, entry)
        count += 1
    mappings = _load_mappings(fs)
    mappings[directory] = {"spec": spec, "prefix": prefix}
    _save_mappings(fs, mappings)
    log.info("mounted %s (%s, prefix=%r): %d entries",
             directory, spec, prefix, count)
    return count


def unmount_remote(fs, directory: str) -> None:
    d, n = split_path(directory)
    if fs.filer.find_entry(d, n) is not None:
        fs.filer.delete_entry(d, n, is_recursive=True, is_delete_data=True)
    mappings = _load_mappings(fs)
    mappings.pop(directory, None)
    _save_mappings(fs, mappings)


def remote_ref(entry: fpb.Entry) -> dict | None:
    raw = entry.extended.get(REMOTE_KEY.decode())
    if not raw:
        return None
    try:
        return json.loads(raw)
    except Exception:  # noqa: BLE001
        return None


def read_remote(entry: fpb.Entry, offset: int = 0,
                size: int | None = None) -> bytes:
    """Stream an uncached remote entry's bytes (read_remote.go)."""
    ref = remote_ref(entry)
    if ref is None:
        raise ValueError("entry has no remote ref")
    client = open_remote(ref["spec"])
    total = ref.get("size") or client.object_size(ref["key"])
    if size is None:
        size = total - offset
    size = max(0, min(size, total - offset))
    if size == 0:
        return b""
    return client.read_object(ref["key"], offset, size)


def cache_remote(fs, path: str) -> fpb.Entry:
    """Materialize a remote entry's data as local chunks
    (shell remote.cache)."""
    d, n = split_path(path)
    entry = fs.filer.find_entry(d, n)
    if entry is None:
        raise FileNotFoundError(path)
    ref = remote_ref(entry)
    if ref is None:
        raise ValueError(f"{path} is not a remote entry")
    if entry.chunks:
        return entry  # already cached
    data = read_remote(entry)
    cached = fs.write_file(path, data, mime=entry.attributes.mime)
    # keep the remote ref so uncache can revert
    updated = fs.filer.find_entry(d, n)
    updated.extended[REMOTE_KEY.decode()] = json.dumps(ref).encode()
    fs.filer.update_entry(d, updated)
    return cached


def uncache_remote(fs, path: str) -> None:
    """Drop local chunks, keep the remote ref (shell remote.uncache)."""
    d, n = split_path(path)
    entry = fs.filer.find_entry(d, n)
    if entry is None:
        raise FileNotFoundError(path)
    ref = remote_ref(entry)
    if ref is None:
        raise ValueError(f"{path} is not a remote entry")
    if not entry.chunks:
        return
    # update_entry's replaced-chunk GC deletes the dropped chunks server-side
    updated = fpb.Entry()
    updated.CopyFrom(entry)
    del updated.chunks[:]
    updated.attributes.file_size = ref.get("size", 0)
    fs.filer.update_entry(d, updated)
