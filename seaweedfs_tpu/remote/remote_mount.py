"""Mount an external object store path into the filer namespace.

Reference behavior (weed/filer/read_remote.go, remote_mapping.go, shell
remote.mount/remote.cache/remote.uncache):
- remote.mount imports the remote listing as entries whose `extended`
  metadata carries the remote ref; no data is copied.
- reads of an uncached entry stream straight from the remote store.
- remote.cache materializes chunks in the blob cluster (after which
  reads are local); remote.uncache drops them again.
- mappings persist at /etc/remote/mount.json (reference stores them in
  the filer the same way).

Remote refs live in entry.extended["remote"] as JSON
{"spec": backend spec, "key": object key, "size": bytes}.
"""

from __future__ import annotations

import json

from ..filer.filer import join_path, split_path
from ..pb import filer_pb2 as fpb
from ..storage.backend import open_remote
from ..utils.log import logger

log = logger("remote")

MOUNT_CONF = "/etc/remote/mount.json"
REMOTE_KEY = b"remote"  # extended map key (bytes per proto)


def _load_mappings(fs) -> dict:
    """Mappings persist as remote_pb.RemoteStorageMapping proto-JSON
    (reference stores remote.proto messages under /etc/remote the same
    way); legacy plain-JSON files from earlier rounds still load."""
    from google.protobuf import json_format

    from ..pb import remote_pb2 as rpb
    d, n = split_path(MOUNT_CONF)
    entry = fs.filer.find_entry(d, n)
    if entry is None:
        return {}
    try:
        raw = fs.read_entry_bytes(entry)
        doc = json.loads(raw)
        if "mappings" in doc:
            # tolerate unknown fields: a hand edit or newer schema must
            # not make load return {} (a later save would then wipe
            # every other mount mapping)
            msg = json_format.ParseDict(doc, rpb.RemoteStorageMapping(),
                                        ignore_unknown_fields=True)
            return {dir_: {"spec": m.spec, "prefix": m.prefix}
                    for dir_, m in msg.mappings.items()}
        return doc  # legacy flat dict
    except Exception:  # noqa: BLE001
        return {}


def _save_mappings(fs, mappings: dict) -> None:
    from google.protobuf import json_format

    from ..pb import remote_pb2 as rpb
    msg = rpb.RemoteStorageMapping()
    for dir_, m in mappings.items():
        msg.mappings[dir_].spec = m.get("spec", "")
        msg.mappings[dir_].prefix = m.get("prefix", "")
    fs.write_file(MOUNT_CONF,
                  json_format.MessageToJson(msg, indent=2).encode(),
                  mime="application/json")


def mount_remote(fs, directory: str, spec: str, prefix: str = "") -> int:
    """Import the remote listing under `directory`; returns entry count."""
    client = open_remote(spec)
    count = 0
    for key in client.list_keys(prefix):
        rel = key[len(prefix):].lstrip("/") if prefix else key
        if not rel:
            continue
        path = join_path(directory, rel)
        d, n = split_path(path)
        size = client.object_size(key)
        entry = fpb.Entry(name=n)
        entry.attributes.file_size = size
        entry.attributes.file_mode = 0o644
        entry.extended[REMOTE_KEY.decode()] = json.dumps(
            {"spec": spec, "key": key, "size": size}).encode()
        fs.filer.create_entry(d, entry)
        count += 1
    mappings = _load_mappings(fs)
    mappings[directory] = {"spec": spec, "prefix": prefix}
    _save_mappings(fs, mappings)
    log.info("mounted %s (%s, prefix=%r): %d entries",
             directory, spec, prefix, count)
    return count


def unmount_remote(fs, directory: str) -> None:
    d, n = split_path(directory)
    if fs.filer.find_entry(d, n) is not None:
        fs.filer.delete_entry(d, n, is_recursive=True, is_delete_data=True)
    mappings = _load_mappings(fs)
    mappings.pop(directory, None)
    _save_mappings(fs, mappings)


def remote_ref(entry: fpb.Entry) -> dict | None:
    raw = entry.extended.get(REMOTE_KEY.decode())
    if not raw:
        return None
    try:
        return json.loads(raw)
    except Exception:  # noqa: BLE001
        return None


def read_remote(entry: fpb.Entry, offset: int = 0,
                size: int | None = None) -> bytes:
    """Stream an uncached remote entry's bytes (read_remote.go)."""
    ref = remote_ref(entry)
    if ref is None:
        raise ValueError("entry has no remote ref")
    client = open_remote(ref["spec"])
    total = ref.get("size") or client.object_size(ref["key"])
    if size is None:
        size = total - offset
    size = max(0, min(size, total - offset))
    if size == 0:
        return b""
    return client.read_object(ref["key"], offset, size)


def cache_remote(fs, path: str) -> fpb.Entry:
    """Materialize a remote entry's data as local chunks
    (shell remote.cache)."""
    d, n = split_path(path)
    entry = fs.filer.find_entry(d, n)
    if entry is None:
        raise FileNotFoundError(path)
    ref = remote_ref(entry)
    if ref is None:
        raise ValueError(f"{path} is not a remote entry")
    if entry.chunks:
        return entry  # already cached
    data = read_remote(entry)
    cached = fs.write_file(path, data, mime=entry.attributes.mime)
    # keep the remote ref so uncache can revert
    updated = fs.filer.find_entry(d, n)
    updated.extended[REMOTE_KEY.decode()] = json.dumps(ref).encode()
    fs.filer.update_entry(d, updated)
    return cached


def uncache_remote(fs, path: str) -> None:
    """Drop local chunks, keep the remote ref (shell remote.uncache)."""
    d, n = split_path(path)
    entry = fs.filer.find_entry(d, n)
    if entry is None:
        raise FileNotFoundError(path)
    ref = remote_ref(entry)
    if ref is None:
        raise ValueError(f"{path} is not a remote entry")
    if not entry.chunks:
        return
    # update_entry's replaced-chunk GC deletes the dropped chunks server-side
    updated = fpb.Entry()
    updated.CopyFrom(entry)
    del updated.chunks[:]
    updated.attributes.file_size = ref.get("size", 0)
    fs.filer.update_entry(d, updated)


# -- write-back sync (weed filer.remote.sync / filer.remote.gateway) ------

def find_mapping(mappings: dict, path: str) -> "tuple[str, dict] | None":
    """Longest mounted-directory prefix covering `path`."""
    best = None
    for directory, m in mappings.items():
        if path == directory or path.startswith(directory.rstrip("/") + "/"):
            if best is None or len(directory) > len(best[0]):
                best = (directory, m)
    return best


def remote_key_for(mount_dir: str, m: dict, path: str) -> str:
    rel = path[len(mount_dir):].lstrip("/")
    prefix = (m.get("prefix") or "").strip("/")
    return f"{prefix}/{rel}" if prefix else rel


def apply_event_to_remote(fs, mappings: dict, directory: str,
                          ev: fpb.EventNotification) -> "str | None":
    """Write one filer metadata event back to the remote store backing
    its mount (reference command/filer_remote_sync.go). Returns a short
    action string, or None when the event doesn't touch a mount.

    Events whose entry carries ONLY a remote ref (no local chunks) came
    FROM the remote import itself and are skipped — without this guard
    the sync would re-upload every object right after remote.mount."""
    has_old = ev.HasField("old_entry") and bool(ev.old_entry.name)
    has_new = ev.HasField("new_entry") and bool(ev.new_entry.name)
    old_path = join_path(directory, ev.old_entry.name) if has_old else ""
    new_dir = ev.new_parent_path or directory
    new_path = join_path(new_dir, ev.new_entry.name) if has_new else ""

    is_rename = has_old and has_new and new_path != old_path
    actions = []
    if has_new and not ev.new_entry.is_directory:
        hit = find_mapping(mappings, new_path)
        if hit:
            client = open_remote(hit[1]["spec"])
            key = remote_key_for(hit[0], hit[1], new_path)
            if ev.new_entry.chunks:
                # metadata-only updates (chmod/utime) keep the chunk list
                # identical — don't re-upload a large unchanged object
                same_content = (not is_rename and has_old and
                                [c.file_id for c in ev.old_entry.chunks] ==
                                [c.file_id for c in ev.new_entry.chunks])
                if not same_content:
                    client.write_object_bytes(
                        key, fs.read_entry_bytes(ev.new_entry))
                    actions.append(f"upload {key}")
            elif is_rename and remote_ref(ev.new_entry) is not None:
                # rename of a remote-only file: copy remote-side BEFORE
                # the delete below, or the object is lost
                old_hit = find_mapping(mappings, old_path)
                if old_hit:
                    src = open_remote(old_hit[1]["spec"])
                    old_key = remote_key_for(old_hit[0], old_hit[1],
                                             old_path)
                    size = src.object_size(old_key)
                    client.write_object_bytes(
                        key, src.read_object(old_key, 0, size))
                    actions.append(f"copy {old_key} -> {key}")
            elif remote_ref(ev.new_entry) is None and \
                    (not has_old or is_rename or ev.old_entry.chunks
                     or remote_ref(ev.old_entry) is not None):
                # empty local file: fresh create, rename, or
                # truncate-to-empty of content that existed locally
                # (chunks) OR remote-only (ref) — but NOT a metadata-only
                # touch of an already-empty file
                client.write_object_bytes(key, b"")
                actions.append(f"upload {key}")
    if has_old and (not has_new or is_rename):
        hit = find_mapping(mappings, old_path)
        if hit and old_path != hit[0]:
            client = open_remote(hit[1]["spec"])
            key = remote_key_for(hit[0], hit[1], old_path)
            if ev.old_entry.is_directory:
                for k in client.list_keys(key + "/"):
                    client.delete_object(k)
            else:
                client.delete_object(key)
            actions.append(f"delete {key}")
    return "; ".join(actions) if actions else None
