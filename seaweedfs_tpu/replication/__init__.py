"""Async replication plane (reference weed/replication).

Event consumers replay filer mutations into pluggable sinks
(replicator.go:38 Replicate; sink/* implementations), and filer.sync
streams metadata directly between two filers with signature-based loop
prevention (command/filer_sync.go).
"""

from .replicator import Replicator
from .sink import FilerSink, LocalSink, ReplicationSink
from .filer_sync import FilerSync

__all__ = ["Replicator", "ReplicationSink", "LocalSink", "FilerSink",
           "FilerSync"]
