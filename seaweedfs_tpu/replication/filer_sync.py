"""Continuous filer-to-filer sync (reference command/filer_sync.go).

Subscribes to the source filer's metadata stream and replays every
mutation into the target through a FilerSink. Loop prevention follows
the reference: each filer stamps events with its signature; a sync
worker drops events that already carry the *target's* signature (they
originated there — command/filer_sync.go excludeSignatures). Offsets
persist in the target's KV store so restarts resume
(track_sync_offset-style).
"""

from __future__ import annotations

import struct
import threading

from ..utils.log import logger
from .replicator import Replicator
from .sink import FilerSink

log = logger("filer.sync")


class FilerSync:
    def __init__(self, source_fs, target_fs, path_prefix: str = "/",
                 from_ns: int | None = None, max_retries: int = 5,
                 retry_base_delay: float = 0.2):
        self.source = source_fs
        self.target = target_fs
        self.prefix = path_prefix
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        sink = FilerSink(target_fs)
        self.replicator = Replicator(sink, self._read_source_data,
                                     path_prefix)
        self._offset_key = (
            f"sync.offset.{self.source.filer.signature}".encode())
        self.from_ns = (self._load_offset() if from_ns is None else from_ns)
        self.applied = 0
        self.skipped = 0
        self.dead_lettered = 0
        self.max_retries = max_retries
        self.retry_base_delay = retry_base_delay

    # -- offsets (reference persists per-peer offsets in store KV) ----------
    def _load_offset(self) -> int:
        try:
            raw = self.target.filer.store.kv_get(self._offset_key)
            if raw:
                return struct.unpack("<q", raw)[0]
        except Exception as e:  # noqa: BLE001
            # a silent fallback here replays the WHOLE journal from 0 —
            # that is correct (sync is idempotent) but never invisible
            log.warning("sync offset read failed (%s); replaying from 0", e)
        return 0

    def _save_offset(self, ts_ns: int) -> None:
        try:
            self.target.filer.store.kv_put(self._offset_key,
                                           struct.pack("<q", ts_ns))
        except Exception as e:  # noqa: BLE001
            log.warning("offset save: %s", e)

    def _read_source_data(self, entry) -> bytes:
        return self.source.read_entry_bytes(entry)

    # -- run -----------------------------------------------------------------
    def start(self) -> "FilerSync":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="filer-sync")
        self._thread.start()
        return self

    def _run(self) -> None:
        target_sig = self.target.filer.signature
        for resp in self.source.filer.meta_log.subscribe(self.from_ns,
                                                         self._stop):
            ev = resp.event_notification
            if target_sig in ev.signatures:
                self.skipped += 1  # originated at the target: loop guard
                if resp.ts_ns:
                    self._save_offset(resp.ts_ns)
                continue
            # Retry with backoff and only advance the offset once the event
            # applied (the reference filer.sync re-processes the event and
            # persists the offset after success) — saving early would skip
            # the mutation forever after a restart.
            applied = False
            for attempt in range(self.max_retries):
                try:
                    self.replicator.replicate(resp.directory, ev)
                    applied = True
                    break
                except Exception as e:  # noqa: BLE001
                    log.warning("sync apply %s (try %d/%d): %s",
                                resp.directory, attempt + 1,
                                self.max_retries, e)
                    if attempt + 1 >= self.max_retries:
                        break  # no point sleeping before the dead-letter
                    if self._stop.wait(self.retry_base_delay * 2 ** attempt):
                        return
            if applied:
                self.applied += 1
            else:
                # dead-letter explicitly: log loudly and move on so one
                # poisoned event can't wedge the stream forever
                self.dead_lettered += 1
                log.error("sync DEAD-LETTER %s after %d tries",
                          resp.directory, self.max_retries)
            if resp.ts_ns:
                self._save_offset(resp.ts_ns)

    def stop(self) -> None:
        self._stop.set()
