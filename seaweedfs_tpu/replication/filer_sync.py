"""Continuous filer-to-filer sync (reference command/filer_sync.go).

Subscribes to the source filer's metadata stream and replays every
mutation into the target through a FilerSink. Loop prevention follows
the reference: each filer stamps events with its signature; a sync
worker drops events that already carry the *target's* signature (they
originated there — command/filer_sync.go excludeSignatures). Offsets
persist in the target's KV store so restarts resume
(track_sync_offset-style).
"""

from __future__ import annotations

import struct
import threading

from ..utils.log import logger
from .replicator import Replicator
from .sink import FilerSink

log = logger("filer.sync")


class FilerSync:
    def __init__(self, source_fs, target_fs, path_prefix: str = "/",
                 from_ns: int | None = None):
        self.source = source_fs
        self.target = target_fs
        self.prefix = path_prefix
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        sink = FilerSink(target_fs)
        self.replicator = Replicator(sink, self._read_source_data,
                                     path_prefix)
        self._offset_key = (
            f"sync.offset.{self.source.filer.signature}".encode())
        self.from_ns = (self._load_offset() if from_ns is None else from_ns)
        self.applied = 0
        self.skipped = 0

    # -- offsets (reference persists per-peer offsets in store KV) ----------
    def _load_offset(self) -> int:
        try:
            raw = self.target.filer.store.kv_get(self._offset_key)
            if raw:
                return struct.unpack("<q", raw)[0]
        except Exception:  # noqa: BLE001
            pass
        return 0

    def _save_offset(self, ts_ns: int) -> None:
        try:
            self.target.filer.store.kv_put(self._offset_key,
                                           struct.pack("<q", ts_ns))
        except Exception as e:  # noqa: BLE001
            log.warning("offset save: %s", e)

    def _read_source_data(self, entry) -> bytes:
        return self.source.read_entry_bytes(entry)

    # -- run -----------------------------------------------------------------
    def start(self) -> "FilerSync":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="filer-sync")
        self._thread.start()
        return self

    def _run(self) -> None:
        target_sig = self.target.filer.signature
        for resp in self.source.filer.meta_log.subscribe(self.from_ns,
                                                         self._stop):
            ev = resp.event_notification
            if target_sig in ev.signatures:
                self.skipped += 1  # originated at the target: loop guard
                continue
            try:
                self.replicator.replicate(resp.directory, ev)
                self.applied += 1
            except Exception as e:  # noqa: BLE001
                log.warning("sync apply %s: %s", resp.directory, e)
            if resp.ts_ns:
                self._save_offset(resp.ts_ns)

    def stop(self) -> None:
        self._stop.set()
