"""Event -> sink dispatch (reference replication/replicator.go:38).

An EventNotification decomposes into create / delete / rename / update;
the replicator routes each to the sink with the source's data reader.
"""

from __future__ import annotations

from ..filer.filer import join_path
from ..pb import filer_pb2 as fpb
from ..utils.log import logger
from .sink import DataReader, ReplicationSink

log = logger("replication")


class Replicator:
    def __init__(self, sink: ReplicationSink, read_data: DataReader,
                 path_prefix: str = "/"):
        self.sink = sink
        self.read_data = read_data
        self.prefix = path_prefix

    @staticmethod
    def _full_path(key: str, name: str) -> str:
        """`key` may be the parent directory (meta-log records) or the
        entry's full path (notification-queue keys, reference
        replicator.go) — normalize to the full path."""
        if key == "/" + name or key.endswith("/" + name):
            return key
        return join_path(key, name)

    def replicate(self, directory: str, ev: fpb.EventNotification) -> None:
        """Mirror replicator.go Replicate: old==nil -> create,
        new==nil -> delete, both with moved path -> rename,
        both same path -> update."""
        has_old = ev.HasField("old_entry") and bool(ev.old_entry.name)
        has_new = ev.HasField("new_entry") and bool(ev.new_entry.name)
        old_path = (self._full_path(directory, ev.old_entry.name)
                    if has_old else "")
        new_path = ""
        if has_new:
            if ev.new_parent_path:
                new_path = join_path(ev.new_parent_path, ev.new_entry.name)
            else:
                new_path = self._full_path(directory, ev.new_entry.name)
        in_scope = ((old_path and old_path.startswith(self.prefix))
                    or (new_path and new_path.startswith(self.prefix)))
        if not in_scope:
            return
        sigs = list(ev.signatures)
        if not has_old and has_new:
            try:
                self.sink.create_entry(new_path, ev.new_entry,
                                       self.read_data, sigs)
            except KeyError as e:
                # source data already gone (deleted after the event was
                # queued) — a later delete event will reconcile the sink
                log.warning("skip create %s: source data missing (%s)",
                            new_path, e)
        elif has_old and not has_new:
            self.sink.delete_entry(old_path, ev.old_entry.is_directory)
        elif has_old and has_new and old_path != new_path:
            self.sink.delete_entry(old_path, ev.old_entry.is_directory)
            self.sink.create_entry(new_path, ev.new_entry, self.read_data,
                                   sigs)
        elif has_old and has_new:
            self.sink.update_entry(new_path, ev.new_entry, self.read_data,
                                   sigs)
