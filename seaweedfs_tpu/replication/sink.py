"""Replication sinks (reference weed/replication/sink).

`ReplicationSink` mirrors sink/replication_sink.go: CreateEntry /
UpdateEntry / DeleteEntry against a destination, with the source's data
readable through a callback (the replicator resolves chunk bytes from
the source cluster — data moves with the metadata).

Built-ins: LocalSink (localsink — a plain directory tree, handy for
backup), FilerSink (filersink — another cluster's filer). The
reference's s3/gcs/azure/b2 sinks need their cloud SDKs; an S3 sink
against any sigv4 endpoint (including our own gateway) is provided since
it needs only HTTP.
"""

from __future__ import annotations

import os
from typing import Callable

from ..pb import filer_pb2 as fpb
from ..utils import failpoints
from ..utils.log import logger

log = logger("replication.sink")

DataReader = Callable[[fpb.Entry], bytes]


class ReplicationSink:
    name = "abstract"

    def create_entry(self, path: str, entry: fpb.Entry,
                     read_data: DataReader,
                     signatures: list[int] | None = None) -> None:
        raise NotImplementedError

    def update_entry(self, path: str, entry: fpb.Entry,
                     read_data: DataReader,
                     signatures: list[int] | None = None) -> None:
        self.delete_entry(path, entry.is_directory)
        self.create_entry(path, entry, read_data, signatures)

    def delete_entry(self, path: str, is_directory: bool) -> None:
        raise NotImplementedError


class LocalSink(ReplicationSink):
    """Mirror into a local directory (reference sink/localsink)."""

    name = "local"

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)

    def _local(self, path: str) -> str:
        return os.path.join(self.dir, path.lstrip("/"))

    def create_entry(self, path: str, entry: fpb.Entry,
                     read_data: DataReader,
                     signatures: list[int] | None = None) -> None:
        target = self._local(path)
        if entry.is_directory:
            os.makedirs(target, exist_ok=True)
            return
        os.makedirs(os.path.dirname(target), exist_ok=True)
        with open(target, "wb") as f:
            f.write(read_data(entry))

    def delete_entry(self, path: str, is_directory: bool) -> None:
        target = self._local(path)
        try:
            if is_directory:
                import shutil
                shutil.rmtree(target, ignore_errors=True)
            else:
                os.unlink(target)
        except FileNotFoundError:
            pass


class FilerSink(ReplicationSink):
    """Write into another cluster's filer (reference sink/filersink).
    Data is re-uploaded into the destination's blob cluster — chunk
    fids are cluster-local and can't be shared."""

    name = "filer"

    def __init__(self, target_filer_server, dir_prefix: str = ""):
        self.fs = target_filer_server
        self.prefix = dir_prefix.rstrip("/")

    def _path(self, path: str) -> str:
        return self.prefix + path if self.prefix else path

    def create_entry(self, path: str, entry: fpb.Entry,
                     read_data: DataReader,
                     signatures: list[int] | None = None) -> None:
        from ..filer.filer import split_path
        # failpoint: destination-cluster hiccup — the replicator's
        # per-event retry/dead-letter path is driven from here
        failpoints.check("replication.sink.create")
        target = self._path(path)
        if entry.is_directory:
            d, n = split_path(target)
            if self.fs.filer.find_entry(d, n) is None:
                e = fpb.Entry(name=n, is_directory=True)
                e.attributes.CopyFrom(entry.attributes)
                self.fs.filer.create_entry(d, e, signatures=signatures)
            return
        data = read_data(entry)
        # signatures ride the destination's event so a reverse sync
        # recognizes its own writes (filer_sync.go excludeSignatures)
        self.fs.write_file(target, data, mime=entry.attributes.mime,
                           signatures=signatures)

    def update_entry(self, path: str, entry: fpb.Entry,
                     read_data: DataReader,
                     signatures: list[int] | None = None) -> None:
        failpoints.check("replication.sink.update")
        # write_file overwrites in place; no need to delete first
        if entry.is_directory:
            return
        self.fs.write_file(self._path(path), read_data(entry),
                           mime=entry.attributes.mime,
                           signatures=signatures)

    def delete_entry(self, path: str, is_directory: bool) -> None:
        from ..filer.filer import split_path
        failpoints.check("replication.sink.delete")
        d, n = split_path(self._path(path))
        try:
            self.fs.filer.delete_entry(d, n, is_recursive=is_directory,
                                       is_delete_data=True)
        except FileNotFoundError:
            pass


class S3Sink(ReplicationSink):
    """Replicate into any sigv4 S3 endpoint (reference sink/s3sink) —
    including our own gateway; needs only HTTP."""

    name = "s3"

    def __init__(self, endpoint: str, bucket: str, access_key: str,
                 secret_key: str, dir_prefix: str = ""):
        self.endpoint = endpoint.rstrip("/")
        self.bucket = bucket
        self.ak, self.sk = access_key, secret_key
        self.prefix = dir_prefix.strip("/")

    def _key(self, path: str) -> str:
        key = path.lstrip("/")
        return f"{self.prefix}/{key}" if self.prefix else key

    def _request(self, method: str, key: str, data: bytes = b""):
        import requests

        from ..s3.auth import sign_request_v4
        url = f"{self.endpoint}/{self.bucket}/{key}"
        headers = sign_request_v4(method, url, {}, data, self.ak, self.sk)
        return requests.request(method, url, data=data, headers=headers,
                                timeout=60)

    def create_entry(self, path: str, entry: fpb.Entry,
                     read_data: DataReader,
                     signatures: list[int] | None = None) -> None:
        if entry.is_directory:
            return
        r = self._request("PUT", self._key(path), read_data(entry))
        if r.status_code >= 300:
            raise OSError(f"s3 sink PUT {path}: HTTP {r.status_code}")

    def update_entry(self, path: str, entry: fpb.Entry,
                     read_data: DataReader,
                     signatures: list[int] | None = None) -> None:
        self.create_entry(path, entry, read_data, signatures)

    def delete_entry(self, path: str, is_directory: bool) -> None:
        if is_directory:
            return
        self._request("DELETE", self._key(path))
