"""S3 gateway: AWS-compatible object API over the filer.

Reference layer L6 (weed/s3api, 14,018 LoC — SURVEY.md §2.6): sigv4 auth
(header + presigned), bucket/object CRUD, ListObjects V1/V2 with delimiter,
multi-delete, zero-copy multipart completion, object tagging."""

from .auth import (ACTION_ADMIN, ACTION_LIST, ACTION_READ, ACTION_TAGGING,
                   ACTION_WRITE, Identity, IdentityAccessManagement, S3Error,
                   sign_request_v4)
from .s3_server import S3Gateway

__all__ = [
    "ACTION_ADMIN", "ACTION_LIST", "ACTION_READ", "ACTION_TAGGING",
    "ACTION_WRITE", "Identity", "IdentityAccessManagement", "S3Error",
    "S3Gateway", "sign_request_v4",
]
