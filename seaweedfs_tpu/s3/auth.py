"""S3 authentication: AWS Signature V4 (header + presigned query) and
identity/action management.

Reference: weed/s3api/auth_credentials.go (identities + Action model),
auth_signature_v4.go (sigv4 verification), s3api/s3_constants. Identities
come from a dict/JSON config shaped like the reference's s3.json:
{"identities": [{"name": ..., "credentials": [{"accessKey","secretKey"}],
"actions": ["Read","Write","List","Tagging","Admin", ...]}]}.
Actions may be suffixed ":bucket" to scope them.
"""

from __future__ import annotations

import hashlib
import hmac
import urllib.parse
from dataclasses import dataclass, field

ACTION_READ = "Read"
ACTION_WRITE = "Write"
ACTION_LIST = "List"
ACTION_TAGGING = "Tagging"
ACTION_ADMIN = "Admin"


class S3Error(Exception):
    def __init__(self, code: str, message: str, status: int):
        super().__init__(message)
        self.code, self.message, self.status = code, message, status


ErrAccessDenied = lambda: S3Error("AccessDenied", "Access Denied.", 403)  # noqa: E731
ErrSignatureMismatch = lambda: S3Error(  # noqa: E731
    "SignatureDoesNotMatch",
    "The request signature we calculated does not match the signature you provided.",
    403)
ErrInvalidAccessKey = lambda: S3Error(  # noqa: E731
    "InvalidAccessKeyId",
    "The AWS Access Key Id you provided does not exist in our records.", 403)
ErrRequestExpired = lambda: S3Error(  # noqa: E731
    "AccessDenied", "Request has expired", 403)

MAX_CLOCK_SKEW_S = 15 * 60  # AWS allows +-15 min on x-amz-date


def _amz_time(s: str) -> float:
    import calendar
    import time as _time

    return calendar.timegm(_time.strptime(s, "%Y%m%dT%H%M%SZ"))


@dataclass
class Identity:
    name: str
    credentials: dict[str, str] = field(default_factory=dict)  # access -> secret
    actions: list[str] = field(default_factory=list)

    def allows(self, action: str, bucket: str) -> bool:
        for a in self.actions:
            if a == ACTION_ADMIN:
                return True
            act, _, scope = a.partition(":")
            if act == action and (not scope or scope == bucket):
                return True
        return False


class IdentityAccessManagement:
    """Access-key → identity lookup + sigv4 verification."""

    def __init__(self, config: dict | None = None):
        self._by_access_key: dict[str, tuple[Identity, str]] = {}
        self.enabled = False
        if config:
            self.load(config)

    def load(self, config: dict) -> None:
        # build then swap atomically — the gateway authenticates on other
        # threads while the IAM API hot-reloads (GIL makes the rebind safe)
        table: dict[str, tuple[Identity, str]] = {}
        for ident_cfg in config.get("identities", []):
            ident = Identity(name=ident_cfg["name"],
                             actions=list(ident_cfg.get("actions", [])))
            for cred in ident_cfg.get("credentials", []):
                ident.credentials[cred["accessKey"]] = cred["secretKey"]
                table[cred["accessKey"]] = (ident, cred["secretKey"])
        self._by_access_key = table
        self.enabled = bool(table)

    def lookup(self, access_key: str) -> tuple[Identity, str]:
        hit = self._by_access_key.get(access_key)
        if hit is None:
            raise ErrInvalidAccessKey()
        return hit

    # -- sigv4 --------------------------------------------------------------
    def authenticate(self, method: str, path: str, query: dict[str, str],
                     headers: dict[str, str], payload_hash: str) -> Identity:
        """Verify a sigv4-signed request; returns the matching identity.
        Raises S3Error on failure. headers keys must be lower-case."""
        auth = headers.get("authorization", "")
        if auth.startswith("AWS4-HMAC-SHA256 "):
            return self._auth_header(method, path, query, headers,
                                     payload_hash, auth)
        if query.get("X-Amz-Algorithm") == "AWS4-HMAC-SHA256":
            return self._auth_presigned(method, path, query, headers)
        raise ErrAccessDenied()

    def _auth_header(self, method, path, query, headers, payload_hash, auth):
        return self._auth_header_ctx(method, path, query, headers,
                                     payload_hash, auth, want_ctx=False)[0]

    def _auth_header_ctx(self, method, path, query, headers, payload_hash,
                         auth, want_ctx=True):
        """Verify; with want_ctx also return the signing context the
        streaming-chunked verifier chains off (reference
        calculateSeedSignature, chunked_reader_v4.go)."""
        fields = {}
        for part in auth[len("AWS4-HMAC-SHA256 "):].split(","):
            k, _, v = part.strip().partition("=")
            fields[k] = v
        cred = fields.get("Credential", "").split("/")
        if len(cred) != 5:
            raise ErrSignatureMismatch()
        access_key, date, region, service, _ = cred
        ident, secret = self.lookup(access_key)
        self._check_freshness(headers.get("x-amz-date", ""))
        signed_headers = fields.get("SignedHeaders", "").split(";")
        canonical = self._canonical_request(
            method, path, query, headers, signed_headers, payload_hash)
        amz_date = headers.get("x-amz-date", "")
        key = self._signing_key(secret, date, region, service)
        sig = self._signature_with_key(key, date, region, service, amz_date,
                                       canonical)
        if not hmac.compare_digest(sig, fields.get("Signature", "")):
            raise ErrSignatureMismatch()
        if not want_ctx:
            return ident, None
        from .chunked import SeedContext
        ctx = SeedContext(
            signing_key=key, amz_date=amz_date,
            scope=f"{date}/{region}/{service}/aws4_request",
            seed_signature=sig)
        return ident, ctx

    def authenticate_streaming(self, method, path, query, headers):
        """Header-auth a STREAMING-AWS4-HMAC-SHA256-PAYLOAD request and hand
        back the seed context for per-chunk verification."""
        auth = headers.get("authorization", "")
        if not auth.startswith("AWS4-HMAC-SHA256 "):
            raise ErrAccessDenied()
        return self._auth_header_ctx(
            method, path, query, headers,
            "STREAMING-AWS4-HMAC-SHA256-PAYLOAD", auth)

    def _auth_presigned(self, method, path, query, headers):
        cred = query.get("X-Amz-Credential", "").split("/")
        if len(cred) != 5:
            raise ErrSignatureMismatch()
        access_key, date, region, service, _ = cred
        ident, secret = self.lookup(access_key)
        self._check_presigned_expiry(query.get("X-Amz-Date", ""),
                                     query.get("X-Amz-Expires", ""))
        signed_headers = query.get("X-Amz-SignedHeaders", "host").split(";")
        q = {k: v for k, v in query.items() if k != "X-Amz-Signature"}
        canonical = self._canonical_request(
            method, path, q, headers, signed_headers, "UNSIGNED-PAYLOAD")
        sig = self._signature(secret, date, region, service,
                              query.get("X-Amz-Date", ""), canonical)
        if not hmac.compare_digest(sig, query.get("X-Amz-Signature", "")):
            raise ErrSignatureMismatch()
        return ident

    @staticmethod
    def _check_freshness(amz_date: str) -> None:
        import time as _time

        try:
            ts = _amz_time(amz_date)
        except ValueError:
            raise ErrSignatureMismatch() from None
        if abs(_time.time() - ts) > MAX_CLOCK_SKEW_S:  # swtpu-lint: disable=wallclock-duration (vs client clock)
            raise S3Error("RequestTimeTooSkewed",
                          "The difference between the request time and the "
                          "server's time is too large.", 403)

    @staticmethod
    def _check_presigned_expiry(amz_date: str, expires: str) -> None:
        import time as _time

        try:
            ts = _amz_time(amz_date)
            ttl = int(expires) if expires else 604800
        except ValueError:
            raise ErrSignatureMismatch() from None
        if _time.time() > ts + min(ttl, 604800):  # 7-day cap like AWS  # swtpu-lint: disable=wallclock-duration (vs client clock)
            raise ErrRequestExpired()

    @staticmethod
    def _canonical_request(method, path, query, headers, signed_headers,
                           payload_hash) -> str:
        enc_path = urllib.parse.quote(path, safe="/~")
        q = "&".join(
            f"{urllib.parse.quote(k, safe='~')}={urllib.parse.quote(v, safe='~')}"
            for k, v in sorted(query.items()))
        hdrs = "".join(f"{h}:{' '.join(headers.get(h, '').split())}\n"
                       for h in signed_headers)
        return "\n".join([method, enc_path, q, hdrs, ";".join(signed_headers),
                          payload_hash])

    @staticmethod
    def _signing_key(secret, date, region, service) -> bytes:
        def h(key, msg):
            return hmac.new(key, msg.encode(), hashlib.sha256).digest()

        k = h(f"AWS4{secret}".encode(), date)
        k = h(k, region)
        k = h(k, service)
        return h(k, "aws4_request")

    @staticmethod
    def _signature_with_key(key, date, region, service, amz_date,
                            canonical) -> str:
        sts = "\n".join(["AWS4-HMAC-SHA256", amz_date,
                         f"{date}/{region}/{service}/aws4_request",
                         hashlib.sha256(canonical.encode()).hexdigest()])
        return hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()

    @classmethod
    def _signature(cls, secret, date, region, service, amz_date,
                   canonical) -> str:
        return cls._signature_with_key(
            cls._signing_key(secret, date, region, service),
            date, region, service, amz_date, canonical)


def _client_sign(method: str, url: str, headers: dict[str, str],
                 payload_hash: str, access_key: str, secret_key: str,
                 region: str, service: str, amz_date: "str | None",
                 ) -> tuple[dict[str, str], str, str, str]:
    """Shared client-side signing core. headers must already include any
    x-amz-* extras to sign. Returns (headers+Authorization, sig, now, date)."""
    import datetime

    u = urllib.parse.urlsplit(url)
    now = amz_date or datetime.datetime.now(datetime.timezone.utc
                                            ).strftime("%Y%m%dT%H%M%SZ")
    date = now[:8]
    out = dict(headers)
    out.setdefault("host", u.netloc)
    out["x-amz-date"] = now
    out["x-amz-content-sha256"] = payload_hash
    signed = sorted(h.lower() for h in out)
    query = dict(urllib.parse.parse_qsl(u.query, keep_blank_values=True))
    iam = IdentityAccessManagement()
    canonical = iam._canonical_request(method, u.path or "/", query,
                                       {k.lower(): v for k, v in out.items()},
                                       signed, payload_hash)
    sig = iam._signature(secret_key, date, region, service, now, canonical)
    out["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={access_key}/{date}/{region}/{service}/"
        f"aws4_request, SignedHeaders={';'.join(signed)}, Signature={sig}")
    return out, sig, now, date


def sign_request_v4(method: str, url: str, headers: dict[str, str],
                    payload: bytes, access_key: str, secret_key: str,
                    region: str = "us-east-1", service: str = "s3",
                    amz_date: str | None = None) -> dict[str, str]:
    """Client-side signer (used by tests and the replication s3 sink).
    Returns headers with Authorization added."""
    return _client_sign(method, url, headers,
                        hashlib.sha256(payload).hexdigest(), access_key,
                        secret_key, region, service, amz_date)[0]


def sign_streaming_request_v4(method: str, url: str, headers: dict[str, str],
                              decoded_length: int, access_key: str,
                              secret_key: str, region: str = "us-east-1",
                              service: str = "s3",
                              amz_date: str | None = None):
    """Client-side signer for STREAMING-AWS4-HMAC-SHA256-PAYLOAD uploads.

    Returns (headers_with_authorization, SeedContext); frame the body with
    chunked.encode_chunked_payload(data, ctx) afterwards. Mirrors what the
    AWS SDKs do for large PUTs (reference chunked_reader_v4.go's client side).
    """
    from .chunked import STREAMING_PAYLOAD, SeedContext

    pre = dict(headers)
    pre["x-amz-decoded-content-length"] = str(decoded_length)
    pre["content-encoding"] = "aws-chunked"
    out, sig, now, date = _client_sign(method, url, pre, STREAMING_PAYLOAD,
                                       access_key, secret_key, region,
                                       service, amz_date)
    ctx = SeedContext(
        signing_key=IdentityAccessManagement._signing_key(
            secret_key, date, region, service),
        amz_date=now, scope=f"{date}/{region}/{service}/aws4_request",
        seed_signature=sig)
    return out, ctx


# ---------------------------------------------------------------------------
# Signature V2 (reference auth_signature_v2.go) — legacy SDK compatibility.
# ---------------------------------------------------------------------------

_SUBRESOURCES = ("acl", "delete", "lifecycle", "location", "logging",
                 "notification", "partNumber", "policy", "requestPayment",
                 "tagging", "torrent", "uploadId", "uploads", "versionId",
                 "versioning", "versions", "website")


def _canonical_resource_v2(path: str, query: dict) -> str:
    sub = "&".join(f"{k}={query[k]}" if query[k] else k
                   for k in sorted(query) if k in _SUBRESOURCES)
    return path + (f"?{sub}" if sub else "")


def _canonical_amz_headers_v2(headers: dict) -> str:
    amz = sorted((k, v) for k, v in headers.items()
                 if k.startswith("x-amz-"))
    return "".join(f"{k}:{v}\n" for k, v in amz)


def _string_to_sign_v2(method: str, path: str, query: dict,
                       headers: dict, date_or_expires: str) -> str:
    return "\n".join([
        method,
        headers.get("content-md5", ""),
        headers.get("content-type", ""),
        date_or_expires,
    ]) + "\n" + _canonical_amz_headers_v2(headers) \
        + _canonical_resource_v2(path, query)


def sign_v2(secret: str, to_sign: str) -> str:
    import base64
    return base64.b64encode(
        hmac.new(secret.encode(), to_sign.encode(),
                 hashlib.sha1).digest()).decode()


def verify_v2_header(iam: "IdentityAccessManagement", method: str, path: str,
                     query: dict, headers: dict) -> "Identity":
    """`Authorization: AWS AKID:sig` (doesSignatureMatchV2)."""
    auth = headers.get("authorization", "")
    cred = auth[len("AWS "):]
    access_key, _, sig = cred.partition(":")
    ident, secret = iam.lookup(access_key)
    # with x-amz-date present the Date slot is empty (the amz date rides
    # the canonical amz headers instead)
    date = "" if headers.get("x-amz-date") else headers.get("date", "")
    want = sign_v2(secret, _string_to_sign_v2(method, path, query, headers,
                                              date))
    if not hmac.compare_digest(want, sig):
        raise ErrSignatureMismatch()
    return ident


def verify_v2_presigned(iam: "IdentityAccessManagement", method: str,
                        path: str, query: dict, headers: dict) -> "Identity":
    """?AWSAccessKeyId=&Expires=&Signature= (doesPresignedSignatureMatchV2)."""
    import time as _time
    ident, secret = iam.lookup(query.get("AWSAccessKeyId", ""))
    expires = query.get("Expires", "0")
    try:
        if _time.time() > int(expires):  # swtpu-lint: disable=wallclock-duration (vs client clock)
            raise ErrRequestExpired()
    except ValueError:
        raise ErrSignatureMismatch() from None
    q = {k: v for k, v in query.items()
         if k not in ("AWSAccessKeyId", "Expires", "Signature")}
    want = sign_v2(secret, _string_to_sign_v2(method, path, q, headers,
                                              expires))
    if not hmac.compare_digest(want, query.get("Signature", "")):
        raise ErrSignatureMismatch()
    return ident


def verify_post_policy(iam: "IdentityAccessManagement",
                       form: dict) -> "Identity":
    """Browser form upload (reference policy_check + post-policy): the v4
    signature covers the base64 policy document; expiration and bucket/key
    conditions are enforced."""
    import base64
    import datetime
    import json as _json

    policy_b64 = form.get("policy", "")
    cred = form.get("x-amz-credential", "").split("/")
    if len(cred) != 5:
        raise ErrSignatureMismatch()
    access_key, date, region, service, _ = cred
    ident, secret = iam.lookup(access_key)
    key = IdentityAccessManagement._signing_key(secret, date, region, service)
    want = hmac.new(key, policy_b64.encode(), hashlib.sha256).hexdigest()
    if not hmac.compare_digest(want, form.get("x-amz-signature", "")):
        raise ErrSignatureMismatch()
    try:
        policy = _json.loads(base64.b64decode(policy_b64))
        exp = policy.get("expiration", "")
        exp_ts = datetime.datetime.fromisoformat(
            exp.replace("Z", "+00:00")).timestamp()
    except Exception:  # noqa: BLE001
        raise S3Error("InvalidPolicyDocument", "malformed policy", 400) \
            from None
    import time as _time
    if _time.time() > exp_ts:  # swtpu-lint: disable=wallclock-duration (vs client clock)
        raise ErrRequestExpired()
    # enforce the conditions we understand (bucket equality, key prefix)
    for cond in policy.get("conditions", []):
        if isinstance(cond, dict):
            for k, v in cond.items():
                if k in ("bucket", "key") and form.get(k) != v:
                    raise S3Error("AccessDenied",
                                  f"policy condition failed on {k}", 403)
        elif isinstance(cond, list) and len(cond) == 3:
            op, field, want = cond[0], str(cond[1]).lstrip("$"), cond[2]
            have = str(form.get(field, ""))
            if op == "starts-with":
                ok = have.startswith(want)
            elif op == "eq":
                ok = have == str(want)
            else:
                # refuse rather than silently skip: an unknown operator is
                # a restriction we cannot honor
                raise S3Error("InvalidPolicyDocument",
                              f"unsupported condition operator {op!r}", 400)
            if not ok:
                raise S3Error("AccessDenied",
                              f"policy condition failed on {field}", 403)
    return ident
