"""Streaming-chunked sigv4 payload decoding + per-chunk verification.

Reference: weed/s3api/chunked_reader_v4.go — AWS SDK clients send large PUTs
with `x-amz-content-sha256: STREAMING-AWS4-HMAC-SHA256-PAYLOAD` and an
aws-chunked body:

    <hex-size>;chunk-signature=<sig64>\r\n<bytes>\r\n ... 0;chunk-signature=<sig>\r\n\r\n

Every chunk's signature chains off the previous one (the request's seed
signature first):

    sig_i = HMAC(signing_key, "AWS4-HMAC-SHA256-PAYLOAD" \n amz_date \n scope
                 \n sig_{i-1} \n sha256("") \n sha256(chunk_bytes))

Also supports the unsigned trailer variant's plain framing
(STREAMING-UNSIGNED-PAYLOAD-TRAILER) by skipping signature checks.
"""

from __future__ import annotations

import hashlib
import hmac
from dataclasses import dataclass

from .auth import S3Error, ErrSignatureMismatch

EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()
STREAMING_PAYLOAD = "STREAMING-AWS4-HMAC-SHA256-PAYLOAD"
STREAMING_UNSIGNED = "STREAMING-UNSIGNED-PAYLOAD-TRAILER"


@dataclass
class SeedContext:
    """Signing context carried from the header auth to the chunk verifier."""
    signing_key: bytes   # derived AWS4 key (date/region/service/aws4_request)
    amz_date: str
    scope: str           # "{date}/{region}/{service}/aws4_request"
    seed_signature: str


def _chunk_string_to_sign(ctx: SeedContext, prev_sig: str,
                          chunk: bytes) -> str:
    return "\n".join([
        "AWS4-HMAC-SHA256-PAYLOAD", ctx.amz_date, ctx.scope, prev_sig,
        EMPTY_SHA256, hashlib.sha256(chunk).hexdigest()])


def sign_chunk(ctx: SeedContext, prev_sig: str, chunk: bytes) -> str:
    return hmac.new(ctx.signing_key,
                    _chunk_string_to_sign(ctx, prev_sig, chunk).encode(),
                    hashlib.sha256).hexdigest()


def decode_chunked_payload(body: bytes, ctx: "SeedContext | None") -> bytes:
    """Strip aws-chunked framing; verify the signature chain when ctx given.

    Raises S3Error on malformed framing or a broken chain (the reference
    returns ErrSignatureDoesNotMatch mid-stream the same way).
    """
    out = bytearray()
    pos = 0
    prev_sig = ctx.seed_signature if ctx else ""
    while True:
        nl = body.find(b"\r\n", pos)
        if nl < 0:
            raise S3Error("IncompleteBody",
                          "chunked encoding truncated", 400)
        header = body[pos:nl].decode("latin-1")
        size_hex, _, ext = header.partition(";")
        try:
            size = int(size_hex, 16)
        except ValueError:
            raise S3Error("IncompleteBody",
                          f"bad chunk size {size_hex!r}", 400) from None
        if size < 0:
            raise S3Error("IncompleteBody",
                          f"negative chunk size {size_hex!r}", 400)
        sig = ""
        for part in ext.split(";"):
            k, _, v = part.strip().partition("=")
            if k == "chunk-signature":
                sig = v
        data_start = nl + 2
        data_end = data_start + size
        if data_end > len(body):
            raise S3Error("IncompleteBody", "chunk data truncated", 400)
        chunk = bytes(body[data_start:data_end])
        if ctx is not None:
            want = sign_chunk(ctx, prev_sig, chunk)
            if not sig or not hmac.compare_digest(want, sig):
                raise ErrSignatureMismatch()
            prev_sig = want
        out += chunk
        # final chunk (size 0) ends the stream; trailers (if any) follow
        if size == 0:
            break
        pos = data_end
        if body[pos:pos + 2] == b"\r\n":
            pos += 2
    return bytes(out)


def encode_chunked_payload(data: bytes, ctx: SeedContext,
                           chunk_size: int = 64 * 1024) -> bytes:
    """Client-side encoder (tests + sdk-less clients): frame and sign."""
    out = bytearray()
    prev = ctx.seed_signature
    offsets = list(range(0, len(data), chunk_size)) or [0]
    for off in offsets:
        chunk = data[off:off + chunk_size]
        if not chunk:
            break
        sig = sign_chunk(ctx, prev, chunk)
        out += f"{len(chunk):x};chunk-signature={sig}\r\n".encode()
        out += chunk + b"\r\n"
        prev = sig
    final = sign_chunk(ctx, prev, b"")
    out += f"0;chunk-signature={final}\r\n\r\n".encode()
    return bytes(out)
