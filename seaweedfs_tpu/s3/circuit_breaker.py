"""Per-action concurrent-request circuit breaker for the S3 gateway.

Reference: weed/s3api/s3api_circuit_breaker.go — global and per-bucket
limits on in-flight requests per action; exceeding a limit returns 503
SlowDown so SDK clients back off and retry, protecting the filer behind the
gateway. (The reference also supports byte-size limits; count limits cover
the protective behavior.)

Config shape (mirrors the spirit of s3_constants circuit-breaker config):

    {"global": {"Read": 64, "Write": 32, "List": 16, "Admin": 8},
     "buckets": {"mybucket": {"Write": 4}}}

Absent actions are unlimited; an empty/None config disables the breaker.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager

from .auth import S3Error


class ErrTooManyRequests(S3Error):
    def __init__(self):
        super().__init__("SlowDown",
                         "Please reduce your request rate.", 503)


class CircuitBreaker:
    def __init__(self, config: "dict | None" = None):
        self._lock = threading.Lock()
        self._inflight: dict[tuple[str, str], int] = {}  # (scope, action)
        self.load(config)

    def load(self, config: "dict | None") -> None:
        """(Re)apply a config — hot-reloaded from the filer at
        /etc/s3/circuit_breaker.json (reference s3api_circuit_breaker.go
        subscribes to the same path; the document is
        s3_pb.S3CircuitBreakerConfig, pb/s3.proto). In-flight counters
        survive. Both shapes load: the proto form
        {global:{actions:{...}}} and the terse {global:{Action:N}}."""
        config = config or {}

        def limits(section: dict) -> dict:
            if "actions" in section or "enabled" in section:
                # proto S3CircuitBreakerOptions shape — validate it.
                # `enabled` semantics: an EXPLICIT false disables; an
                # absent key counts as on (divergence from strict proto3
                # omission noted: our shell always writes explicit keys,
                # and silently enforcing a disabled config is the worse
                # failure mode of the two).
                from google.protobuf import json_format

                from ..pb import s3_pb2 as spb
                opts = json_format.ParseDict(section,
                                             spb.S3CircuitBreakerOptions(),
                                             ignore_unknown_fields=True)
                if section.get("enabled") is False:
                    return {}  # kept on disk but switched off
                merged = dict(opts.actions)
                # terse top-level action keys overlay (the shell's
                # s3.circuitbreaker writes Action:N at section level;
                # dropping them silently would ignore operator edits)
                for k, v in section.items():
                    if k not in ("enabled", "actions") and \
                            isinstance(v, (int, float)):
                        merged[k] = int(v)
                return merged
            return dict(section)

        with self._lock:
            self.global_limits = limits(config.get("global") or {})
            self.bucket_limits = {
                b: limits(v) for b, v in (config.get("buckets") or {}).items()}
            self.enabled = bool(self.global_limits or self.bucket_limits)

    @contextmanager
    def acquire(self, action: str, bucket: str):
        if not self.enabled:
            yield
            return
        keys = []
        g_limit = self.global_limits.get(action)
        if g_limit is not None:
            keys.append((("", action), g_limit))
        b_limit = self.bucket_limits.get(bucket, {}).get(action)
        if b_limit is not None:
            keys.append(((bucket, action), b_limit))
        taken = []
        with self._lock:
            for key, limit in keys:
                if self._inflight.get(key, 0) >= limit:
                    for k in taken:  # roll back partial acquisition
                        self._inflight[k] -= 1
                    raise ErrTooManyRequests()
                self._inflight[key] = self._inflight.get(key, 0) + 1
                taken.append(key)
        try:
            yield
        finally:
            with self._lock:
                for key in taken:
                    self._inflight[key] -= 1
