"""Per-action concurrent-request circuit breaker for the S3 gateway.

Reference: weed/s3api/s3api_circuit_breaker.go — global and per-bucket
limits on in-flight requests per action; exceeding a limit returns 503
SlowDown so SDK clients back off and retry, protecting the filer behind
the gateway.

Both of the reference's limit TYPES are enforced: request COUNTS and
in-flight BYTES (the reference keys its actions map `<action>:count` /
`<action>:bytes`, s3_constants LimitTypeCount/LimitTypeBytes). Byte
values accept ints or "512MB"-style strings via the qos size grammar.
Like the reference, byte accounting comes from the request's
Content-Length — it bounds in-flight UPLOAD payloads (Write/Tagging
actions); a `Read:bytes` limit never binds since GETs carry no body
(response-byte pacing is the QoS scheduler's post-charge job).

Config shape (mirrors the spirit of s3_constants circuit-breaker
config):

    {"global": {"Read": 64, "Write:count": 32, "Write:bytes": "64MB"},
     "buckets": {"mybucket": {"Write": 4, "Write:bytes": "16MB"}}}

A bare action key is a count limit (back-compat with the earlier config
documents). Absent actions are unlimited; an empty/None config disables
the breaker. The gateway folds these in-flight limits into the same
admission decision as the QoS scheduler (s3_server._route): one 503
SlowDown + Retry-After path whichever mechanism refuses.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager

from ..qos.policy import parse_size
from .auth import S3Error


class ErrTooManyRequests(S3Error):
    def __init__(self, retry_after_s: int = 1):
        super().__init__("SlowDown",
                         "Please reduce your request rate.", 503)
        # surfaced as the 503's Retry-After header (real S3 SlowDown
        # semantics: back off, then retry the identical request)
        self.retry_after_s = max(1, int(retry_after_s))


def _split_limits(section: dict) -> "tuple[dict, dict]":
    """(count_limits, byte_limits) from one action map. Keys: bare
    action or `action:count` for counts, `action:bytes` for bytes."""
    counts: dict[str, int] = {}
    nbytes: dict[str, float] = {}
    for k, v in section.items():
        action, _, kind = k.partition(":")
        kind = kind.lower()
        if kind in ("", "count"):
            counts[action] = int(v)
        elif kind == "bytes":
            nbytes[action] = parse_size(v, k)
        else:
            raise ValueError(f"circuit breaker: unknown limit type in "
                             f"{k!r} (want :count or :bytes)")
    return counts, nbytes


class CircuitBreaker:
    def __init__(self, config: "dict | None" = None):
        self._lock = threading.Lock()
        self._inflight: dict[tuple[str, str], int] = {}  # (scope, action)
        self._inflight_bytes: dict[tuple[str, str], float] = {}
        self.load(config)

    def load(self, config: "dict | None") -> None:
        """(Re)apply a config — hot-reloaded from the filer at
        /etc/s3/circuit_breaker.json (reference s3api_circuit_breaker.go
        subscribes to the same path; the document is
        s3_pb.S3CircuitBreakerConfig, pb/s3.proto). In-flight counters
        survive. Both shapes load: the proto form
        {global:{actions:{...}}} and the terse {global:{Action:N}}."""
        config = config or {}

        def limits(section: dict) -> "tuple[dict, dict]":
            if "actions" in section or "enabled" in section:
                # proto S3CircuitBreakerOptions shape — validate it.
                # `enabled` semantics: an EXPLICIT false disables; an
                # absent key counts as on (divergence from strict proto3
                # omission noted: our shell always writes explicit keys,
                # and silently enforcing a disabled config is the worse
                # failure mode of the two).
                from google.protobuf import json_format

                from ..pb import s3_pb2 as spb
                opts = json_format.ParseDict(section,
                                             spb.S3CircuitBreakerOptions(),
                                             ignore_unknown_fields=True)
                if section.get("enabled") is False:
                    return {}, {}  # kept on disk but switched off
                merged = dict(opts.actions)
                # terse top-level action keys overlay (the shell's
                # s3.circuitbreaker writes Action:N at section level;
                # dropping them silently would ignore operator edits).
                # Byte limits may arrive as "64MB" strings, which the
                # proto's int64 map can't carry — overlay those too.
                for k, v in section.items():
                    if k not in ("enabled", "actions") and \
                            isinstance(v, (int, float, str)):
                        merged[k] = v
                return _split_limits(merged)
            return _split_limits(dict(section))

        with self._lock:
            self.global_limits, self.global_byte_limits = \
                limits(config.get("global") or {})
            self.bucket_limits = {}
            self.bucket_byte_limits = {}
            for b, v in (config.get("buckets") or {}).items():
                counts, nbytes = limits(v)
                self.bucket_limits[b] = counts
                self.bucket_byte_limits[b] = nbytes
            self.enabled = bool(
                self.global_limits or self.bucket_limits
                or self.global_byte_limits
                or any(self.bucket_byte_limits.values()))

    @contextmanager
    def acquire(self, action: str, bucket: str, nbytes: int = 0):
        """Admit one request of `nbytes` payload (0 = size-free read).
        Count and byte caps share this one enforcement path — exceeding
        EITHER sheds with 503 SlowDown before any work happens."""
        if not self.enabled:
            yield
            return
        keys = []       # ((scope, action), count_limit | None)
        byte_keys = []  # ((scope, action), byte_limit)
        g_limit = self.global_limits.get(action)
        if g_limit is not None:
            keys.append((("", action), g_limit))
        b_limit = self.bucket_limits.get(bucket, {}).get(action)
        if b_limit is not None:
            keys.append(((bucket, action), b_limit))
        gb = self.global_byte_limits.get(action)
        if gb is not None:
            byte_keys.append((("", action), gb))
        bb = self.bucket_byte_limits.get(bucket, {}).get(action)
        if bb is not None:
            byte_keys.append(((bucket, action), bb))
        taken: list = []
        taken_bytes: list = []
        with self._lock:
            try:
                for key, limit in keys:
                    if self._inflight.get(key, 0) >= limit:
                        raise ErrTooManyRequests()
                    self._inflight[key] = self._inflight.get(key, 0) + 1
                    taken.append(key)
                for key, limit in byte_keys:
                    cur = self._inflight_bytes.get(key, 0.0)
                    # an over-sized single request must still pass an
                    # idle gateway (cur == 0), or it could NEVER run
                    if cur > 0 and cur + nbytes > limit:
                        raise ErrTooManyRequests()
                    self._inflight_bytes[key] = cur + nbytes
                    taken_bytes.append(key)
            except ErrTooManyRequests:
                for k in taken:  # roll back partial acquisition
                    self._inflight[k] -= 1
                for k in taken_bytes:
                    self._inflight_bytes[k] -= nbytes
                raise
        try:
            yield
        finally:
            with self._lock:
                for key in taken:
                    self._inflight[key] -= 1
                for key in taken_bytes:
                    self._inflight_bytes[key] -= nbytes
