"""S3 gateway: AWS-compatible REST API over the filer namespace.

Reference: weed/s3api (14,018 LoC — SURVEY.md §2.6): s3api_server.go:109
(router), s3api_object_handlers_put.go, filer_multipart.go (multipart
completes by concatenating part chunk lists), s3api_object_tagging.go,
s3api_bucket_handlers.go. Buckets map to filer dirs /buckets/<bucket>,
object keys to paths beneath. Multipart completion is zero-copy: the final
entry references the parts' chunks with rebased offsets.
"""

from __future__ import annotations

import hashlib
import threading
import time
import urllib.parse
import uuid
import xml.etree.ElementTree as ET

from ..filer.chunks import total_size
from ..filer.filer import join_path, split_path
from ..pb import filer_pb2 as fpb
from ..utils.log import logger
from .auth import (ACTION_LIST, ACTION_READ, ACTION_TAGGING, ACTION_WRITE,
                   IdentityAccessManagement, S3Error)

log = logger("s3")

BUCKETS_DIR = "/buckets"


def _parse_multipart_form(body: bytes, content_type: str
                          ) -> "tuple[dict, str, bytes]":
    """(fields, file_name, file_bytes) from a multipart/form-data body."""
    import email.parser
    import email.policy

    parser = email.parser.BytesParser(policy=email.policy.HTTP)
    msg = parser.parsebytes(
        b"Content-Type: " + content_type.encode() + b"\r\n\r\n" + body)
    fields: dict = {}
    file_name, file_bytes = "", b""
    for part in msg.iter_parts():
        name = part.get_param("name", header="content-disposition")
        if not name:
            continue
        lower = name.lower()
        if lower == "file":
            file_name = part.get_filename() or ""
            file_bytes = part.get_payload(decode=True) or b""
            ctype = part.get_content_type()
            if ctype and ctype != "text/plain":
                fields.setdefault("Content-Type", ctype)
        else:
            payload = part.get_payload(decode=True) or b""
            # AWS matches policy/x-amz-* form fields case-insensitively
            key_name = (lower if lower.startswith("x-amz")
                        or lower in ("policy", "key", "bucket",
                                     "success_action_status",
                                     "content-type") else name)
            fields[key_name] = payload.decode("utf-8", errors="replace")
    return fields, file_name, file_bytes
UPLOADS_DIR = ".uploads"  # hidden per-bucket multipart staging dir
TAG_PREFIX = "x-amz-tag-"
HIGH = "\U0010FFFF"

ErrNoSuchBucket = lambda b: S3Error("NoSuchBucket",  # noqa: E731
                                    f"The specified bucket does not exist: {b}", 404)
ErrNoSuchKey = lambda k: S3Error("NoSuchKey",  # noqa: E731
                                 f"The specified key does not exist: {k}", 404)
ErrBucketNotEmpty = lambda b: S3Error(  # noqa: E731
    "BucketNotEmpty", "The bucket you tried to delete is not empty", 409)
ErrNoSuchUpload = lambda u: S3Error(  # noqa: E731
    "NoSuchUpload", f"The specified upload does not exist: {u}", 404)


class S3Gateway:
    def __init__(self, filer_server, ip: str = "127.0.0.1", port: int = 8333,
                 iam_config: dict | None = None,
                 circuit_breaker: dict | None = None,
                 qos_policy: "dict | str | None" = None,
                 allowed_origins: str = "*"):
        from ..qos import QosScheduler
        from .circuit_breaker import CircuitBreaker
        self.fs = filer_server  # in-process FilerServer
        self.ip, self.port = ip, port
        self.iam = IdentityAccessManagement(iam_config)
        self.breaker = CircuitBreaker(circuit_breaker)
        # multi-tenant QoS (qos/): tenant = the request's access key
        # (falling back to the bucket for anonymous traffic), classes
        # from the verb. The breaker's in-flight count/byte caps and
        # the scheduler's rate/fairness decisions fold into ONE
        # admission path in _route, both answering 503 SlowDown +
        # Retry-After. Policy doc hot-reloads from the filer at
        # /etc/qos/policy.json (standalone gateway) or via load().
        self.qos = QosScheduler(name=f"s3-{port}")
        if isinstance(qos_policy, str) and qos_policy:
            self.qos.attach_file(qos_policy)
        elif qos_policy:
            self.qos.load(qos_policy)
        self.allowed_origins = allowed_origins
        self._stop = threading.Event()
        self._http_thread: threading.Thread | None = None

    @property
    def url(self) -> str:
        return f"{self.ip}:{self.port}"

    def start(self) -> "S3Gateway":
        from ..profiling import LoopLagMonitor, acquire_sampler
        self._sampler = acquire_sampler()
        self._loop_lag = LoopLagMonitor("s3")
        self._http_thread = threading.Thread(target=self._run_http, daemon=True,
                                             name=f"s3-http-{self.port}")
        self._http_ready = threading.Event()
        self._http_thread.start()
        self._http_ready.wait(10)  # port bound before start() returns
        log.info("s3 gateway %s up (auth %s)", self.url,
                 "on" if self.iam.enabled else "off")
        return self

    def stop(self) -> None:
        self._stop.set()
        self.qos.close()
        lag = getattr(self, "_loop_lag", None)
        if lag is not None:
            lag.close()
        if getattr(self, "_sampler", None) is not None:
            from ..profiling import release_sampler
            release_sampler()
            self._sampler = None

    # -- HTTP plumbing -------------------------------------------------------
    def _run_http(self) -> None:
        import asyncio

        from aiohttp import web

        from .. import tracing
        from ..stats import S3_REQUEST_COUNTER, S3_REQUEST_SECONDS

        async def dispatch(request: web.Request):
            import time as _time
            kind = request.method.lower()
            resp = None
            t0 = _time.perf_counter()
            # server span continues the caller's trace; the in-process
            # filer + blob-IO child spans land under it
            with tracing.start_span(
                    f"s3.{kind}", component="s3",
                    child_of=tracing.extract(request.headers),
                    attrs={"path": request.path}) as sp:
                with S3_REQUEST_SECONDS.time(kind):
                    try:
                        if request.method == "OPTIONS":
                            resp = self._cors_preflight(request)
                        else:
                            resp = await self._route(request)
                    except S3Error as e:
                        sp.add_event("s3_error", code=e.code)
                        resp = _error_response(e, request.path)
                    except FileNotFoundError as e:
                        resp = _error_response(
                            S3Error("NoSuchKey", str(e), 404), request.path)
                    except Exception as e:  # noqa: BLE001
                        log.error("s3 http: %r", e)
                        sp.set_error(e)
                        resp = _error_response(
                            S3Error("InternalError", str(e), 500),
                            request.path)
                sp.set_attr("status", resp.status)
                # slow/errored requests land in the flight ring (single
                # stage — the S3 envelope has no wire-level split)
                from ..profiling import record_flight
                record_flight(f"s3.{kind}", _time.perf_counter() - t0,
                              status=resp.status, path=request.path,
                              node=self.url)
            # Label by bucket only for successful requests — failed probes
            # (scanners, typos) would otherwise mint unbounded label sets.
            bucket = (request.path.lstrip("/").split("/", 1)[0]
                      if resp.status < 400 else "")
            S3_REQUEST_COUNTER.inc(kind, str(resp.status), bucket)
            self._apply_cors(request, resp)
            return resp

        def _operator_gate(request):
            """The S3 plane is tenant-facing: with IAM on, spans (fids,
            paths, peer addresses) and metrics (per-bucket traffic
            labels) are operator data — demand a SigV4-signed request
            (deliberately NOT the legacy V2 scheme the object handlers
            still accept). Unsigned or V2-only scrapers belong on the
            filer/master/volume ports, which serve the same process
            registry. Returns an error response, or None to proceed."""
            if request.method == "OPTIONS":
                return self._cors_preflight(request)
            if request.method != "GET":
                return web.json_response({"error": "method not allowed"},
                                         status=405)
            if not self.iam.enabled:
                return None
            try:
                headers = {k.lower(): v
                           for k, v in request.headers.items()}
                self.iam.authenticate(
                    request.method, request.path, dict(request.query),
                    headers,
                    headers.get("x-amz-content-sha256",
                                "UNSIGNED-PAYLOAD"))
            except S3Error as e:
                return _error_response(e, request.path)
            return None

        async def debug_traces(request):
            denied = _operator_gate(request)
            if denied is not None:
                return denied
            return web.json_response(
                tracing.debug_traces_payload(dict(request.query)))

        async def debug_events(request):
            denied = _operator_gate(request)
            if denied is not None:
                return denied
            from ..ops import events
            return web.json_response(
                events.debug_events_payload(dict(request.query)))

        async def debug_locks(request):
            denied = _operator_gate(request)
            if denied is not None:
                return denied
            from ..utils import locktrack
            return web.json_response(
                locktrack.debug_locks_payload(dict(request.query)))

        async def debug_qos(request):
            # live scheduler dump, operator-gated like the other
            # /debug surfaces (per-tenant counters are operator data).
            # Retunes land via the /etc/qos/policy.json watcher (or
            # qos.load() for the embedded gateway), not this endpoint —
            # the gate is deliberately GET-only.
            denied = _operator_gate(request)
            if denied is not None:
                return denied
            return web.json_response(self.qos.debug_payload())

        async def debug_profile(request):
            # shared /debug/profile contract (profiling package):
            # validated/clamped seconds, continuous/summary modes, hz
            # retune — operator-gated like /debug/traces (stacks leak
            # paths and peer addresses); capture runs off the event
            # loop so it can't stall tenant traffic
            denied = _operator_gate(request)
            if denied is not None:
                return denied
            import asyncio as _asyncio

            from .. import profiling as prof
            code, ctype, body = await _asyncio.to_thread(
                prof.handle_profile_query, dict(request.query))
            return web.Response(text=body, status=code,
                                content_type=ctype.split(";")[0])

        async def debug_flight(request):
            denied = _operator_gate(request)
            if denied is not None:
                return denied
            from .. import profiling as prof
            code, payload = prof.debug_flight_payload(dict(request.query))
            return web.json_response(payload, status=code)

        async def metrics(request):
            denied = _operator_gate(request)
            if denied is not None:
                return denied
            from ..stats.metrics import aiohttp_metrics_handler
            return await aiohttp_metrics_handler(request)

        def routes(app):
            # exact routes win over the bucket/key catch-all and claim
            # EVERY method (a GET-only route would let PUT/POST fall
            # through to the object handlers and mint entries no read
            # can ever reach): these two paths are fully reserved
            app.router.add_route("*", "/debug/traces", debug_traces)
            app.router.add_route("*", "/debug/events", debug_events)
            app.router.add_route("*", "/debug/locks", debug_locks)
            app.router.add_route("*", "/debug/qos", debug_qos)
            app.router.add_route("*", "/debug/profile", debug_profile)
            app.router.add_route("*", "/debug/flight", debug_flight)
            app.router.add_route("*", "/metrics", metrics)
            # alias matching the filer's reserved-namespace spelling so
            # the fleet telemetry collector can scrape either daemon
            # kind at /__metrics__ without knowing which it hit
            app.router.add_route("*", "/__metrics__", metrics)
            app.router.add_route("*", "/{tail:.*}", dispatch)

        from ..utils.webapp import serve_web_app
        serve_web_app(routes, self.ip, self.port, self._stop,
                      ready=getattr(self, "_http_ready", None),
                      on_loop=getattr(self, "_loop_lag", None)
                      and self._loop_lag.attach)

    # CORS (reference s3api_server.go cors.AllowAll-style middleware)
    def _cors_preflight(self, request):
        from aiohttp import web
        return web.Response(status=200, headers={
            "Access-Control-Allow-Origin": self.allowed_origins,
            "Access-Control-Allow-Methods":
                "GET, PUT, POST, DELETE, HEAD, OPTIONS",
            "Access-Control-Allow-Headers":
                request.headers.get("Access-Control-Request-Headers")
                or "Authorization, Content-Type, x-amz-date, "
                   "x-amz-content-sha256, *",
            "Access-Control-Expose-Headers": "*",
            "Access-Control-Max-Age": "86400",
        })

    def _apply_cors(self, request, resp) -> None:
        if getattr(resp, "prepared", False):
            return  # streamed response: headers already on the wire
        if request.headers.get("Origin") and self.allowed_origins:
            resp.headers.setdefault("Access-Control-Allow-Origin",
                                    self.allowed_origins)
            resp.headers.setdefault("Access-Control-Expose-Headers", "*")

    @staticmethod
    def _classify_action(method: str, q: dict, bucket: str, key: str) -> str:
        if not bucket or (method in ("GET", "HEAD") and not key):
            return ACTION_LIST
        if "tagging" in q:
            return ACTION_TAGGING
        if method in ("GET", "HEAD"):
            return ACTION_READ
        return ACTION_WRITE

    def _stream_put_ok(self, request, bucket: str, key: str,
                       q: dict) -> bool:
        """True when this PUT can stream through the filer's chunked
        fan-out instead of buffering the whole body: a plain object/part
        upload, large enough to span chunks, whose auth scheme can be
        verified from headers (SigV4's signature covers the DECLARED
        x-amz-content-sha256; the body digest is checked incrementally
        and a mismatch aborts before the entry commits). aws-chunked
        framing and V2 Content-MD5 still need the buffered decoder."""
        if request.method != "PUT" or not bucket or not key \
                or key.endswith("/"):
            return False
        if not hasattr(self.fs, "stream_write"):  # remote-filer gateway
            return False
        if request.headers.get("x-amz-copy-source"):
            return False
        if any(k in q for k in ("acl", "tagging", "retention",
                                "legal-hold")):
            return False
        from .chunked import STREAMING_PAYLOAD, STREAMING_UNSIGNED
        sha = request.headers.get("x-amz-content-sha256", "")
        if sha in (STREAMING_PAYLOAD, STREAMING_UNSIGNED) or \
                "aws-chunked" in request.headers.get("content-encoding", ""):
            return False
        auth_hdr = request.headers.get("Authorization", "")
        if auth_hdr.startswith("AWS ") or (
                "Signature" in q and "AWSAccessKeyId" in q):
            return False  # legacy V2: Content-MD5 precheck needs the body
        try:
            length = int(request.headers.get("Content-Length", ""))
        except ValueError:
            return False
        return length > getattr(self.fs, "chunk_size", 4 << 20)

    @staticmethod
    def _qos_tenant(request, bucket: str) -> str:
        """Tenant identity at the gateway: the request's ACCESS KEY,
        parsed cheaply from whichever auth form it arrived in (SigV4
        Credential scope, presigned X-Amz-Credential, legacy V2 header
        or query). Verification happens later in _authorize — for
        throttle accounting a forged key id only picks whose bucket the
        forger drains. Anonymous traffic falls back to the bucket name
        (the owner's resource is what it competes for)."""
        auth = request.headers.get("Authorization", "")
        if "Credential=" in auth:  # AWS4-HMAC-SHA256 ... Credential=AK/...
            return auth.split("Credential=", 1)[1].split("/", 1)[0]
        if auth.startswith("AWS "):  # V2: "AWS AKID:signature"
            return auth[4:].split(":", 1)[0]
        q = request.query
        cred = q.get("X-Amz-Credential", "")
        if cred:
            return urllib.parse.unquote(cred).split("/", 1)[0]
        if q.get("AWSAccessKeyId"):
            return q["AWSAccessKeyId"]
        return bucket or "anonymous"

    async def _route(self, request):
        from .. import qos as qos_mod
        path = urllib.parse.unquote(request.path)
        parts = path.lstrip("/").split("/", 1)
        bucket = parts[0]
        key = parts[1] if len(parts) > 1 else ""
        q = dict(request.query)
        action = self._classify_action(request.method, q, bucket, key)
        try:
            nbytes = int(request.headers.get("Content-Length") or 0)
        except ValueError:
            nbytes = 0
        # ONE admission decision: the QoS scheduler's rate/fairness/
        # priority verdict, then the breaker's in-flight count+byte
        # caps. Either refusal is a 503 SlowDown + Retry-After.
        grant = None
        if self.qos.enabled:
            is_read = request.method in ("GET", "HEAD")
            klass = qos_mod.class_from_headers(
                request.headers,
                qos_mod.CLASS_INTERACTIVE if is_read
                else qos_mod.CLASS_INGEST)
            try:
                grant = await self.qos.admit(
                    self._qos_tenant(request, bucket), klass, cost=nbytes)
            except qos_mod.QosShed as e:
                from .circuit_breaker import ErrTooManyRequests
                raise ErrTooManyRequests(
                    int(e.retry_after_header)) from None
        try:
            with self.breaker.acquire(action, bucket, nbytes):
                resp = await self._route_admitted(request, bucket, key, q,
                                                  action)
                if grant is not None and request.method == "GET":
                    body = getattr(resp, "body", None)
                    if body:
                        grant.charge(len(body))
                    else:
                        # streamed large-object GETs carry no .body —
                        # charge the declared length, or the biggest
                        # reads would be exactly the ones that bypass
                        # every byte-rate limit
                        length = getattr(resp, "content_length", None)
                        if length:
                            grant.charge(int(length))
                return resp
        finally:
            if grant is not None:
                grant.release()

    async def _route_admitted(self, request, bucket, key, q, action):
        if self._stream_put_ok(request, bucket, key, q):
            self._authorize(request, bucket, key, q, None, action)
            return await self._put_streaming(request, bucket, key, q)
        body = await request.read()
        # browser post-policy uploads carry their signature IN the
        # form; post_policy_upload authorizes from the policy fields
        is_post_policy = (request.method == "POST" and bucket and not key
                          and "delete" not in q
                          and request.content_type.startswith(
                              "multipart/form-data"))
        if not is_post_policy:
            seed_ctx = self._authorize(request, bucket, key, q, body,
                                       action)
            body = self._maybe_decode_chunked(request, body, seed_ctx)

        if not bucket:
            return self.list_buckets()
        if not key:
            return await self._route_bucket(request, bucket, q, body)
        return await self._route_object(request, bucket, key, q, body)

    def _maybe_decode_chunked(self, request, body, seed_ctx):
        """Strip + verify aws-chunked framing on streaming-signed uploads
        (reference chunked_reader_v4.go)."""
        from .chunked import (STREAMING_PAYLOAD, STREAMING_UNSIGNED,
                              decode_chunked_payload)
        sha = request.headers.get("x-amz-content-sha256", "")
        enc = request.headers.get("content-encoding", "")
        if sha == STREAMING_PAYLOAD:
            decoded = decode_chunked_payload(body, seed_ctx)
        elif sha == STREAMING_UNSIGNED or "aws-chunked" in enc:
            decoded = decode_chunked_payload(body, None)
        else:
            return body
        declared = request.headers.get("x-amz-decoded-content-length")
        if declared is not None and declared.isdigit() and \
                int(declared) != len(decoded):
            raise S3Error("IncompleteBody",
                          "You did not provide the number of bytes specified "
                          "by the Content-Length HTTP header.", 400)
        return decoded

    def _authorize(self, request, bucket, key, q, body, action):
        """Returns the streaming SeedContext for chunk verification when the
        request is streaming-signed, else None."""
        if not self.iam.enabled:
            return None
        from .chunked import STREAMING_PAYLOAD, STREAMING_UNSIGNED
        payload_hash = request.headers.get("x-amz-content-sha256",
                                           "UNSIGNED-PAYLOAD")
        headers = {k.lower(): v for k, v in request.headers.items()}
        seed_ctx = None
        auth_hdr = headers.get("authorization", "")
        if auth_hdr.startswith("AWS ") or (
                "Signature" in q and "AWSAccessKeyId" in q):
            # legacy signature V2 clients (reference auth_signature_v2.go)
            from . import auth as auth_mod
            path = urllib.parse.unquote(request.path)
            if auth_hdr.startswith("AWS "):
                md5_hdr = headers.get("content-md5", "")
                if md5_hdr:
                    import base64
                    actual = base64.b64encode(
                        hashlib.md5(body,
                                    usedforsecurity=False).digest()).decode()
                    if actual != md5_hdr:
                        raise S3Error("BadDigest",
                                      "The Content-MD5 you specified did "
                                      "not match what we received.", 400)
                ident = auth_mod.verify_v2_header(
                    self.iam, request.method, path, dict(request.query),
                    headers)
            else:
                ident = auth_mod.verify_v2_presigned(
                    self.iam, request.method, path, dict(request.query),
                    headers)
            from .auth import ErrAccessDenied
            if not ident.allows(action, bucket):
                raise ErrAccessDenied()
            request["s3_identity"] = ident
            return None
        if payload_hash == STREAMING_PAYLOAD:
            ident, seed_ctx = self.iam.authenticate_streaming(
                request.method, urllib.parse.unquote(request.path),
                dict(request.query), headers)
        else:
            if payload_hash not in ("UNSIGNED-PAYLOAD", STREAMING_UNSIGNED) \
                    and body is not None:
                # body=None = streaming PUT: the digest is verified
                # incrementally by _put_streaming before the entry commits
                actual = hashlib.sha256(body).hexdigest()
                if actual != payload_hash:
                    raise S3Error("XAmzContentSHA256Mismatch",
                                  "The provided 'x-amz-content-sha256' header "
                                  "does not match what was computed.", 400)
            ident = self.iam.authenticate(request.method,
                                          urllib.parse.unquote(request.path),
                                          dict(request.query), headers,
                                          payload_hash)
        from .auth import ErrAccessDenied

        if not ident.allows(action, bucket):
            raise ErrAccessDenied()
        request["s3_identity"] = ident
        return seed_ctx

    async def _route_bucket(self, request, bucket, q, body):
        from aiohttp import web
        m = request.method
        if m == "PUT":
            if "acl" in q:
                return self.put_acl(bucket, "", request, body)
            if "lifecycle" in q:
                return self.put_bucket_lifecycle(bucket, body)
            if "policy" in q:
                # reference parity: PutBucketPolicyHandler -> NotImplemented
                # (s3api_bucket_skip_handlers.go:35)
                raise S3Error("NotImplemented",
                              "Bucket policies are not implemented.", 501)
            if "versioning" in q:
                raise S3Error("NotImplemented",  # skip_handlers.go:47
                              "Versioning cannot be enabled.", 501)
            if "object-lock" in q:
                # bucket-level subresource; acknowledged no-op like the
                # reference's PutObjectLockConfigurationHandler (204)
                return web.Response(status=204)
            return self.put_bucket(bucket, acl=self._canned_acl(request))
        if m == "HEAD":
            return self.head_bucket(bucket)
        if m == "DELETE":
            if "lifecycle" in q:
                return self.delete_bucket_lifecycle(bucket)
            if "policy" in q:  # skip_handlers.go:41 returns 204
                return web.Response(status=204)
            return self.delete_bucket(bucket)
        if m == "POST" and "delete" in q:
            return self.delete_multiple_objects(bucket, body)
        if m == "POST" and request.content_type.startswith(
                "multipart/form-data"):
            return self.post_policy_upload(request, bucket, body)
        if m == "GET":
            if "acl" in q:
                return self.get_acl(bucket, "")
            if "lifecycle" in q:
                return self.get_bucket_lifecycle(bucket)
            if "policy" in q:  # skip_handlers.go:29
                raise S3Error("NoSuchBucketPolicy",
                              "The bucket policy does not exist", 404)
            if "versioning" in q:
                return self.get_bucket_versioning(bucket)
            if "object-lock" in q:
                raise S3Error("ObjectLockConfigurationNotFoundError",
                              "Object Lock configuration does not exist "
                              "for this bucket", 404)
            if "uploads" in q:
                return self.list_multipart_uploads(bucket, q)
            return self.list_objects(bucket, q)
        raise S3Error("MethodNotAllowed", "Method not allowed.", 405)

    async def _route_object(self, request, bucket, key, q, body):
        m = request.method
        if m == "PUT":
            if "partNumber" in q and "uploadId" in q:
                src = request.headers.get("x-amz-copy-source")
                if src:
                    return self.upload_part_copy(
                        bucket, key, q, src,
                        request.headers.get("x-amz-copy-source-range", ""),
                        request)
                return self.upload_part(bucket, key, q, body)
            if "acl" in q:
                return self.put_acl(bucket, key, request, body)
            if "tagging" in q:
                return self.put_object_tagging(bucket, key, body)
            if "retention" in q or "legal-hold" in q:
                # reference parity: PutObjectRetention/LegalHold are
                # acknowledged no-ops (object_handlers_skip.go:25-37)
                from aiohttp import web
                return web.Response(status=204)
            src = request.headers.get("x-amz-copy-source")
            if src:
                return self.copy_object(bucket, key, src,
                                        acl=self._canned_acl(request),
                                        request=request)
            return self.put_object(bucket, key, body,
                                   request.content_type or "",
                                   acl=self._canned_acl(request),
                                   meta=_user_meta(request.headers))
        if m == "POST":
            if "uploads" in q:
                return self.initiate_multipart(
                    bucket, key, acl=self._canned_acl(request),
                    meta=_user_meta(request.headers))
            if "uploadId" in q:
                return self.complete_multipart(bucket, key, q["uploadId"], body)
        if m in ("GET", "HEAD"):
            if "acl" in q:
                return self.get_acl(bucket, key)
            if "tagging" in q:
                return self.get_object_tagging(bucket, key)
            if "retention" in q or "legal-hold" in q:
                # never set (the PUTs are no-ops): answer not-found, not
                # the object body
                raise S3Error("NoSuchObjectLockConfiguration",
                              "The specified object does not have an "
                              "ObjectLock configuration", 404)
            if "uploadId" in q:
                return self.list_parts(bucket, key, q)
            return await self.get_object(bucket, key, request)
        if m == "DELETE":
            if "uploadId" in q:
                return self.abort_multipart(bucket, key, q["uploadId"])
            if "tagging" in q:
                return self.delete_object_tagging(bucket, key)
            return self.delete_object(bucket, key)
        raise S3Error("MethodNotAllowed", "Method not allowed.", 405)

    # -- buckets -------------------------------------------------------------
    # -- bucket lifecycle (reference s3api_bucket_handlers.go:300-470:
    # expiration rules map onto filer.conf TTL path rules; transitions
    # and date-based expiry are NotImplemented there too) ------------------
    def _read_filer_conf(self):
        from ..filer.filer_conf import CONF_DIR, CONF_NAME, FilerConf
        entry = self.fs.filer.find_entry(CONF_DIR, CONF_NAME)
        raw = self.fs.read_entry_bytes(entry) if entry is not None else b""
        return FilerConf.from_bytes(raw)

    def _save_filer_conf(self, conf) -> None:
        from ..filer.filer_conf import CONF_PATH
        self.fs.write_file(CONF_PATH, conf.to_bytes(),
                           mime="application/json")

    def put_bucket_lifecycle(self, bucket, body):
        from aiohttp import web

        from ..filer.filer_conf import PathRule
        self._require_bucket(bucket)
        try:
            root = ET.fromstring(body)
        except ET.ParseError:
            raise S3Error("MalformedXML", "Invalid lifecycle XML.", 400)
        ns = root.tag.split("}")[0] + "}" if root.tag.startswith("{") else ""
        conf = self._read_filer_conf()
        # S3 semantics: PUT REPLACES the whole lifecycle configuration —
        # strip the TTLs a previous PUT installed before applying the new
        # rules (a PUT that only drops a rule must not be a no-op)
        changed = self._strip_lifecycle_ttls(conf, bucket)
        for rule in root.iter(f"{ns}Rule"):
            if (rule.findtext(f"{ns}Status") or "").strip() != "Enabled":
                continue
            prefix = (rule.findtext(f"{ns}Filter/{ns}Prefix")
                      or rule.findtext(f"{ns}Prefix") or "").strip()
            exp = rule.find(f"{ns}Expiration")
            try:
                days = int(exp.findtext(f"{ns}Days") or 0) \
                    if exp is not None else 0
            except ValueError:
                raise S3Error("MalformedXML", "Invalid expiration days.",
                              400)
            if exp is not None and exp.find(f"{ns}Date") is not None or \
                    rule.find(f"{ns}Transition") is not None:
                raise S3Error("NotImplemented",
                              "Only Days-based expiration is supported.",
                              501)
            if days <= 0:
                continue
            lp = f"{BUCKETS_DIR}/{bucket}/{prefix}"
            # merge into any admin-set rule for the prefix: the lifecycle
            # owns only the TTL, never replication/collection/disk_type
            import dataclasses
            existing = next((r for r in conf.rules
                             if r.location_prefix == lp), None)
            conf.upsert(dataclasses.replace(existing, ttl=f"{days}d",
                                            from_lifecycle=True)
                        if existing is not None
                        else PathRule(location_prefix=lp, ttl=f"{days}d",
                                      from_lifecycle=True))
            changed = True
        if changed:
            self._save_filer_conf(conf)
        return web.Response(status=200)

    def get_bucket_lifecycle(self, bucket):
        self._require_bucket(bucket)
        conf = self._read_filer_conf()
        prefix = f"{BUCKETS_DIR}/{bucket}/"
        rules = [(r.location_prefix[len(prefix):], r.ttl)
                 for r in conf.rules
                 if r.location_prefix.startswith(prefix)
                 and r.ttl.endswith("d")]
        if not rules:
            raise S3Error("NoSuchLifecycleConfiguration",
                          "The lifecycle configuration does not exist.", 404)
        root = ET.Element("LifecycleConfiguration")
        for i, (p, ttl) in enumerate(sorted(rules)):
            rule = ET.SubElement(root, "Rule")
            ET.SubElement(rule, "ID").text = f"rule-{i + 1}"
            f = ET.SubElement(rule, "Filter")
            ET.SubElement(f, "Prefix").text = p
            ET.SubElement(rule, "Status").text = "Enabled"
            exp = ET.SubElement(rule, "Expiration")
            ET.SubElement(exp, "Days").text = ttl[:-1]
        return _xml_response(root)

    def _strip_lifecycle_ttls(self, conf, bucket: str) -> bool:
        """Remove the TTLs lifecycle PUTs own under the bucket — only rules
        carrying the from_lifecycle marker; TTLs an admin set via
        fs.configure survive, and rules an admin enriched with
        replication/collection/disk_type survive TTL-less. Returns whether
        anything changed."""
        import dataclasses
        prefix = f"{BUCKETS_DIR}/{bucket}/"
        changed = False
        # NOTE upgrade path: rules persisted by pre-marker builds carry no
        # from_lifecycle flag and are treated as admin-owned — remove them
        # once with `fs.configure -locationPrefix ... -ttl ""` if they came
        # from an old lifecycle PUT. Guessing here would re-open the bug
        # where DeleteBucketLifecycle strips TTLs an admin set.
        for r in list(conf.rules):
            if not (r.location_prefix.startswith(prefix)
                    and r.from_lifecycle and r.ttl.endswith("d")):
                continue
            stripped = dataclasses.replace(r, ttl="", from_lifecycle=False)
            if any(getattr(stripped, k) not in ("", False, 0)
                   for k in ("collection", "replication", "disk_type",
                             "fsync", "volume_growth_count")):
                conf.upsert(stripped)
            else:
                conf.delete(r.location_prefix)
            changed = True
        return changed

    def delete_bucket_lifecycle(self, bucket):
        from aiohttp import web
        self._require_bucket(bucket)
        conf = self._read_filer_conf()
        if self._strip_lifecycle_ttls(conf, bucket):
            self._save_filer_conf(conf)
        return web.Response(status=204)

    def get_bucket_versioning(self, bucket):
        """Reference GetBucketVersioningHandler: always Suspended
        (s3api_bucket_handlers.go:651)."""
        self._require_bucket(bucket)
        root = ET.Element("VersioningConfiguration")
        ET.SubElement(root, "Status").text = "Suspended"
        return _xml_response(root)

    def _bucket_dir(self, bucket: str) -> str:
        return f"{BUCKETS_DIR}/{bucket}"

    def _require_bucket(self, bucket: str) -> None:
        if self.fs.filer.find_entry(BUCKETS_DIR, bucket) is None:
            raise ErrNoSuchBucket(bucket)

    def list_buckets(self):
        root = ET.Element("ListAllMyBucketsResult")
        owner = ET.SubElement(root, "Owner")
        ET.SubElement(owner, "ID").text = "swtpu"
        buckets = ET.SubElement(root, "Buckets")
        for e in self.fs.filer.list_entries(BUCKETS_DIR):
            if not e.is_directory:
                continue
            b = ET.SubElement(buckets, "Bucket")
            ET.SubElement(b, "Name").text = e.name
            ET.SubElement(b, "CreationDate").text = _iso(e.attributes.crtime)
        return _xml_response(root)

    def put_bucket(self, bucket, acl: str | None = None):
        from aiohttp import web

        existing = self.fs.filer.find_entry(BUCKETS_DIR, bucket)
        if existing is None:
            e = fpb.Entry(name=bucket, is_directory=True)
            e.attributes.file_mode = 0o40755
            if acl:
                e.extended["acl"] = acl.encode()
            self.fs.filer.create_entry(BUCKETS_DIR, e)
        elif acl:
            self._store_acl(BUCKETS_DIR, existing, acl)
        return web.Response(status=200, headers={"Location": f"/{bucket}"})

    def head_bucket(self, bucket):
        from aiohttp import web

        self._require_bucket(bucket)
        return web.Response(status=200)

    def delete_bucket(self, bucket):
        from aiohttp import web

        self._require_bucket(bucket)
        for e in self.fs.filer.list_entries(self._bucket_dir(bucket), limit=2):
            if e.name != UPLOADS_DIR:
                raise ErrBucketNotEmpty(bucket)
        self.fs.filer.delete_entry(BUCKETS_DIR, bucket, is_recursive=True)
        return web.Response(status=204)

    # -- objects -------------------------------------------------------------
    def _object_path(self, bucket: str, key: str) -> str:
        return f"{self._bucket_dir(bucket)}/{key}"

    def _check_quota(self, bucket: str) -> None:
        """s3.bucket.quota.check marks over-quota buckets read-only in the
        bucket entry's extended attrs (reference s3_bucket_quota)."""
        e = self.fs.filer.find_entry(BUCKETS_DIR, bucket)
        if e is not None and e.extended.get("quota_readonly") == b"1":
            raise S3Error("QuotaExceeded",
                          "bucket is over its configured quota", 403)

    def post_policy_upload(self, request, bucket, body):
        """Browser form upload (reference post-policy handling in
        s3api_object_handlers_postpolicy.go)."""
        from aiohttp import web

        from . import auth as auth_mod

        # full header WITH the boundary param (aiohttp's .content_type
        # strips parameters)
        fields, file_name, file_bytes = _parse_multipart_form(
            body, request.headers.get("Content-Type", ""))
        fields["bucket"] = bucket  # policy {"bucket": ...} condition input
        if self.iam.enabled:
            ident = auth_mod.verify_post_policy(self.iam, fields)
            from .auth import ErrAccessDenied
            if not ident.allows(ACTION_WRITE, bucket):
                raise ErrAccessDenied()
        key = fields.get("key", "")
        if not key:
            raise S3Error("InvalidArgument", "missing key field", 400)
        key = key.replace("${filename}", file_name or "file")
        self._require_bucket(bucket)
        self._check_quota(bucket)
        acl = self._validate_canned(fields.get("acl"))
        entry = self.fs.write_file(self._object_path(bucket, key), file_bytes,
                                   mime=fields.get("Content-Type", ""))
        attrs = {k.lower(): v.encode() for k, v in fields.items()
                 if k.lower().startswith("x-amz-meta-")}
        if acl:
            attrs["acl"] = acl.encode()
        d, _n = split_path(self._object_path(bucket, key))
        self._merge_extended(d, entry, attrs)
        try:
            status = int(fields.get("success_action_status", "204"))
        except ValueError:
            status = 204  # AWS ignores junk values the same way
        if status not in (200, 201, 204):
            status = 204
        return web.Response(status=status)

    _CANNED_ACLS = ("private", "public-read", "public-read-write",
                    "authenticated-read", "bucket-owner-read",
                    "bucket-owner-full-control")

    def _validate_canned(self, canned: str | None) -> str | None:
        if canned is not None and canned not in self._CANNED_ACLS:
            raise S3Error("InvalidArgument",
                          f"unsupported ACL {canned!r}", 400)
        return canned

    def _canned_acl(self, request) -> str | None:
        return self._validate_canned(request.headers.get("x-amz-acl"))

    def _acl_entry(self, bucket, key):
        self._require_bucket(bucket)
        if key:
            return self._find_object(bucket, key)
        return BUCKETS_DIR, bucket, self.fs.filer.find_entry(
            BUCKETS_DIR, bucket)

    def _merge_extended(self, d: str, e: fpb.Entry,
                        attrs: "dict[str, bytes]") -> None:
        """Merge extended attributes (acl, x-amz-meta-*, tags) in ONE
        metadata-only update: no mtime bump (Last-Modified must not move
        for an ACL/metadata change) and no chunk GC."""
        if not attrs:
            return
        upd = fpb.Entry()
        upd.CopyFrom(e)
        for k, v in attrs.items():
            upd.extended[k] = v
        self.fs.filer.update_entry(d, upd, gc_chunks=False,
                                   touch_mtime=False)
        e.CopyFrom(upd)

    def _store_acl(self, d: str, e: fpb.Entry, canned: str) -> None:
        self._merge_extended(d, e, {"acl": canned.encode()})

    def put_acl(self, bucket, key, request, body):
        """Canned ACLs via the x-amz-acl header (reference
        s3api_object_handlers_acl.go). Explicit grant-XML bodies are not
        interpreted — they fail loudly rather than silently mis-apply."""
        from aiohttp import web

        canned = self._canned_acl(request)
        if canned is None:
            if body:
                raise S3Error(
                    "NotImplemented",
                    "AccessControlPolicy grant bodies are not supported; "
                    "use the x-amz-acl canned header.", 501)
            raise S3Error("InvalidArgument", "missing x-amz-acl header", 400)
        d, _n, e = self._acl_entry(bucket, key)
        self._store_acl(d, e, canned)
        return web.Response(status=200)

    _ALL_USERS = "http://acs.amazonaws.com/groups/global/AllUsers"
    _AUTH_USERS = "http://acs.amazonaws.com/groups/global/AuthenticatedUsers"
    _XSI = "http://www.w3.org/2001/XMLSchema-instance"

    def get_acl(self, bucket, key):
        _d, _n, e = self._acl_entry(bucket, key)
        canned = (e.extended.get("acl") or b"private").decode()
        root = ET.Element("AccessControlPolicy")
        owner = ET.SubElement(root, "Owner")
        ET.SubElement(owner, "ID").text = "owner"
        acl = ET.SubElement(root, "AccessControlList")

        def grant(perm: str, group_uri: str | None = None,
                  user_id: str = "owner"):
            g = ET.SubElement(acl, "Grant")
            gt = ET.SubElement(g, "Grantee", {"xmlns:xsi": self._XSI})
            if group_uri:
                gt.set("xsi:type", "Group")
                ET.SubElement(gt, "URI").text = group_uri
            else:
                gt.set("xsi:type", "CanonicalUser")
                ET.SubElement(gt, "ID").text = user_id
            ET.SubElement(g, "Permission").text = perm

        grant("FULL_CONTROL")
        if canned.startswith("public-read"):
            grant("READ", self._ALL_USERS)
        if canned == "public-read-write":
            grant("WRITE", self._ALL_USERS)
        elif canned == "authenticated-read":
            grant("READ", self._AUTH_USERS)
        elif canned == "bucket-owner-read":
            grant("READ", user_id="bucket-owner")
        elif canned == "bucket-owner-full-control":
            grant("FULL_CONTROL", user_id="bucket-owner")
        return _xml_response(root)

    def put_object(self, bucket, key, body, mime, acl: str | None = None,
                   meta: "dict[str, str] | None" = None):
        from aiohttp import web

        self._require_bucket(bucket)
        self._check_quota(bucket)
        attrs = {k.lower(): v.encode() for k, v in (meta or {}).items()}
        if acl:
            attrs["acl"] = acl.encode()
        if key.endswith("/"):  # directory object
            d, n = split_path(self._object_path(bucket, key))
            e = fpb.Entry(name=n, is_directory=True)
            e.attributes.file_mode = 0o40755
            for k, v in attrs.items():
                e.extended[k] = v
            existing = self.fs.filer.find_entry(d, n)
            if existing is None:
                self.fs.filer.create_entry(d, e)
            else:
                self._merge_extended(d, existing, attrs)
            return web.Response(status=200, headers={"ETag": '"d41d8cd98f00b204e9800998ecf8427e"'})
        entry = self.fs.write_file(self._object_path(bucket, key), body,
                                   mime=mime)
        d, _n = split_path(self._object_path(bucket, key))
        self._merge_extended(d, entry, attrs)
        return web.Response(status=200,
                            headers={"ETag": f'"{entry.attributes.md5.hex()}"'})

    async def _put_streaming(self, request, bucket, key, q):
        """Large-object PutObject/UploadPart: the body is chunked AS IT
        ARRIVES and fanned out on the filer's upload window, so a
        multi-GB PUT holds O(chunk_size x concurrency) — never the whole
        object. A signed payload's sha256 is computed incrementally;
        a mismatch aborts BEFORE the entry is committed and the landed
        chunks are deleted (no partial object is ever visible)."""
        from aiohttp import web

        self._require_bucket(bucket)
        self._check_quota(bucket)
        sha = request.headers.get("x-amz-content-sha256", "")
        hasher = (hashlib.sha256()
                  if sha and sha != "UNSIGNED-PAYLOAD" else None)

        def finalize():
            if hasher is not None and hasher.hexdigest() != sha:
                raise S3Error(
                    "XAmzContentSHA256Mismatch",
                    "The provided 'x-amz-content-sha256' header does not "
                    "match what was computed.", 400)

        observer = hasher.update if hasher is not None else None
        if "partNumber" in q and "uploadId" in q:
            upload_id = q["uploadId"]
            self._find_upload(bucket, upload_id)
            part = int(q["partNumber"])
            path = f"{self._upload_dir(bucket, upload_id)}/{part:05d}.part"
            entry = await self.fs.stream_write(
                request.content, path, observer=observer, finalize=finalize)
            return web.Response(status=200, headers={
                "ETag": f'"{entry.attributes.md5.hex()}"'})
        acl = self._canned_acl(request)
        attrs = {k.lower(): v.encode()
                 for k, v in _user_meta(request.headers).items()}
        if acl:
            attrs["acl"] = acl.encode()
        path = self._object_path(bucket, key)
        entry = await self.fs.stream_write(
            request.content, path, mime=request.content_type or "",
            observer=observer, finalize=finalize)
        d, _n = split_path(path)
        self._merge_extended(d, entry, attrs)
        return web.Response(status=200, headers={
            "ETag": f'"{entry.attributes.md5.hex()}"'})

    def _resolve_copy_source(self, src: str, request):
        """(src_bucket, src_key, entry) for an x-amz-copy-source value.
        Enforces READ on the SOURCE bucket — without this, write access
        to one bucket would exfiltrate objects from any other."""
        src = urllib.parse.unquote(src)
        src = src[src.startswith("/") and 1 or 0:]
        sb, _, sk = src.partition("/")
        ident = request.get("s3_identity") if request is not None else None
        if self.iam.enabled and ident is not None \
                and not ident.allows(ACTION_READ, sb):
            from .auth import ErrAccessDenied
            raise ErrAccessDenied()
        d, n = split_path(self._object_path(sb, sk))
        entry = self.fs.filer.find_entry(d, n)
        if entry is None:
            raise ErrNoSuchKey(sk)
        return sb, sk, entry

    def _can_copy_by_reference(self, entry) -> bool:
        """Server-side copy moves zero object bytes when the source is a
        plain chunked entry and the backing filer supports shared-chunk
        refcounts (the in-process FilerServer; a remote-filer gateway
        falls back to data copy)."""
        return bool(entry.chunks) and not entry.content \
            and hasattr(getattr(self.fs, "filer", None), "adopt_chunks")

    def _create_cloned_entry(self, dst_path: str, chunks, file_size: int,
                             md5_digest: bytes, mime: str,
                             extended: "dict[str, bytes]",
                             adopted: "list[str]") -> fpb.Entry:
        """Create an entry over an already-cloned chunk list: bump the
        shared-chunk refcounts FIRST (a crash between the two leaks a
        count — harmless — instead of double-freeing a live chunk), roll
        them back if the create fails."""
        d, n = split_path(dst_path)
        new = fpb.Entry(name=n)
        for c in chunks:
            nc = new.chunks.add()
            nc.CopyFrom(c)
        a = new.attributes
        a.file_size = file_size
        a.mime = mime
        a.file_mode = 0o644
        a.md5 = md5_digest
        for k, v in extended.items():
            new.extended[k] = v
        adopted = [f for f in adopted if f]
        if adopted:
            self.fs.filer.adopt_chunks(adopted)
        try:
            self.fs.filer.create_entry(d, new)
        except BaseException:
            if adopted:
                self.fs.filer.release_chunks(adopted)
            raise
        return new

    def _verify_copy_source_alive(self, sb: str, sk: str,
                                  dst_path: str) -> None:
        """Close the copy/delete race: the refcounts were adopted, so if
        the source entry STILL exists, any later delete observes them
        and spares the shared blobs (the filer deletes the entry before
        releasing chunks). If it's gone, a delete may have released —
        and possibly freed — the blobs before our adoption: undo the
        clone and answer NoSuchKey like a copy that lost the race
        outright."""
        sd, sn = split_path(self._object_path(sb, sk))
        if self.fs.filer.find_entry(sd, sn) is not None:
            return
        dd, dn = split_path(dst_path)
        try:
            # the clone's own data-delete consumes the adopted counts in
            # EITHER interleaving: if the source's release beat the
            # adoption (blobs already freed) it just zeroes the stray
            # counts; if the adoption won, it drops the last reference
            # and frees the now-unreferenced blobs
            self.fs.filer.delete_entry(dd, dn, is_delete_data=True)
        except Exception as e:  # noqa: BLE001 — undo is best-effort
            log.warning("copy-race cleanup of %s: %s", dst_path, e)
        raise ErrNoSuchKey(sk)

    def _clone_chunk_range(self, entry, lo: int, size: int,
                           dst_path: str):
        """(chunks, adopted_fids) covering [lo, lo+size) of the source.
        Visible intervals that span a chunk's WHOLE blob clone by
        reference with rebased offsets; sub-chunk head/tail slices (and
        partially-overwritten chunks) fall back to data copy — a
        FileChunk cannot address a mid-blob range. Manifest chunks are
        resolved first: their nested offsets are absolute and cannot be
        rebased wholesale."""
        from ..filer.chunks import resolve_chunks
        chunks = self.fs.filer.data_chunks(entry, self.fs._fetch_blob)
        out: "list[fpb.FileChunk]" = []
        adopted: "list[str]" = []
        hi = lo + size
        try:
            for s, e, c in resolve_chunks(chunks):
                if e <= lo or s >= hi:
                    continue
                if s >= lo and e <= hi and s == c.offset \
                        and e == c.offset + c.size:
                    nc = fpb.FileChunk()
                    nc.CopyFrom(c)
                    nc.offset = s - lo
                    out.append(nc)
                    adopted.append(c.file_id)
                else:
                    ov_lo, ov_hi = max(s, lo), min(e, hi)
                    data = self.fs.read_entry_bytes(entry, ov_lo,
                                                    ov_hi - ov_lo)
                    nc = self.fs._save_blob(data, path=dst_path)
                    nc.offset = ov_lo - lo
                    out.append(nc)
        except BaseException:
            self._drop_copied_slices(out, adopted)
            raise
        return out, adopted

    def _drop_copied_slices(self, chunks, adopted: "list[str]") -> None:
        """Delete the DATA-COPIED slice blobs of a failed clone (the
        by-reference fids roll back via refcounts; slices are brand-new
        needles nothing else references)."""
        shared = set(adopted)
        copied = [c.file_id for c in chunks
                  if c.file_id and c.file_id not in shared]
        if copied:
            try:
                self.fs.filer.chunk_deleter(copied)
            except Exception as e:  # noqa: BLE001 — cleanup best-effort
                log.warning("slice cleanup %s: %s", copied, e)

    def copy_object(self, bucket, key, src, acl: str | None = None,
                    request=None):
        self._check_quota(bucket)
        self._require_bucket(bucket)
        sb, sk, entry = self._resolve_copy_source(src, request)
        hdrs = request.headers if request is not None else {}
        directive = (hdrs.get("x-amz-metadata-directive") or "COPY").upper()
        if directive not in ("COPY", "REPLACE"):
            raise S3Error("InvalidArgument",
                          "Unknown metadata directive.", 400)
        if sb == bucket and sk == key and directive == "COPY":
            # s3tests test_object_copy_to_itself: illegal without
            # changing metadata (REPLACE)
            raise S3Error(
                "InvalidRequest",
                "This copy request is illegal because it is trying to "
                "copy an object to itself without changing the object's "
                "metadata, storage class, website redirect location or "
                "encryption attributes.", 400)
        # x-amz-copy-source-if-* (s3tests test_copy_object_ifmatch_good /
        # ifnonematch_failed / ...): all failures answer 412
        cond = _check_preconditions(hdrs, _entry_etag(entry),
                                    entry.attributes.mtime,
                                    prefix="x-amz-copy-source-")
        if cond is not None:
            raise S3Error("PreconditionFailed",
                          "At least one of the pre-conditions you "
                          "specified did not hold", 412)
        if directive == "REPLACE":
            mime = (hdrs.get("Content-Type") or hdrs.get("content-type")
                    or entry.attributes.mime)
            attrs = {k: v.encode() for k, v in _user_meta(hdrs).items()}
        else:  # COPY: source metadata AND tags travel with the object
            mime = entry.attributes.mime
            attrs = {k: bytes(v) for k, v in entry.extended.items()
                     if k.startswith(("x-amz-meta-", TAG_PREFIX))}
        if acl:
            attrs["acl"] = acl.encode()
        dst_path = self._object_path(bucket, key)
        if self._can_copy_by_reference(entry):
            # zero-copy: clone the chunk list (offsets unchanged for a
            # whole-object copy, so manifest chunks clone too) and bump
            # the shared-chunk refcounts — deleting the source later
            # must not GC the copy's data
            if entry.extended.get("s3-etag"):
                attrs = dict(attrs)
                attrs["s3-etag"] = bytes(entry.extended["s3-etag"])
            same = sb == bucket and sk == key
            new = self._create_cloned_entry(
                dst_path, list(entry.chunks),
                entry.attributes.file_size or total_size(entry.chunks),
                bytes(entry.attributes.md5), mime, attrs,
                # copy-onto-itself replaces the entry: the GC's
                # keep-set already protects the shared fids, a bump
                # here would leak them forever
                [] if same else [c.file_id for c in entry.chunks])
            if not same:
                self._verify_copy_source_alive(sb, sk, dst_path)
        else:
            data = self.fs.read_entry_bytes(entry)
            new = self.fs.write_file(dst_path, data, mime=mime)
            dd, _n = split_path(dst_path)
            self._merge_extended(dd, new, attrs)
        root = ET.Element("CopyObjectResult")
        ET.SubElement(root, "ETag").text = f'"{_entry_etag(new)}"'
        ET.SubElement(root, "LastModified").text = _iso(new.attributes.mtime)
        return _xml_response(root)

    async def get_object(self, bucket, key, request):
        from aiohttp import web

        self._require_bucket(bucket)
        d, n = split_path(self._object_path(bucket, key))
        entry = self.fs.filer.find_entry(d, n)
        if entry is not None and entry.is_directory and key.endswith("/"):
            dir_headers = {"ETag": '"d41d8cd98f00b204e9800998ecf8427e"',
                           "Content-Type": "application/octet-stream"}
            for k, v in entry.extended.items():
                if k.startswith("x-amz-meta-"):
                    dir_headers[k] = v.decode()
            return web.Response(  # directory object: empty body
                status=200, headers=dir_headers)
        if entry is None or entry.is_directory:
            raise ErrNoSuchKey(key)
        fsize = entry.attributes.file_size or total_size(entry.chunks)
        etag = _entry_etag(entry)
        # conditional GET/HEAD (s3tests test_get_object_ifmatch_* /
        # ifnonematch / ifmodifiedsince / ifunmodifiedsince)
        cond = _check_preconditions(request.headers, etag,
                                    entry.attributes.mtime)
        if cond == 304:
            return web.Response(status=304, headers={
                "ETag": f'"{etag}"',
                "Last-Modified": _http_date(entry.attributes.mtime)})
        if cond == 412:
            raise S3Error("PreconditionFailed",
                          "At least one of the pre-conditions you "
                          "specified did not hold", 412)
        headers = {"ETag": f'"{etag}"', "Accept-Ranges": "bytes",
                   "Last-Modified": _http_date(entry.attributes.mtime),
                   "Content-Type": entry.attributes.mime or
                   "application/octet-stream"}
        for k, v in entry.extended.items():
            if k.startswith("x-amz-meta-"):
                headers[k] = v.decode()
        # response header overrides (s3tests test_object_response_headers:
        # GetObject response-* query params rewrite the reply headers) —
        # honored only on authenticated (signed) requests; real S3 answers
        # InvalidRequest when an anonymous GET carries any response-*
        # parameter, and an unsigned request here never gets an identity
        wanted = [(qparam, hname, request.query[qparam])
                  for qparam, hname in
                  (("response-content-type", "Content-Type"),
                   ("response-content-language", "Content-Language"),
                   ("response-expires", "Expires"),
                   ("response-cache-control", "Cache-Control"),
                   ("response-content-disposition", "Content-Disposition"),
                   ("response-content-encoding", "Content-Encoding"))
                  if request.query.get(qparam)]
        if wanted:
            if request.get("s3_identity") is None:
                raise S3Error(
                    "InvalidRequest",
                    "Request specific response headers cannot be used "
                    "for anonymous GET requests.", 400)
            for _qparam, hname, v in wanted:
                headers[hname] = v
        rng = request.http_range
        has_range = rng.start is not None or rng.stop is not None
        offset = rng.start or 0
        if offset < 0:
            offset, stop = max(0, fsize + offset), fsize
        else:
            stop = min(rng.stop if rng.stop is not None else fsize, fsize)
        if (offset > 0 and offset >= fsize) or (has_range and fsize == 0):
            # any Range on an empty object is unsatisfiable (s3tests
            # test_ranged_request_empty_object expects 416)
            raise S3Error("InvalidRange",
                          "The requested range is not satisfiable", 416)
        status = 200 if (offset == 0 and stop >= fsize) else 206
        if status == 206:
            headers["Content-Range"] = f"bytes {offset}-{stop - 1}/{fsize}"
        if request.method == "HEAD":
            headers["Content-Length"] = str(fsize)
            return web.Response(status=200, headers=headers)
        length = stop - offset
        if not hasattr(self.fs, "stream_entry") or not entry.chunks \
                or length <= getattr(self.fs, "chunk_size", 4 << 20):
            data = self.fs.read_entry_bytes(entry, offset, length)
            return web.Response(body=data, status=status, headers=headers)
        # large objects stream window-by-window through the filer's read
        # fan-out: a 1 GB GET never materializes 1 GB in the gateway.
        # CORS lands pre-prepare — a StreamResponse's headers are on the
        # wire before dispatch() gets the response back
        if request.headers.get("Origin") and self.allowed_origins:
            headers.setdefault("Access-Control-Allow-Origin",
                               self.allowed_origins)
            headers.setdefault("Access-Control-Expose-Headers", "*")
        return await self.fs.stream_entry(request, entry, offset, length,
                                          status, headers)

    def delete_object(self, bucket, key):
        from aiohttp import web

        self._require_bucket(bucket)
        d, n = split_path(self._object_path(bucket, key))
        try:
            self.fs.filer.delete_entry(d, n, is_delete_data=True,
                                       is_recursive=True)
        except FileNotFoundError:
            pass
        return web.Response(status=204)

    def delete_multiple_objects(self, bucket, body):
        self._require_bucket(bucket)
        req = ET.fromstring(body)
        ns = _ns(req)
        quiet = (req.findtext(f"{ns}Quiet") or "false") == "true"
        root = ET.Element("DeleteResult")
        for obj in req.findall(f"{ns}Object"):
            key = obj.findtext(f"{ns}Key") or ""
            d, n = split_path(self._object_path(bucket, key))
            try:
                self.fs.filer.delete_entry(d, n, is_delete_data=True,
                                           is_recursive=True)
                if not quiet:
                    deleted = ET.SubElement(root, "Deleted")
                    ET.SubElement(deleted, "Key").text = key
            except Exception as e:  # noqa: BLE001
                err = ET.SubElement(root, "Error")
                ET.SubElement(err, "Key").text = key
                ET.SubElement(err, "Message").text = str(e)
        return _xml_response(root)

    # -- listing -------------------------------------------------------------
    def _level_entries(self, directory: str, hide_uploads: bool):
        """Entries of one dir sorted by S3 *key* order: a subtree's keys all
        start with '<name>/', so ordering siblings by name+'/' for dirs and
        name for files yields global lexicographic key order (e.g. file
        'b.txt' sorts before dir 'b' because 'b.txt' < 'b/')."""
        entries = [e for e in self.fs.filer.list_entries(directory)
                   if not (hide_uploads and e.name == UPLOADS_DIR)]
        entries.sort(key=lambda e: e.name + "/" if e.is_directory else e.name)
        return entries

    def _walk_keys(self, base: str, rel: str, marker: str, prefix: str):
        """Yield (key, entry) recursively in lexicographic key order,
        pruning subtrees outside prefix/marker and skipping the multipart
        staging dir."""
        directory = join_path(base, rel.rstrip("/")) if rel else base
        for e in self._level_entries(directory, hide_uploads=not rel):
            key = f"{rel}{e.name}"
            if e.is_directory:
                sub = key + "/"
                if marker >= sub + HIGH:
                    continue  # entire subtree <= marker
                if not (prefix.startswith(sub) or sub.startswith(prefix)):
                    continue  # subtree cannot contain prefix keys
                yield from self._walk_keys(base, sub, marker, prefix)
            elif key > marker and key.startswith(prefix):
                yield key, e
            elif key > prefix + HIGH:
                return  # past the prefix range entirely

    def list_objects(self, bucket, q):
        self._require_bucket(bucket)
        prefix = q.get("prefix", "")
        delimiter = q.get("delimiter", "")
        max_keys = int(q.get("max-keys", "1000"))
        v2 = q.get("list-type") == "2"
        marker = q.get("continuation-token", "") if v2 else q.get("marker", "")
        if v2 and not marker:
            # a continuation token always wins over start-after (s3tests
            # test_bucket_listv2_both_continuationtoken_startafter)
            marker = q.get("start-after", "")
        base = self._bucket_dir(bucket)

        contents: list[tuple[str, fpb.Entry]] = []
        prefixes: list[str] = []
        truncated = False
        if max_keys <= 0:
            # s3tests test_bucket_listv2_maxkeys_zero: empty result,
            # NOT truncated
            return self._list_response(bucket, q, prefix, delimiter, 0,
                                       v2, [], [], False)
        if delimiter and delimiter != "/":
            # generic delimiter (s3tests test_bucket_listv2_delimiter_alt):
            # flatten the recursive walk, roll keys up at the first
            # delimiter occurrence after the prefix
            seen_p: set[str] = set()
            # marker pruning is safe: a rollup is a prefix of its key, so
            # any key <= marker would be dropped by the checks below anyway
            for key, e in self._walk_keys(base, "", marker, prefix):
                idx = key.find(delimiter, len(prefix))
                rollup = key[:idx + len(delimiter)] if idx >= 0 else None
                if rollup is not None:
                    if rollup in seen_p or rollup <= marker:
                        continue
                    if len(contents) + len(prefixes) >= max_keys:
                        truncated = True
                        break
                    seen_p.add(rollup)
                    prefixes.append(rollup)
                else:
                    if key <= marker:
                        continue
                    if len(contents) + len(prefixes) >= max_keys:
                        truncated = True
                        break
                    contents.append((key, e))
            return self._list_response(bucket, q, prefix, delimiter,
                                       max_keys, v2, contents, prefixes,
                                       truncated)
        if delimiter:
            # list the dir named by the prefix; subdirs become CommonPrefixes
            pdir, pname = prefix.rpartition("/")[0], prefix.rpartition("/")[2]
            directory = join_path(base, pdir)
            rel = f"{pdir}/" if pdir else ""
            seen = 0
            for e in self._level_entries(directory, hide_uploads=not rel):
                if pname and not e.name.startswith(pname):
                    continue
                key = f"{rel}{e.name}"
                ck = key + "/" if e.is_directory else key
                if ck <= marker:  # a dir's ck <= any marker inside its subtree
                    continue
                if seen >= max_keys:
                    truncated = True
                    break
                if e.is_directory:
                    prefixes.append(ck)
                else:
                    contents.append((key, e))
                seen += 1
        else:
            for key, e in self._walk_keys(base, "", marker, prefix):
                if len(contents) >= max_keys:
                    truncated = True
                    break
                contents.append((key, e))
        return self._list_response(bucket, q, prefix, delimiter, max_keys,
                                   v2, contents, prefixes, truncated)

    def _list_response(self, bucket, q, prefix, delimiter, max_keys, v2,
                       contents, prefixes, truncated):
        # s3tests test_bucket_listv2_encoding_basic: encoding-type=url
        # percent-encodes keys/prefixes in the XML
        url_encode = q.get("encoding-type") == "url"

        def enc(s: str) -> str:
            return urllib.parse.quote(s, safe="/") if url_encode else s

        root = ET.Element("ListBucketResult",
                          xmlns="http://s3.amazonaws.com/doc/2006-03-01/")
        ET.SubElement(root, "Name").text = bucket
        ET.SubElement(root, "Prefix").text = enc(prefix)
        ET.SubElement(root, "MaxKeys").text = str(max_keys)
        ET.SubElement(root, "IsTruncated").text = "true" if truncated else "false"
        if url_encode:
            ET.SubElement(root, "EncodingType").text = "url"
        if delimiter:
            ET.SubElement(root, "Delimiter").text = enc(delimiter)
        last = ""
        for key, e in contents:
            c = ET.SubElement(root, "Contents")
            ET.SubElement(c, "Key").text = enc(key)
            ET.SubElement(c, "LastModified").text = _iso(e.attributes.mtime)
            ET.SubElement(c, "ETag").text = f'"{_entry_etag(e)}"'
            ET.SubElement(c, "Size").text = str(e.attributes.file_size)
            ET.SubElement(c, "StorageClass").text = "STANDARD"
            last = max(last, key)
        for p in prefixes:
            cp = ET.SubElement(root, "CommonPrefixes")
            ET.SubElement(cp, "Prefix").text = enc(p)
            last = max(last, p)
        if v2:
            ET.SubElement(root, "KeyCount").text = \
                str(len(contents) + len(prefixes))
            if truncated:
                # v2 tokens are OPAQUE: SDKs echo them back verbatim
                # without decoding, and list_objects consumes the raw
                # key — so no encoding here even under encoding-type=url
                ET.SubElement(root, "NextContinuationToken").text = last
        elif truncated:
            # v1 NextMarker is a key-valued element: clients DECODE it
            # under encoding-type=url before resending, so encode it like
            # Key/Prefix or the resumed listing skips keys
            ET.SubElement(root, "NextMarker").text = enc(last)
        return _xml_response(root)

    # -- multipart -----------------------------------------------------------
    def _upload_dir(self, bucket: str, upload_id: str) -> str:
        return f"{self._bucket_dir(bucket)}/{UPLOADS_DIR}/{upload_id}"

    def initiate_multipart(self, bucket, key, acl: str | None = None,
                           meta: "dict[str, str] | None" = None):
        self._require_bucket(bucket)
        upload_id = uuid.uuid4().hex
        d, n = split_path(self._upload_dir(bucket, upload_id))
        e = fpb.Entry(name=n, is_directory=True)
        e.extended["key"] = key.encode()
        if acl:
            e.extended["acl"] = acl.encode()
        # x-amz-meta-* from CreateMultipartUpload rides the upload dir and
        # lands on the final object at complete time (boto3's transfer
        # manager sends metadata here, never on the parts)
        for k, v in (meta or {}).items():
            e.extended[k.lower()] = v.encode()
        self.fs.filer.create_entry(d, e)
        root = ET.Element("InitiateMultipartUploadResult")
        ET.SubElement(root, "Bucket").text = bucket
        ET.SubElement(root, "Key").text = key
        ET.SubElement(root, "UploadId").text = upload_id
        return _xml_response(root)

    def _find_upload(self, bucket, upload_id) -> fpb.Entry:
        d, n = split_path(self._upload_dir(bucket, upload_id))
        e = self.fs.filer.find_entry(d, n)
        if e is None:
            raise ErrNoSuchUpload(upload_id)
        return e

    def upload_part(self, bucket, key, q, body):
        self._check_quota(bucket)
        from aiohttp import web

        self._require_bucket(bucket)
        upload_id = q["uploadId"]
        self._find_upload(bucket, upload_id)
        part = int(q["partNumber"])
        path = f"{self._upload_dir(bucket, upload_id)}/{part:05d}.part"
        entry = self.fs.write_file(path, body)
        return web.Response(status=200,
                            headers={"ETag": f'"{entry.attributes.md5.hex()}"'})

    def upload_part_copy(self, bucket, key, q, src, src_range: str,
                         request=None):
        """UploadPartCopy (reference CopyObjectPartHandler,
        s3api_server.go:165): the part's bytes come from an existing
        object, optionally a byte range. Copy is by FileChunk REFERENCE
        at chunk granularity — whole chunks inside the range clone with
        rebased offsets and a refcount bump, only sub-chunk head/tail
        slices move bytes; a part copy out of a huge object moves (at
        most) two chunks of data through the gateway."""
        self._check_quota(bucket)
        self._require_bucket(bucket)
        upload_id = q["uploadId"]
        self._find_upload(bucket, upload_id)
        _sb, _sk, entry = self._resolve_copy_source(src, request)
        size = entry.attributes.file_size or total_size(entry.chunks)
        lo, plen = 0, size
        if src_range:
            m = src_range.removeprefix("bytes=")
            lo_s, _, hi_s = m.partition("-")
            try:
                lo, hi = int(lo_s), int(hi_s)
            except ValueError:
                raise S3Error("InvalidRange",
                              "The requested range is not satisfiable",
                              416)
            if lo > hi or hi >= size:
                raise S3Error("InvalidRange",
                              "The requested range is not satisfiable",
                              416)
            plen = hi - lo + 1
        part = int(q["partNumber"])
        path = f"{self._upload_dir(bucket, upload_id)}/{part:05d}.part"
        if not self._can_copy_by_reference(entry):
            data = self.fs.read_entry_bytes(entry, lo, plen)
            new = self.fs.write_file(path, data)
        else:
            whole = lo == 0 and plen == size
            if whole and not any(c.is_chunk_manifest
                                 for c in entry.chunks):
                chunks = list(entry.chunks)
                adopted = [c.file_id for c in entry.chunks]
            else:
                # complete_multipart rebases part-chunk offsets, which a
                # manifest chunk cannot survive (nested offsets are
                # absolute) — resolve through the range cloner instead
                chunks, adopted = self._clone_chunk_range(entry, lo, plen,
                                                          path)
            if whole and entry.attributes.md5:
                digest = bytes(entry.attributes.md5)
            else:
                # the part's bytes never pass through the gateway, so no
                # content md5 exists; a deterministic surrogate keeps the
                # CopyPartResult ETag, the stored part entry, and the
                # complete-time ETag check mutually consistent
                digest = hashlib.md5(
                    f"{_entry_etag(entry)}:{lo}:{plen}".encode(),
                    usedforsecurity=False).digest()
            try:
                new = self._create_cloned_entry(path, chunks, plen,
                                                digest, "", {}, adopted)
            except BaseException:
                # adopted fids rolled back inside; the data-copied
                # slices are ours to delete
                self._drop_copied_slices(chunks, adopted)
                raise
            if adopted:
                self._verify_copy_source_alive(_sb, _sk, path)
        root = ET.Element("CopyPartResult")
        ET.SubElement(root, "ETag").text = f'"{new.attributes.md5.hex()}"'
        ET.SubElement(root, "LastModified").text = _iso(new.attributes.mtime)
        return _xml_response(root)

    def complete_multipart(self, bucket, key, upload_id, body):
        self._check_quota(bucket)
        self._require_bucket(bucket)
        upload = self._find_upload(bucket, upload_id)
        updir = self._upload_dir(bucket, upload_id)
        req = ET.fromstring(body) if body else None
        wanted: list[int] | None = None
        wanted_etags: dict[int, str] = {}
        if req is not None:
            ns = _ns(req)
            wanted = []
            for p in req.findall(f"{ns}Part"):
                num = int(p.findtext(f"{ns}PartNumber") or "0")
                wanted.append(num)
                et = (p.findtext(f"{ns}ETag") or "").strip().strip('"')
                if et:
                    wanted_etags[num] = et
            if not wanted:
                # s3tests test_multipart_upload_empty
                raise S3Error("MalformedXML",
                              "You must specify at least one part.", 400)
        parts = {int(e.name.split(".")[0]): e
                 for e in self.fs.filer.list_entries(updir)
                 if e.name.endswith(".part")}
        order = sorted(parts) if wanted is None else wanted
        if any(b <= a for a, b in zip(order, order[1:])):
            raise S3Error("InvalidPartOrder",
                          "The list of parts was not in ascending order.", 400)
        if any(p not in parts for p in order):
            raise S3Error("InvalidPart", "One or more of the specified parts "
                          "could not be found.", 400)
        for num, et in wanted_etags.items():
            # s3tests test_multipart_upload_incorrect_etag
            if parts[num].attributes.md5.hex() != et:
                raise S3Error(
                    "InvalidPart", "One or more of the specified parts "
                    "could not be found. The part may not have been "
                    "uploaded, or the specified entity tag may not match "
                    "the part's entity tag.", 400)
        # zero-copy concat: rebase each part's chunks onto the final offset
        final = fpb.Entry()
        offset = 0
        md5s = hashlib.md5(usedforsecurity=False)  # multipart ETag
        for p in order:
            pe = parts[p]
            md5s.update(pe.attributes.md5)
            for c in pe.chunks:
                nc = final.chunks.add()
                nc.CopyFrom(c)
                nc.offset = offset + c.offset
            offset += pe.attributes.file_size
        d, n = split_path(self._object_path(bucket, key))
        final.name = n
        final.attributes.file_size = offset
        final.attributes.mime = "application/octet-stream"
        etag = f"{md5s.hexdigest()}-{len(order)}"
        final.extended["s3-etag"] = etag.encode()
        if upload.extended.get("acl"):
            final.extended["acl"] = upload.extended["acl"]
        for k, v in upload.extended.items():
            # user metadata staged at initiate time lands on the object
            if k.startswith("x-amz-meta-"):
                final.extended[k] = v
        self.fs.filer.create_entry(d, final)
        # drop staging metadata but never the chunks (now owned by `final`)
        pdir, pname = split_path(updir)
        for pe in list(self.fs.filer.list_entries(updir)):
            self.fs.filer.store.delete_entry(updir, pe.name)
        self.fs.filer.store.delete_entry(pdir, pname)
        root = ET.Element("CompleteMultipartUploadResult")
        ET.SubElement(root, "Bucket").text = bucket
        ET.SubElement(root, "Key").text = key
        ET.SubElement(root, "ETag").text = f'"{etag}"'
        return _xml_response(root)

    def abort_multipart(self, bucket, key, upload_id):
        from aiohttp import web

        self._require_bucket(bucket)
        # s3tests test_abort_multipart_upload_not_found: unknown id -> 404
        self._find_upload(bucket, upload_id)
        d, n = split_path(self._upload_dir(bucket, upload_id))
        self.fs.filer.delete_entry(d, n, is_delete_data=True,
                                   is_recursive=True)
        return web.Response(status=204)

    def list_multipart_uploads(self, bucket, q):
        self._require_bucket(bucket)
        root = ET.Element("ListMultipartUploadsResult")
        ET.SubElement(root, "Bucket").text = bucket
        updir = f"{self._bucket_dir(bucket)}/{UPLOADS_DIR}"
        for e in self.fs.filer.list_entries(updir):
            u = ET.SubElement(root, "Upload")
            ET.SubElement(u, "Key").text = e.extended.get("key", b"").decode()
            ET.SubElement(u, "UploadId").text = e.name
            ET.SubElement(u, "Initiated").text = _iso(e.attributes.crtime)
        return _xml_response(root)

    def list_parts(self, bucket, key, q):
        self._require_bucket(bucket)
        upload_id = q["uploadId"]
        self._find_upload(bucket, upload_id)
        root = ET.Element("ListPartsResult")
        ET.SubElement(root, "Bucket").text = bucket
        ET.SubElement(root, "Key").text = key
        ET.SubElement(root, "UploadId").text = upload_id
        updir = self._upload_dir(bucket, upload_id)
        for e in self.fs.filer.list_entries(updir):
            if not e.name.endswith(".part"):
                continue
            p = ET.SubElement(root, "Part")
            ET.SubElement(p, "PartNumber").text = str(int(e.name.split(".")[0]))
            ET.SubElement(p, "ETag").text = f'"{e.attributes.md5.hex()}"'
            ET.SubElement(p, "Size").text = str(e.attributes.file_size)
            ET.SubElement(p, "LastModified").text = _iso(e.attributes.mtime)
        return _xml_response(root)

    # -- tagging -------------------------------------------------------------
    def _find_object(self, bucket, key) -> tuple[str, str, fpb.Entry]:
        d, n = split_path(self._object_path(bucket, key))
        e = self.fs.filer.find_entry(d, n)
        if e is None:
            raise ErrNoSuchKey(key)
        return d, n, e

    def put_object_tagging(self, bucket, key, body):
        from aiohttp import web

        self._require_bucket(bucket)
        d, n, e = self._find_object(bucket, key)
        req = ET.fromstring(body)
        ns = _ns(req)
        for k in [k for k in e.extended if k.startswith(TAG_PREFIX)]:
            del e.extended[k]
        for tag in req.iter(f"{ns}Tag"):
            tk = tag.findtext(f"{ns}Key") or ""
            tv = tag.findtext(f"{ns}Value") or ""
            e.extended[TAG_PREFIX + tk] = tv.encode()
        self.fs.filer.update_entry(d, e)  # publishes a meta-log event
        return web.Response(status=200)

    def get_object_tagging(self, bucket, key):
        self._require_bucket(bucket)
        _, _, e = self._find_object(bucket, key)
        root = ET.Element("Tagging")
        tags = ET.SubElement(root, "TagSet")
        for k, v in sorted(e.extended.items()):
            if k.startswith(TAG_PREFIX):
                t = ET.SubElement(tags, "Tag")
                ET.SubElement(t, "Key").text = k[len(TAG_PREFIX):]
                ET.SubElement(t, "Value").text = v.decode()
        return _xml_response(root)

    def delete_object_tagging(self, bucket, key):
        from aiohttp import web

        self._require_bucket(bucket)
        d, n, e = self._find_object(bucket, key)
        for k in [k for k in e.extended if k.startswith(TAG_PREFIX)]:
            del e.extended[k]
        self.fs.filer.update_entry(d, e)  # publishes a meta-log event
        return web.Response(status=204)


# -- helpers -----------------------------------------------------------------

def _user_meta(headers) -> "dict[str, str]":
    """x-amz-meta-* user metadata from request headers (case folded)."""
    return {k.lower(): v for k, v in headers.items()
            if k.lower().startswith("x-amz-meta-")}


def _parse_http_date(value: str) -> "int | None":
    import email.utils
    try:
        return int(email.utils.parsedate_to_datetime(value).timestamp())
    except (TypeError, ValueError):
        return None


def _check_preconditions(headers, etag: str, mtime: int,
                         prefix: str = "") -> "int | None":
    """RFC 7232 / S3 conditional semantics -> None (proceed), 304, or 412.

    prefix='' evaluates GET/HEAD If-* headers; 'x-amz-copy-source-if-'
    style prefixes evaluate CopyObject's source conditions (which answer
    412 instead of 304 for the not-modified cases, per S3)."""
    def h(name):
        # aiohttp headers are case-insensitive; internal callers pass {}.
        # Present-but-empty must stay distinct from absent.
        return headers.get(prefix + name)

    def etag_matches(spec: str) -> bool:
        cands = [c.strip().strip('"') for c in spec.split(",")]
        return "*" in spec or etag in cands

    if_match = h("if-match")
    if if_match is not None and not etag_matches(if_match):
        return 412
    if_unmod = h("if-unmodified-since")
    if if_unmod is not None and if_match is None:
        ts = _parse_http_date(if_unmod)
        if ts is not None and mtime > ts:
            return 412
    if_none = h("if-none-match")
    if if_none is not None and etag_matches(if_none):
        return 412 if prefix else 304
    if_mod = h("if-modified-since")
    if if_mod is not None and if_none is None:
        ts = _parse_http_date(if_mod)
        if ts is not None and mtime <= ts:
            return 412 if prefix else 304
    return None


def _entry_etag(e: fpb.Entry) -> str:
    s3etag = e.extended.get("s3-etag")
    if s3etag:
        return s3etag.decode()
    return e.attributes.md5.hex() if e.attributes.md5 else ""


def _iso(ts: int) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%S.000Z", time.gmtime(ts or 0))


def _http_date(ts: int) -> str:
    return time.strftime("%a, %d %b %Y %H:%M:%S GMT", time.gmtime(ts or 0))


def _ns(elem: ET.Element) -> str:
    return elem.tag.split("}")[0] + "}" if "}" in elem.tag else ""


def _xml_response(root: ET.Element, status: int = 200):
    from aiohttp import web

    body = b'<?xml version="1.0" encoding="UTF-8"?>\n' + ET.tostring(root)
    return web.Response(body=body, status=status,
                        content_type="application/xml")


def _error_response(e: S3Error, resource: str):
    root = ET.Element("Error")
    ET.SubElement(root, "Code").text = e.code
    ET.SubElement(root, "Message").text = e.message
    ET.SubElement(root, "Resource").text = resource
    resp = _xml_response(root, e.status)
    if e.status == 503:
        # SlowDown answers carry Retry-After (the qos scheduler's
        # bucket ETA when admission refused; 1s for plain breaker
        # trips) so SDK backoff has a server-provided hint
        resp.headers["Retry-After"] = str(
            getattr(e, "retry_after_s", None) or 1)
    return resp
