"""Security: JWT write/read authz and access guard.

TPU-native re-design of the reference's weed/security package
(jwt.go:30 GenJwtForVolumeServer, guard.go:42 Guard). Masters mint an
HS256 JWT scoped to a single file id on Assign; volume servers verify it
before accepting writes (and optionally reads). The guard also supports an
IP white list and basic auth, checked in that order (guard.go:27-28).
"""

from .jwt import (
    gen_jwt_for_volume_server,
    gen_jwt_for_fid_range,
    gen_jwt_for_filer_server,
    decode_jwt,
    jwt_from_request,
    parse_range_claim,
    range_covers_fid,
    JwtError,
)
from .guard import Guard

__all__ = [
    "gen_jwt_for_volume_server",
    "gen_jwt_for_fid_range",
    "gen_jwt_for_filer_server",
    "decode_jwt",
    "jwt_from_request",
    "parse_range_claim",
    "range_covers_fid",
    "JwtError",
    "Guard",
]
