"""Per-chunk encryption at rest (reference weed/util/cipher.go).

Each chunk gets a random AES-256-GCM key stored in its FileChunk.cipher_key
metadata (never on the volume server, which only ever sees ciphertext); the
nonce rides in front of the ciphertext. Matches the reference's model: the
filer namespace is trusted, the blob plane is not.
"""

from __future__ import annotations

import os

from cryptography.hazmat.primitives.ciphers.aead import AESGCM

NONCE_SIZE = 12
KEY_SIZE = 32


def encrypt(data: bytes) -> tuple[bytes, bytes]:
    """-> (nonce || ciphertext+tag, key)."""
    key = os.urandom(KEY_SIZE)
    nonce = os.urandom(NONCE_SIZE)
    sealed = AESGCM(key).encrypt(nonce, data, None)
    return nonce + sealed, key


def decrypt(blob: bytes, key: bytes) -> bytes:
    if len(blob) < NONCE_SIZE:
        raise ValueError("cipher blob too short")
    return AESGCM(bytes(key)).decrypt(bytes(blob[:NONCE_SIZE]),
                                      bytes(blob[NONCE_SIZE:]), None)
