"""Access guard: white list -> basic auth -> JWT, in that order.

Reference: weed/security/guard.go:42 (Guard), :55 (NewGuard), and the
volume server's write-path JWT check (weed/server/volume_server.go guard
wiring; volume_server_handlers_write.go). A guard with no white list, no
credentials, and no signing keys allows everything — security is opt-in,
matching the reference's default `security.toml` (all keys empty).
"""

from __future__ import annotations

import base64
import ipaddress

from . import jwt as _jwt


class Guard:
    def __init__(self,
                 white_list: list[str] | None = None,
                 signing_key: str = "",
                 expires_after_sec: int = 10,
                 read_signing_key: str = "",
                 read_expires_after_sec: int = 60,
                 username: str = "",
                 password: str = ""):
        self.white_list = list(white_list or [])
        self.signing_key = signing_key
        self.expires_after_sec = expires_after_sec
        self.read_signing_key = read_signing_key
        self.read_expires_after_sec = read_expires_after_sec
        self.username = username
        self.password = password

    # -- policy flags --------------------------------------------------

    @property
    def is_write_active(self) -> bool:
        return bool(self.white_list) or bool(self.signing_key)

    @property
    def is_read_active(self) -> bool:
        return bool(self.read_signing_key)

    # -- checks --------------------------------------------------------

    def white_listed(self, remote_ip: str) -> bool:
        if not self.white_list:
            return False
        try:
            ip = ipaddress.ip_address(remote_ip)
        except ValueError:
            return False
        for item in self.white_list:
            try:
                if "/" in item:
                    if ip in ipaddress.ip_network(item, strict=False):
                        return True
                elif ip == ipaddress.ip_address(item):
                    return True
            except ValueError:
                continue
        return False

    def basic_auth_ok(self, headers) -> bool:
        if not self.username:
            return False
        auth = headers.get("Authorization", "") or headers.get("authorization", "")
        if not auth.startswith("Basic "):
            return False
        try:
            user, _, pw = base64.b64decode(auth[6:]).decode().partition(":")
        except Exception:
            return False
        return user == self.username and pw == self.password

    def _admit_write(self, remote_ip: str, query: dict, headers,
                     ) -> "tuple[bool | None, str, dict]":
        """Shared write-admission preamble (guard.go:27-28 ordering:
        write-active, whitelist, basic auth, then jwt). Returns
        (decision, reason, claims): decision True/False is final;
        None means 'token decoded OK — caller applies its scope check
        on claims'."""
        if not self.is_write_active:
            return True, "", {}
        if self.white_listed(remote_ip):
            return True, "", {}
        if self.basic_auth_ok(headers):
            return True, "", {}
        if not self.signing_key:
            return False, "not in white list", {}
        token = _jwt.jwt_from_request(query, headers)
        if not token:
            return False, "missing jwt", {}
        try:
            return None, "", _jwt.decode_jwt(token, self.signing_key)
        except _jwt.JwtError as e:
            return False, str(e), {}

    def check_write(self, remote_ip: str, query: dict, headers,
                    fid: str = "") -> tuple[bool, str]:
        """Gate a mutating request. Returns (allowed, reason)."""
        decision, why, claims = self._admit_write(remote_ip, query, headers)
        if decision is not None:
            return decision, why
        # The master scopes write tokens to one file id (jwt.go:18-21)
        # and the volume server demands an EXACT match
        # (volume_server_handlers.go:199) — an empty claimed fid must
        # NOT act as a wildcard on fid-scoped checks, else any
        # filer-style token doubles as a write-everything pass. A
        # range token (fid-range lease, jwt.py gen_jwt_for_fid_range)
        # is accepted for any fid INSIDE its leased range, so leased
        # clients can also issue plain per-needle PUTs.
        if fid and "rng" in claims:
            if _jwt.range_covers_fid(claims, fid):
                return True, ""
            return False, "jwt fid outside leased range"
        claimed = claims.get("fid", "")
        if fid and claimed != fid:
            return False, "jwt fid mismatch"
        return True, ""

    def check_bulk(self, remote_ip: str, query: dict, headers, vid: int,
                   keys, cookie: int) -> tuple[bool, str]:
        """Gate one bulk-PUT frame with a SINGLE token validation: the
        range token must cover every needle key in the frame (all share
        one cookie by lease construction). Admission ordering is
        check_write's, via the shared preamble."""
        decision, why, claims = self._admit_write(remote_ip, query, headers)
        if decision is not None:
            return decision, why
        rng = _jwt.parse_range_claim(claims)
        if rng is None:
            return False, "bulk write requires a range jwt"
        r_vid, r_start, r_count, r_cookie = rng
        if r_vid != vid:
            return False, "jwt vid mismatch"
        if r_cookie != cookie:
            return False, "jwt cookie mismatch"
        lo, hi = min(keys), max(keys)
        if lo < r_start or hi >= r_start + r_count:
            return False, "jwt fid outside leased range"
        return True, ""

    def check_read(self, remote_ip: str, query: dict, headers,
                   fid: str = "") -> tuple[bool, str]:
        if not self.is_read_active:
            return True, ""
        if self.white_listed(remote_ip):
            return True, ""
        token = _jwt.jwt_from_request(query, headers)
        if not token:
            return False, "missing jwt"
        try:
            claims = _jwt.decode_jwt(token, self.read_signing_key)
        except _jwt.JwtError as e:
            return False, str(e)
        claimed = claims.get("fid", "")
        if fid and claimed != fid:
            return False, "jwt fid mismatch"
        return True, ""

    def check_ip(self, remote_ip: str) -> tuple[bool, str]:
        """IP-whitelist-only gate for non-mutating endpoints (the reference
        applies just guard.WhiteList to master HTTP handlers)."""
        if not self.white_list:
            return True, ""
        if self.white_listed(remote_ip):
            return True, ""
        return False, "not in white list"
