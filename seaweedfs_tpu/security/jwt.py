"""Minimal HS256 JSON Web Tokens (stdlib only).

Mirrors the behavior of reference weed/security/jwt.go: the master signs
`SeaweedFileIdClaims{fid}` (jwt.go:18-21) with an optional `exp`; filer
tokens carry only registered claims (jwt.go:26-28). Token extraction order
matches jwt.go:76-99: `jwt` query param, then `Authorization: Bearer`,
then a `jwt` cookie.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time


class JwtError(Exception):
    pass


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode("ascii")


def _unb64url(s: str) -> bytes:
    pad = "=" * (-len(s) % 4)
    return base64.urlsafe_b64decode(s + pad)


_HEADER = _b64url(json.dumps({"alg": "HS256", "typ": "JWT"},
                             separators=(",", ":")).encode())


def encode(claims: dict, key: bytes | str) -> str:
    if isinstance(key, str):
        key = key.encode()
    payload = _b64url(json.dumps(claims, separators=(",", ":"),
                                 sort_keys=True).encode())
    signing_input = f"{_HEADER}.{payload}".encode("ascii")
    sig = hmac.new(key, signing_input, hashlib.sha256).digest()
    return f"{_HEADER}.{payload}.{_b64url(sig)}"


def decode_jwt(token: str, key: bytes | str, *, now: float | None = None) -> dict:
    """Verify signature + time claims; returns the claims dict."""
    if isinstance(key, str):
        key = key.encode()
    parts = token.split(".")
    if len(parts) != 3:
        raise JwtError("malformed token")
    header_b64, payload_b64, sig_b64 = parts
    try:
        header = json.loads(_unb64url(header_b64))
        payload = json.loads(_unb64url(payload_b64))
        sig = _unb64url(sig_b64)
    except Exception as e:
        raise JwtError(f"bad encoding: {e}") from e
    if header.get("alg") != "HS256":
        raise JwtError(f"unexpected alg {header.get('alg')!r}")
    expect = hmac.new(key, f"{header_b64}.{payload_b64}".encode("ascii"),
                      hashlib.sha256).digest()
    if not hmac.compare_digest(sig, expect):
        raise JwtError("signature mismatch")
    t = time.time() if now is None else now
    if "exp" in payload and t > float(payload["exp"]):
        raise JwtError("token expired")
    if "nbf" in payload and t < float(payload["nbf"]):
        raise JwtError("token not yet valid")
    return payload


def gen_jwt_for_volume_server(signing_key: str | bytes,
                              expires_after_sec: int, file_id: str) -> str:
    """Single-file write token, minted by the master on Assign
    (reference jwt.go:30 GenJwtForVolumeServer). Empty key -> empty token."""
    if not signing_key:
        return ""
    claims: dict = {"fid": file_id}
    if expires_after_sec > 0:
        claims["exp"] = int(time.time()) + expires_after_sec
    return encode(claims, signing_key)


def gen_jwt_for_fid_range(signing_key: str | bytes,
                          expires_after_sec: int, vid: int,
                          start_key: int, count: int, cookie: int) -> str:
    """Range-scoped write token for a fid-range lease (TPU extension;
    the reference's Assign(count=N) still mints a single-fid token,
    master_grpc_server_assign.go). One signature covers the whole leased
    key range [start_key, start_key+count) on `vid`, so a bulk client
    can write N needles without N master-minted tokens. Claim layout:
    `rng = "<vid>,<start_hex>,<count>,<cookie_hex>"` — hex keys avoid
    any JSON big-int precision questions for snowflake-sized keys."""
    if not signing_key:
        return ""
    claims: dict = {"rng": f"{vid},{start_key:x},{count},{cookie:08x}"}
    if expires_after_sec > 0:
        claims["exp"] = int(time.time()) + expires_after_sec
    return encode(claims, signing_key)


def parse_range_claim(claims: dict) -> "tuple[int, int, int, int] | None":
    """(vid, start_key, count, cookie) from a range token's claims, or
    None when the token carries no (or a malformed) `rng` claim."""
    rng = claims.get("rng", "")
    if not rng:
        return None
    try:
        vid_s, start_s, count_s, cookie_s = rng.split(",")
        return int(vid_s), int(start_s, 16), int(count_s), int(cookie_s, 16)
    except ValueError:
        return None


def range_covers_fid(claims: dict, fid: str) -> bool:
    """True when the token's leased range covers `fid` (vid, key within
    [start, start+count), cookie equal)."""
    rng = parse_range_claim(claims)
    if rng is None:
        return False
    vid, start, count, cookie = rng
    # one fid grammar for the whole tree (lazy: keep this module
    # importable without the storage package on the path)
    from ..storage.types import parse_file_id
    try:
        f_vid, f_key, f_cookie = parse_file_id(fid)
    except ValueError:
        return False
    return (f_vid == vid and f_cookie == cookie
            and start <= f_key < start + count)


def peek_claims(token: str) -> dict:
    """UNVERIFIED claims decode — for a client reading its OWN token's
    exp/rng (e.g. deriving a lease TTL from the range JWT the master
    minted when the transport carried no TTL field). Never use for
    authorization: the signature is not checked."""
    try:
        return json.loads(_unb64url(token.split(".")[1]))
    except Exception:  # noqa: BLE001 — opaque/foreign token: no claims
        return {}


def gen_jwt_for_filer_server(signing_key: str | bytes,
                             expires_after_sec: int) -> str:
    """Filer-API token used by gateways (jwt.go:53 GenJwtForFilerServer)."""
    if not signing_key:
        return ""
    claims: dict = {}
    if expires_after_sec > 0:
        claims["exp"] = int(time.time()) + expires_after_sec
    return encode(claims, signing_key)


def derive_cluster_key(signing_key: str) -> str:
    """Derive the gRPC-plane bearer key from the HTTP signing key, so a
    cluster token sniffed off plaintext gRPC metadata can never validate
    as a volume-server write/read JWT (the reference keeps the planes
    apart with a distinct filer key + mTLS, security/tls.go:26)."""
    if not signing_key:
        return ""
    return hmac.new(signing_key.encode(), b"swtpu-grpc-cluster-v1",
                    hashlib.sha256).hexdigest()


def jwt_from_request(query: dict, headers) -> str:
    """Extract a token the way jwt.go:76-99 does: query param, bearer
    header, cookie. `query` is a mapping; `headers` any mapping with .get."""
    tok = query.get("jwt", "")
    if tok:
        return tok
    bearer = headers.get("Authorization", "") or headers.get("authorization", "")
    if bearer.startswith("Bearer ") or bearer.startswith("BEARER "):
        return bearer[7:].strip()
    cookie = headers.get("Cookie", "") or headers.get("cookie", "")
    for part in cookie.split(";"):
        k, _, v = part.strip().partition("=")
        if k == "jwt":
            return v
    return ""
