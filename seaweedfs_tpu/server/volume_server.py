"""Volume server daemon: HTTP data path + gRPC admin/EC + master heartbeat.

Reference: weed/server/volume_server.go, volume_server_handlers_write.go:18
(PostHandler -> ReplicatedWrite), volume_server_handlers_read.go:44,
volume_grpc_client_to_master.go:50 (heartbeat loop),
volume_grpc_erasure_coding.go (EC RPC set incl. fork CopyByRebuild/Move),
topology/store_replicate.go:25 (synchronous replica fan-out).
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.parse

from ..ec import files as ec_files
from ..ec.encoder import rebuild_shards
from ..ec.locate import EcGeometry
from ..pb import master_pb2 as mpb
from ..pb import volume_server_pb2 as vpb
from ..storage.needle import Needle
from ..storage.store import Store
from ..storage.types import TTL, parse_file_id
from ..storage.vacuum import commit_compact, compact
from ..telemetry.hot import record as hot_record
from ..utils import failpoints, fsutil, retry
from ..utils.log import logger
from ..utils.rpc import MASTER_SERVICE, RpcService, Stub, VOLUME_SERVICE, serve

log = logger("volume")


def _observe_stages(kind: str, t_recv: float, t_parsed: float, t0: float,
                    t_admit, t_done, t_end: float) -> dict:
    """Per-stage timing for the protocol-ceiling teardown (BENCH_r05:
    93-139 us of protocol per hop): contiguous perf_counter segments
    recv/parse (first wire byte -> request parsed), queue_wait (parsed
    -> handler entry: drain-queue + event-loop queueing, the split that
    de-confounds the old queueing-inflated recv_parse number),
    auth/admit (QoS admission), store (the storage handler itself, jwt
    check included) and serialize/flush (response build + accounting).
    The five sums cover the full wire-to-wire interval, so per-type
    stage totals account for >= 100% of VOLUME_REQUEST_SECONDS.
    t_admit/t_done may be None on shed/error paths (stage collapses to
    zero and the tail lands in serialize_flush). Returns the stage dict
    so the flight recorder can reuse it without re-deriving."""
    from ..stats import VOLUME_STAGE_SECONDS
    a = t_admit if t_admit is not None else t0
    d = t_done if t_done is not None else a
    r = t_recv or t_parsed or t0
    p = t_parsed or r
    stages = {
        "recv_parse": max(0.0, p - r),
        "queue_wait": max(0.0, t0 - p),
        "auth_admit": max(0.0, a - t0),
        "store": max(0.0, d - a),
        "serialize_flush": max(0.0, t_end - d),
    }
    for stage, v in stages.items():
        VOLUME_STAGE_SECONDS.observe(kind, stage, value=v)
    return stages


def _vid_of_path(path: str) -> "str | None":
    head = path.lstrip("/").split(",", 1)[0]
    return head if head.isdigit() else None


def _maintenance_tagged(fn):
    """Tag a gRPC handler's whole execution maintenance-class: these
    RPCs exist ONLY as repair/replication/rebalance machinery, so their
    nested reads (ranged survivor fetches, CopyFile pulls from peers)
    inherit the tag and yield to foreground work wherever they land —
    even when an operator drives them by hand from the shell."""
    import functools

    from .. import qos as qos_mod

    @functools.wraps(fn)
    def wrapped(req, context):
        with qos_mod.tagged(qos_mod.CLASS_MAINTENANCE):
            return fn(req, context)
    return wrapped


def _ec_stage_fields(stats: dict) -> dict:
    """ec.encode.finish event fields from an encode pipeline stats dict:
    the fill/dispatch/drain/write stage split plus the overlap fraction, so
    /debug/events shows WHERE an encode spent its wall time without pulling
    the trace."""
    fields = {}
    for key in ("fill_s", "dispatch_s", "coder_s", "drain_block_s",
                "write_s", "write_block_s", "wall_s"):
        if key in stats:
            fields[key] = round(stats[key], 3)
    for key in ("write_overlap", "writers", "batches", "mode"):
        if key in stats:
            fields[key] = stats[key]
    return fields


class VolumeServer:
    def __init__(self, store: Store, master_address: str,
                 ip: str = "127.0.0.1", port: int = 8080,
                 grpc_port: int | None = None,
                 data_center: str = "", rack: str = "",
                 pulse_seconds: float = 2.0, read_mode: str = "proxy",
                 guard=None, metrics_gateway: str = "",
                 metrics_interval_s: int = 15,
                 qos_policy: "dict | str | None" = None):
        self.store = store
        # optional push-gateway loop (reference -metricsPort push config);
        # started in start(), joined in stop() via the PushLoop handle
        self.metrics_gateway = metrics_gateway
        self.metrics_interval_s = metrics_interval_s
        self._metrics_push = None
        # comma-separated master quorum; heartbeats follow leader hints
        # and rotate through the list on failure (reference
        # volume_grpc_client_to_master.go:28 checkWithMaster)
        self.masters = [m for m in master_address.split(",") if m]
        self.master_address = self.masters[0]
        self._master_rr = 0
        self.current_leader = self.masters[0]
        self.ip = ip
        self.port = port
        self.grpc_port = grpc_port or port + 10000
        self.data_center = data_center
        self.rack = rack
        self.pulse_seconds = pulse_seconds
        self.read_mode = read_mode
        # security.Guard: JWT/white-list gate on mutating HTTP requests
        # (reference guard wiring in weed/server/volume_server.go; the write
        # token is the single-fid JWT the master minted on Assign).
        self.guard = guard
        self._stop = threading.Event()
        self._leave = threading.Event()  # volume.server.leave: stop heartbeats
        self._hb_wake = threading.Event()
        # heartbeat flush bookkeeping: state seq bumps on every mutation
        # trigger; the loop records which seq each SENT snapshot covered and
        # advances _hb_acked_seq when the master's 1:1 response arrives, so
        # flush_heartbeat() can wait for "master has processed my change"
        self._hb_cond = threading.Condition()
        self._hb_state_seq = 0
        self._hb_acked_seq = -1
        self._hb_inflight: "list[int]" = []
        self._grpc = None
        self._http_thread = None
        self._hb_thread = None
        self._hb_active_stream = None
        self._http_runner = None
        # EC shard-location cache (tiers, store_ec.go:256-267) + the
        # degraded-read fan-out pool (store_ec.go:367 goroutine fan-out)
        self._ec_loc_cache: dict[int, tuple[dict, float, bool]] = {}
        self._ec_loc_lock = threading.Lock()
        # geo plane: peer gRPC address -> data center, learned from the
        # master's LookupEcVolume answers (Location.data_center). Keyed
        # by address, not volume — a server's DC never changes within a
        # process lifetime, so single whole-value writes under the GIL
        # need no lock and staleness is not a failure mode.
        self._ec_addr_dc: dict[str, str] = {}
        # replica-set cache for the write fan-out (see _lookup_replicas_cached)
        self._replica_cache: dict[int, tuple[float, list[str]]] = {}
        from ..profiling import LoopLagMonitor, MonitoredPool
        self._ec_read_pool = MonitoredPool(
            "ec_read", max_workers=16,
            thread_name_prefix="ec-degraded-read")
        # read-path data plane: the hot-needle cache (segmented LRU,
        # storage/read_cache.py; SWTPU_READ_CACHE_MB=0 disables) and the
        # pool GET/bulk-GET storage reads run on. With the seqlock read
        # protocol (storage/volume.py) these threads read in PARALLEL —
        # no GET ever queues behind a writer's fsync on the volume lock.
        from ..storage import read_cache as read_cache_mod
        from ..utils.env import env_int
        self.read_cache = read_cache_mod.default_cache()
        # lifecycle heat epoch: read counters live in memory, so this
        # server can only attest "quiet for <= uptime" — the planner
        # uses it as the ceiling for volumes with no recorded read
        self._started_mono = time.monotonic()
        self._read_pool = MonitoredPool(
            "read", max_workers=max(1, env_int("SWTPU_READ_THREADS", 8)),
            thread_name_prefix=f"vs-read-{port}")
        # profiling plane: loop-lag probe (installed on the HTTP loop by
        # serve_fast_app's on_loop hook) + the process-shared continuous
        # sampler (acquired in start(), released in stop())
        self._loop_lag = LoopLagMonitor("volume")
        self._sampler = None
        # multi-tenant QoS plane (qos/): tenant = collection, classes
        # interactive (GET) > ingest (PUT/DELETE) > maintenance (tagged
        # repair/rebuild/copy traffic). A dict is a policy document; a
        # string is a policy FILE hot-reloaded on mtime change
        # (-qosPolicy); None/empty = admission disabled (zero-cost
        # pass-through). Live state at /debug/qos, retune via POST.
        from ..qos import QosScheduler
        self.qos = QosScheduler(name=f"volume-{port}")
        if isinstance(qos_policy, str) and qos_policy:
            self.qos.attach_file(qos_policy)
        elif qos_policy:
            self.qos.load(qos_policy)

    @property
    def url(self) -> str:
        return f"{self.ip}:{self.port}"

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        from ..profiling import acquire_sampler
        self._sampler = acquire_sampler()
        key = self.guard.signing_key if self.guard is not None else ""
        if key:
            from ..utils.rpc import set_cluster_key
            set_cluster_key(key)
        self._grpc = serve(f"{self.ip}:{self.grpc_port}",
                           [self._build_service()], auth_key=key)
        self._http_thread = threading.Thread(target=self._run_http, daemon=True,
                                             name=f"vs-http-{self.port}")
        self._http_thread.start()
        self._hb_thread = threading.Thread(target=self._heartbeat_loop,
                                           daemon=True,
                                           name=f"vs-hb-{self.port}")
        self._hb_thread.start()
        if self.metrics_gateway:
            from ..stats import start_push_loop
            self._metrics_push = start_push_loop(
                self.metrics_gateway, f"volume-{self.url}",
                self.metrics_interval_s)
        log.info("volume server %s up (grpc :%d)", self.url, self.grpc_port)

    def stop(self) -> None:
        if self._stop.is_set():
            return
        self._stop.set()
        self._hb_wake.set()
        # tear the live heartbeat stream so the blocked thread unblocks
        # NOW, then join it — otherwise it outlives the test/daemon and
        # spams "I/O operation on closed file" retrying against a closed
        # store and torn-down logging
        stream = self._hb_active_stream
        if stream is not None:
            try:
                stream.cancel()
            except Exception as e:  # noqa: BLE001
                log.debug("heartbeat stream cancel failed: %s", e)
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2.0)
        if self._metrics_push is not None:
            self._metrics_push.stop()
        if self._grpc:
            self._grpc.stop(grace=0.5)
        self._ec_read_pool.shutdown(wait=False, cancel_futures=True)
        self._read_pool.shutdown(wait=False, cancel_futures=True)
        self._loop_lag.close()
        if self._sampler is not None:
            from ..profiling import release_sampler
            release_sampler()
            self._sampler = None
        self.qos.close()
        if self.read_cache is not None:
            self.read_cache.clear()
        self.store.close()

    # -- heartbeat (reference volume_grpc_client_to_master.go) ---------------
    def _update_gauges(self, hb: dict) -> None:
        """Volume/EC/disk gauges from heartbeat state (reference sets
        VolumeServerDiskSizeGauge from EC heartbeat, store_ec.go:41).
        Label sets seen before but absent now are zeroed, so removed
        volumes/collections don't linger in dashboards."""
        from ..stats import (VOLUME_SERVER_DISK_SIZE_GAUGE,
                             VOLUME_SERVER_EC_SHARD_GAUGE,
                             VOLUME_SERVER_VOLUME_GAUGE)
        per: dict[tuple[str, str], int] = {}
        size: dict[tuple[str, str], int] = {}
        for v in hb["volumes"]:
            key = (v["collection"], v["disk_type"])
            per[key] = per.get(key, 0) + 1
            size[key] = size.get(key, 0) + v["size"]
        ec_per: dict[tuple[str], int] = {}
        for s in hb["ec_shards"]:
            n = bin(s["ec_index_bits"]).count("1")
            key = (s["collection"],)
            ec_per[key] = ec_per.get(key, 0) + n
        for gauge, cur, attr in (
                (VOLUME_SERVER_VOLUME_GAUGE, per, "_g_vol"),
                (VOLUME_SERVER_DISK_SIZE_GAUGE, size, "_g_size"),
                (VOLUME_SERVER_EC_SHARD_GAUGE, ec_per, "_g_ec")):
            prev: set = getattr(self, attr, set())
            for key in prev - set(cur):
                gauge.set(*key, value=0)
            for key, n in cur.items():
                gauge.set(*key, value=n)
            setattr(self, attr, set(cur))

    def _heartbeat_messages(self):
        while not (self._stop.is_set() or self._leave.is_set()):
            try:
                # per-pulse housekeeping (fork store.go:389 reap +
                # ec_volume.go idle-handle close). Reaps are lifecycle
                # transitions (→trash): journaled + metered like every
                # other tier move so the plane's books balance.
                reaped = self.store.delete_expired_ec_volumes()
                if reaped:
                    from ..lifecycle import TIER_TRASH
                    from ..ops import events
                    from ..stats import (LIFECYCLE_BYTES_MOVED,
                                         LIFECYCLE_TRANSITIONS)
                    for rec in reaped:
                        events.emit("lifecycle.transition", kind="reap",
                                    vid=rec["vid"], node=self.url,
                                    collection=rec["collection"],
                                    **{"from": rec["from"],
                                       "to": TIER_TRASH},
                                    bytes_moved=rec["bytes"])
                        LIFECYCLE_TRANSITIONS.inc(rec["from"], TIER_TRASH)
                        LIFECYCLE_BYTES_MOVED.inc(rec["from"], TIER_TRASH,
                                                  amount=rec["bytes"])
                    log.info("reaped expired ec volumes %s",
                             [r["vid"] for r in reaped])
                self.store.close_idle_ec_handles()
            except Exception as e:  # noqa: BLE001
                log.warning("ec housekeeping: %s", e)
            # read the seq BEFORE snapshotting: any mutation that bumped
            # the seq before this point is included in the snapshot, so
            # acking snap_seq proves the master saw those mutations
            snap_seq = self._hb_state_seq
            hb = self.store.collect_heartbeat()
            self._update_gauges(hb)
            msg = mpb.Heartbeat(
                ip=self.ip, port=self.port, grpc_port=self.grpc_port,
                public_url=self.store.public_url,
                max_file_key=hb["max_file_key"],
                data_center=self.data_center, rack=self.rack,
                max_volume_counts=hb["max_volume_counts"],
                has_no_volumes=not hb["volumes"],
                has_no_ec_shards=not hb["ec_shards"])
            for v in hb["volumes"]:
                msg.volumes.add(**v)
            for s in hb["ec_shards"]:
                msg.ec_shards.add(**s)
            # failpoint: a raised error tears the heartbeat stream (the
            # master sees the disconnect and unregisters); delay models a
            # stalled node feeding the failure detector
            failpoints.check("volume.heartbeat")
            with self._hb_cond:
                self._hb_inflight.append(snap_seq)
            yield msg
            self._hb_wake.wait(timeout=self.pulse_seconds)
            self._hb_wake.clear()

    def _heartbeat_loop(self) -> None:
        while not (self._stop.is_set() or self._leave.is_set()):
            try:
                stub = Stub(self.current_leader, MASTER_SERVICE)
                stream = stub.stream_stream(
                    "SendHeartbeat", self._heartbeat_messages(),
                    mpb.Heartbeat, mpb.HeartbeatResponse)
                # kept for stop(): cancelling unblocks this thread so the
                # join in stop() returns promptly
                self._hb_active_stream = stream
                if self._stop.is_set():
                    stream.cancel()
                    return
                for resp in stream:
                    # master answers 1:1 AFTER ingesting each heartbeat:
                    # the oldest in-flight snapshot is now master-visible
                    with self._hb_cond:
                        if self._hb_inflight:
                            self._hb_acked_seq = self._hb_inflight.pop(0)
                            self._hb_cond.notify_all()
                    if resp.volume_size_limit:
                        pass  # informational
                    if resp.leader and resp.leader != self.current_leader:
                        log.info("leader moved to %s", resp.leader)
                        self.current_leader = resp.leader
                        break
                    if self._stop.is_set():
                        return
            except Exception as e:  # noqa: BLE001
                if not self._stop.is_set():
                    log.warning("heartbeat to %s failed: %s; retrying",
                                self.current_leader, e)
                    if len(self.masters) > 1:
                        self._master_rr = ((self._master_rr + 1)
                                           % len(self.masters))
                        self.current_leader = self.masters[self._master_rr]
                    # interruptible wait: a stop() during the retry pause
                    # must not leave a zombie heartbeat thread behind
                    self._stop.wait(min(self.pulse_seconds, 2.0))
            finally:
                with self._hb_cond:
                    # unacked sends died with the stream; the next stream
                    # re-sends full state, so waiters should not count them
                    self._hb_inflight.clear()
                    self._hb_cond.notify_all()

    def trigger_heartbeat(self) -> None:
        with self._hb_cond:
            self._hb_state_seq += 1
        self._hb_wake.set()

    def flush_heartbeat(self, timeout: float = 3.0) -> bool:
        """Block until the master has ingested a heartbeat reflecting every
        state change made before this call (or timeout). Admin RPCs that
        mutate volume/EC registration call this so topology reads anywhere
        in the cluster see the change once the RPC returns — closing the
        assemble-send-ingest race the old fire-and-forget trigger left."""
        if self._stop.is_set() or self._leave.is_set():
            return False  # no heartbeat loop to ack (leave/decommission)
        with self._hb_cond:
            self._hb_state_seq += 1
            target = self._hb_state_seq
        self._hb_wake.set()
        deadline = time.monotonic() + timeout
        with self._hb_cond:
            while self._hb_acked_seq < target:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._stop.is_set() \
                        or self._leave.is_set():
                    return False
                self._hb_cond.wait(min(remaining, 0.25))
        return True

    # -- HTTP data path (utils/fastweb hand-rolled HTTP/1.1) ----------------
    def _flight_record(self, kind: str, request, status: int,
                       stages: dict, sp, t_wire: float,
                       t_end: float) -> None:
        """Offer a finished request to the flight recorder with the
        at-admit context (loop lag, pool queue depths) a postmortem
        needs to tell 'this request was slow' from 'the node was
        drowning'. Below-threshold requests cost two float compares."""
        from ..profiling import record_flight
        record_flight(
            kind, t_end - t_wire, status=status, path=request.path,
            stages=stages,
            qos_class=str(sp.attrs.get("qos_class", "")),
            cache=sp.attrs.get("cache"),
            loop_lag_s=self._loop_lag.last_lag_s,
            queue_depths={"read": self._read_pool.queued(),
                          "ec_read": self._ec_read_pool.queued()},
            node=self.url)

    def _run_http(self) -> None:
        import asyncio

        from ..utils import fastweb
        from ..utils.fastweb import Redirect, json_response

        from ..stats import (VOLUME_REQUEST_COUNTER,
                             VOLUME_REQUEST_SECONDS)

        from .. import tracing

        _kind = {"POST": "post", "PUT": "put", "GET": "get",
                 "HEAD": "head", "DELETE": "delete"}

        async def handle(request: fastweb.Request):
            kind = _kind.get(request.method, "other")
            t0 = time.perf_counter()
            t_admit = t_done = None
            resp = None
            status = 500
            # server span continues the caller's trace (traceparent
            # header) — a PUT's span parents the replication fan-out and
            # a GET's the EC shard fetches; the latency observation runs
            # INSIDE the span so the histogram captures its exemplar
            with tracing.start_span(
                    f"volume.{kind}", component="volume",
                    child_of=tracing.extract(request.headers),
                    attrs={"fid": request.path.lstrip("/"),
                           "server": self.url}) as sp:
                try:
                    # QoS admission: tenant = the fid's collection,
                    # class from the verb unless the hop is tagged
                    # (maintenance repair reads, class-inheriting
                    # replica hops). Reads post-charge their response
                    # bytes; replica hops charge but never shed.
                    grant, qos_token = None, None
                    if self.qos.enabled:
                        from .. import qos as qos_mod
                        is_read = request.method in ("GET", "HEAD")
                        klass = qos_mod.class_from_headers(
                            request.headers,
                            qos_mod.CLASS_INTERACTIVE if is_read
                            else qos_mod.CLASS_INGEST)
                        try:
                            grant = await self.qos.admit(
                                self._qos_tenant_of_path(request.path),
                                klass,
                                cost=len(request.body or b""),
                                no_shed=request.query.get("type")
                                == "replicate")
                        except qos_mod.QosShed as e:
                            status = 503
                            sp.set_attr("qos", "shed")
                            return self._qos_shed_response(e)
                        sp.set_attr("qos_class", klass)
                        # the handler (and its replication fan-out)
                        # inherits the admitted class
                        qos_token = qos_mod.set_class(klass)
                    t_admit = time.perf_counter()
                    try:
                        if request.method in ("POST", "PUT"):
                            resp = await self._handle_write(request)
                        elif request.method in ("GET", "HEAD"):
                            resp = await self._handle_read(request)
                        elif request.method == "DELETE":
                            resp = await self._handle_delete(request)
                        else:
                            resp = json_response(
                                {"error": "method not allowed"}, status=405)
                    except KeyError as e:
                        resp = json_response({"error": str(e)}, status=404)
                    except PermissionError as e:
                        resp = json_response({"error": str(e)}, status=403)
                    except Redirect as e:
                        status = e.status
                        sp.status = "redirect"  # control flow, not a fault
                        raise
                    except Exception as e:  # noqa: BLE001
                        log.error("http error: %s", e)
                        resp = json_response({"error": str(e)}, status=500)
                    t_done = time.perf_counter()
                    status = resp.status
                    if grant is not None and request.method in \
                            ("GET", "HEAD") and resp.body:
                        grant.charge(len(resp.body))
                    return resp
                finally:
                    if qos_token is not None:
                        from .. import qos as qos_mod
                        qos_mod.reset_class(qos_token)
                    if grant is not None:
                        grant.release()
                    sp.set_attr("status", status)
                    if status >= 500:
                        sp.set_error(f"HTTP {status}")
                    t_end = time.perf_counter()
                    VOLUME_REQUEST_COUNTER.inc(kind, str(status))
                    VOLUME_REQUEST_SECONDS.observe(kind, value=t_end - t0)
                    stages = _observe_stages(kind, request.t_recv,
                                             request.t_parsed, t0,
                                             t_admit, t_done, t_end)
                    self._flight_record(f"volume.{kind}", request, status,
                                        stages, sp,
                                        request.t_recv or t0, t_end)
                    # heavy hitters: bytes moved = payload in + body out
                    hot_record(
                        volume=_vid_of_path(request.path),
                        tenant=self._qos_tenant_of_path(request.path),
                        method=kind,
                        nbytes=len(request.body or b"")
                        + (len(resp.body) if resp is not None and resp.body
                           else 0))

        def status(request):
            return json_response({"version": "swtpu", **self.store.status()})

        def metrics(request):
            from ..stats import scrape_payload
            body, ctype = scrape_payload(request.headers.get("Accept", ""))
            return fastweb.Response(body.encode(), content_type=ctype)

        def debug_traces(request):
            return json_response(tracing.debug_traces_payload(request.query))

        def debug_events(request):
            from ..ops import events
            return json_response(events.debug_events_payload(request.query))

        def debug_locks(request):
            from ..utils import locktrack
            return json_response(
                locktrack.debug_locks_payload(request.query))

        def debug_qos(request):
            """GET dumps live scheduler state (buckets, queues, per-
            tenant counters); POST with a JSON policy document hot-
            reloads it (the operator retune path the S3 breaker's
            config reload established); GET ?reload=1 re-reads the
            attached -qosPolicy file immediately. On a guarded cluster
            the MUTATING forms demand write admission (whitelist/basic
            auth/any valid cluster jwt) — a throttled tenant must not
            be able to switch its own throttle off."""
            if (request.method == "POST" or request.query.get("reload")) \
                    and self.guard is not None:
                ok, why = self.guard.check_write(request.remote or "",
                                                 request.query,
                                                 request.headers)
                if not ok:
                    return json_response({"error": why}, status=401)
            if request.method == "POST":
                try:
                    doc = json.loads(request.body or b"{}")
                    self.qos.load(doc)
                except (ValueError, TypeError) as e:
                    return json_response({"error": str(e)}, status=400)
                return json_response({"ok": True,
                                      "enabled": self.qos.enabled})
            if request.query.get("reload"):
                self.qos._reload_file(initial=True)
            return json_response(self.qos.debug_payload())

        def debug_lifecycle(request):
            """GET dumps this server's per-volume heat + tier state —
            the planner's input: read counters and last-read/last-write
            ages from the storage layer (the read-cache hit path feeds
            them too), per-EC-volume local vs offloaded shards, remote
            read counts and DestroyTime. POST stamps a DestroyTime onto
            a local EC volume's .vif ({"volume": N, "destroy_time": T}
            — the lifecycle executor's TTL verb after a policy encode);
            guarded like /debug/qos: a tenant must not be able to
            schedule its own data's reaping."""
            if request.method == "POST":
                if self.guard is not None:
                    ok, why = self.guard.check_write(request.remote or "",
                                                     request.query,
                                                     request.headers)
                    if not ok:
                        return json_response({"error": why}, status=401)
                try:
                    doc = json.loads(request.body or b"{}")
                    vid = int(doc["volume"])
                    at = float(doc["destroy_time"])
                except (KeyError, TypeError, ValueError) as e:
                    return json_response({"error": str(e)}, status=400)
                if not self._set_destroy_time(vid, at):
                    return json_response(
                        {"error": f"no ec volume {vid}"}, status=404)
                return json_response({"ok": True, "volume": vid,
                                      "destroy_time": at})
            return json_response(self._lifecycle_payload())

        def _operator_gate(request):
            """Same gate policy as the master's guarded() debug routes:
            stacks/flight entries leak fids, paths and peer addresses,
            so the IP whitelist applies (this route shipped unguarded
            while master/S3 gated theirs — all four daemons now gate
            identically). Returns an error response, or None."""
            if request.method != "GET":
                return json_response({"error": "method not allowed"},
                                     status=405)
            if self.guard is not None:
                ok, why = self.guard.check_ip(request.remote or "")
                if not ok:
                    return json_response({"error": why}, status=401)
            return None

        async def debug_profile(request):
            import contextvars

            from .. import profiling as prof
            denied = _operator_gate(request)
            if denied is not None:
                return denied
            # shared contract (profiling.handle_profile_query): seconds
            # validation/clamp, continuous/summary modes, hz retune;
            # offloaded — a capture blocks for `seconds`
            loop = asyncio.get_running_loop()
            ctx = contextvars.copy_context()  # keep the trace span
            code, ctype, body = await loop.run_in_executor(
                None, ctx.run, prof.handle_profile_query, request.query)
            return fastweb.Response(body.encode(), status=code,
                                    content_type=ctype)

        def debug_flight(request):
            from .. import profiling as prof
            denied = _operator_gate(request)
            if denied is not None:
                return denied
            code, payload = prof.debug_flight_payload(request.query)
            return json_response(payload, status=code)

        def debug_jax_profiler(request):
            from ..utils import profiling
            port = int(request.query.get("port", "9999"))
            return fastweb.text_response(profiling.start_jax_profiler(port))

        def debug_failpoints(request):
            """GET: list armed failpoints; ?name=X&spec=Y arms/updates one
            at runtime (operator-driven chaos drills). A bare ?name=X
            without spec is a read — it must not disarm mid-drill."""
            name = request.query.get("name")
            spec = request.query.get("spec")
            if name and spec is not None:
                try:
                    failpoints.configure(name, spec)
                except ValueError as e:
                    return fastweb.text_response(f"bad spec: {e}",
                                                 status=400)
            return json_response({"armed": failpoints.active(),
                                  "fired": failpoints.fired_counts()})

        def status_ui(request):
            # human status UI (reference weed/server/volume_server_ui)
            from ..utils.ui import render_page
            st = self.store.status()
            rows = []
            ec_rows = []
            for loc in self.store.locations:
                with loc.lock:  # allocate/mount mutate these dicts
                    vols = sorted(loc.volumes.items())
                    ecs = sorted(loc.ec_volumes.items())
                for vid, v in vols:
                    rows.append([vid, v.collection or "-", loc.disk_type,
                                 f"{v.content_size >> 20} MB",
                                 v.file_count, v.deleted_count,
                                 "ro" if v.read_only else "rw"])
                for vid, ev in ecs:
                    ec_rows.append([vid, ev.collection or "-",
                                    sorted(ev.shards)])
            page = render_page(
                f"swtpu volume server {self.url}",
                {"Master": ", ".join(self.masters),
                 "Volumes": st["volumes"], "EC volumes": len(ec_rows),
                 "Rack": self.rack or "-",
                 "Data center": self.data_center or "-"},
                [("Volumes", ["id", "collection", "disk", "size", "files",
                              "deleted", "mode"], rows),
                 ("EC volumes", ["id", "collection", "shards"], ec_rows)])
            return fastweb.html_response(page)

        async def handle_bulk(request: fastweb.Request):
            # same envelope as the default data-path handler, with its
            # own request kind so dashboards separate bulk frames from
            # per-needle PUTs; the span is the bulk.put root the
            # replication fan-out children hang under
            t0 = time.perf_counter()
            t_admit = t_done = None
            resp = None
            status = 500
            with tracing.start_span(
                    "bulk.put", component="volume",
                    child_of=tracing.extract(request.headers),
                    attrs={"server": self.url,
                           "bytes": len(request.body or b"")}) as sp:
                try:
                    grant, qos_token = None, None
                    if self.qos.enabled:
                        from .. import qos as qos_mod
                        klass = qos_mod.class_from_headers(
                            request.headers, qos_mod.CLASS_INGEST)
                        try:
                            grant = await self.qos.admit(
                                self._qos_tenant_of_query(request.query),
                                klass,
                                cost=len(request.body or b""),
                                no_shed=request.query.get("type")
                                == "replicate")
                        except qos_mod.QosShed as e:
                            status = 503
                            sp.set_attr("qos", "shed")
                            return self._qos_shed_response(e)
                        sp.set_attr("qos_class", klass)
                        qos_token = qos_mod.set_class(klass)
                    t_admit = time.perf_counter()
                    try:
                        resp = await self._handle_bulk(request, sp)
                    except KeyError as e:
                        resp = json_response({"error": str(e)}, status=404)
                    except PermissionError as e:
                        resp = json_response({"error": str(e)}, status=403)
                    except Exception as e:  # noqa: BLE001
                        log.error("bulk http error: %s", e)
                        resp = json_response({"error": str(e)}, status=500)
                    t_done = time.perf_counter()
                    status = resp.status
                    return resp
                finally:
                    if qos_token is not None:
                        from .. import qos as qos_mod
                        qos_mod.reset_class(qos_token)
                    if grant is not None:
                        grant.release()
                    sp.set_attr("status", status)
                    if status >= 500:
                        sp.set_error(f"HTTP {status}")
                    t_end = time.perf_counter()
                    VOLUME_REQUEST_COUNTER.inc("bulk", str(status))
                    VOLUME_REQUEST_SECONDS.observe("bulk", value=t_end - t0)
                    stages = _observe_stages("bulk", request.t_recv,
                                             request.t_parsed, t0,
                                             t_admit, t_done, t_end)
                    self._flight_record("volume.bulk", request, status,
                                        stages, sp,
                                        request.t_recv or t0, t_end)
                    hot_record(
                        volume=request.query.get("vid") or None,
                        tenant=self._qos_tenant_of_query(request.query),
                        method="bulk",
                        nbytes=len(request.body or b""))

        async def handle_bulk_read(request: fastweb.Request):
            # bulk.read mirrors bulk.put: its own request kind on the
            # dashboards, one span the per-needle resolution hangs under
            t0 = time.perf_counter()
            t_admit = t_done = None
            resp = None
            status = 500
            with tracing.start_span(
                    "bulk.read", component="volume",
                    child_of=tracing.extract(request.headers),
                    attrs={"server": self.url,
                           "bytes": len(request.body or b"")}) as sp:
                try:
                    grant, qos_token = None, None
                    if self.qos.enabled:
                        from .. import qos as qos_mod
                        klass = qos_mod.class_from_headers(
                            request.headers, qos_mod.CLASS_INTERACTIVE)
                        try:
                            grant = await self.qos.admit(
                                self._qos_tenant_of_query(request.query),
                                klass)
                        except qos_mod.QosShed as e:
                            status = 503
                            sp.set_attr("qos", "shed")
                            return self._qos_shed_response(e)
                        sp.set_attr("qos_class", klass)
                        qos_token = qos_mod.set_class(klass)
                    t_admit = time.perf_counter()
                    try:
                        resp = await self._handle_bulk_read(request, sp)
                    except KeyError as e:
                        resp = json_response({"error": str(e)}, status=404)
                    except PermissionError as e:
                        resp = json_response({"error": str(e)}, status=403)
                    except Exception as e:  # noqa: BLE001
                        log.error("bulk-read http error: %s", e)
                        resp = json_response({"error": str(e)}, status=500)
                    t_done = time.perf_counter()
                    status = resp.status
                    if grant is not None and resp.body:
                        # the assembled frame is the byte cost of a bulk
                        # read — charged once known
                        grant.charge(len(resp.body))
                    return resp
                finally:
                    if qos_token is not None:
                        from .. import qos as qos_mod
                        qos_mod.reset_class(qos_token)
                    if grant is not None:
                        grant.release()
                    sp.set_attr("status", status)
                    if status >= 500:
                        sp.set_error(f"HTTP {status}")
                    t_end = time.perf_counter()
                    VOLUME_REQUEST_COUNTER.inc("bulk-read", str(status))
                    VOLUME_REQUEST_SECONDS.observe("bulk-read",
                                                   value=t_end - t0)
                    stages = _observe_stages("bulk-read", request.t_recv,
                                             request.t_parsed, t0,
                                             t_admit, t_done, t_end)
                    self._flight_record("volume.bulk-read", request,
                                        status, stages, sp,
                                        request.t_recv or t0, t_end)
                    hot_record(
                        volume=request.query.get("vid") or None,
                        tenant=self._qos_tenant_of_query(request.query),
                        method="bulk-read",
                        nbytes=(len(resp.body) if resp is not None
                                and resp.body else 0))

        app = fastweb.FastApp()
        app.route("/status", status)
        app.route("/ui", status_ui)
        app.route("/bulk", handle_bulk)
        app.route("/bulk-read", handle_bulk_read)
        app.route("/metrics", metrics)
        # pprof-style triggers (reference -debug.port net/http/pprof)
        app.route("/debug/profile", debug_profile)
        app.route("/debug/flight", debug_flight)
        app.route("/debug/jax-profiler", debug_jax_profiler)
        app.route("/debug/failpoints", debug_failpoints)
        app.route("/debug/traces", debug_traces)
        app.route("/debug/events", debug_events)
        app.route("/debug/locks", debug_locks)
        app.route("/debug/qos", debug_qos)
        app.route("/debug/lifecycle", debug_lifecycle)
        app.default(handle)
        fastweb.serve_fast_app(app, self.ip, self.port, self._stop,
                               client_max_size=256 << 20, logger=log,
                               on_loop=self._loop_lag.attach)

    # -- lifecycle heat report ----------------------------------------------
    def _set_destroy_time(self, vid: int, at: float) -> bool:
        """Stamp DestroyTime into a local EC volume's .vif + live
        object (one seam for the gRPC verb and the debug POST).
        False = no such EC volume here."""
        ev = self.store.find_ec_volume(vid)
        if ev is None:
            return False
        from ..ec import files as ec_files
        ec_files.update_vif(ev.base + ".vif", {"destroy_time": at})
        ev.destroy_time = at
        return True

    def _lifecycle_payload(self) -> dict:
        """The planner's per-server input (served at /debug/lifecycle):
        heat AGES, never absolute clocks — monotonic read clocks and
        wall-clock needle timestamps both reduce to seconds-ago here so
        the planner compares apples across processes."""
        access = self.store.access_snapshot()
        now_wall = time.time()  # swtpu-lint: disable=wallclock-duration (needle timestamps are persisted wall-clock)
        now_mono = time.monotonic()
        vols: dict = {}
        ecs: dict = {}
        for loc in self.store.locations:
            for vid, v in list(loc.volumes.items()):
                a = access.get(vid, {})
                if v.last_append_at_ns:
                    write_age = max(0.0,
                                    now_wall - v.last_append_at_ns / 1e9)
                else:  # loaded sealed: the .dat mtime is the last write
                    try:
                        write_age = max(0.0, now_wall - os.path.getmtime(
                            v.dat_path))
                    except OSError:
                        write_age = None
                vols[str(vid)] = {
                    "collection": v.collection,
                    "size": v.content_size,
                    "read_only": v.read_only,
                    "tiered": v.remote_spec is not None,
                    "last_write_age_s": (round(write_age, 3)
                                         if write_age is not None
                                         else None),
                    "reads": a.get("reads", 0),
                    "last_read_age_s": a.get("last_read_age_s"),
                }
            for vid, ev in list(loc.ec_volumes.items()):
                a = access.get(vid, {})
                # read_age_s() extends the quiet period across restarts
                # via the .vif last-read stamp; the store's counter can
                # only SHORTEN it (a more recent read)
                ages = [ev.read_age_s()]
                if a.get("last_read_age_s") is not None:
                    ages.append(a["last_read_age_s"])
                remote = ev.remote_shard_ids()
                ecs[str(vid)] = {
                    "collection": ev.collection,
                    "local_shards": sorted(set(ev.shards) - set(remote)),
                    "remote_shards": remote,
                    "remote_spec": (ev.remote_spec or {}).get("spec", ""),
                    "remote_reads": ev.remote_reads(),
                    "reads": ev.reads,
                    "last_read_age_s": round(min(ages), 3),
                    "destroy_time": ev.destroy_time,
                    "shard_size": ev.shard_size,
                    "dat_size": ev.dat_size,
                }
        return {"server": self.url,
                "uptime_s": round(now_mono - self._started_mono, 3),
                "volumes": vols, "ec_volumes": ecs}

    # -- QoS helpers ---------------------------------------------------------
    def _qos_tenant(self, vid: int) -> str:
        """Tenant identity at the volume tier: the vid's collection
        ('default' for the unnamed collection and unknown vids)."""
        v = self.store.find_volume(vid)
        if v is None:
            ev = self.store.find_ec_volume(vid)
            return (ev.collection or "default") if ev is not None \
                else "default"
        return v.collection or "default"

    def _qos_tenant_of_path(self, path: str) -> str:
        try:
            vid = int(path.lstrip("/").split(",", 1)[0])
        except ValueError:
            return "default"
        return self._qos_tenant(vid)

    def _qos_tenant_of_query(self, query: dict) -> str:
        try:
            vid = int(query.get("vid", ""))
        except ValueError:
            return "default"
        return self._qos_tenant(vid)

    @staticmethod
    def _qos_shed_response(e):
        """503 + Retry-After, the volume-tier mirror of S3's SlowDown:
        the client (or SDK) backs off for the bucket's ETA."""
        from ..utils.fastweb import Response
        return Response(
            json.dumps({"error": str(e), "qos": "shed",
                        "retryAfterSeconds": e.retry_after_header}).encode(),
            status=503, content_type="application/json",
            headers={"Retry-After": e.retry_after_header})

    def _read_body(self, request):
        ct = request.headers.get("Content-Type") or ""
        name = mime = b""
        gzipped = False
        if ct.startswith("multipart/"):
            from ..utils.fastweb import parse_multipart_single
            data, filename, ptype, part_headers = parse_multipart_single(
                request.body, ct)
            name = filename.encode()
            if ptype and not ptype.startswith("multipart/"):
                mime = ptype.encode()
            gzipped = part_headers.get("Content-Encoding") == "gzip"
            return data, name, mime, gzipped
        data = request.body
        if ct and ct != "application/octet-stream":
            mime = ct.encode()
        gzipped = request.headers.get("Content-Encoding") == "gzip"
        name = (request.query.get("name") or "").encode()  # replicate fan-out
        return data, name, mime, gzipped

    async def _handle_write(self, request):
        from ..utils.fastweb import json_response

        fid = request.path.lstrip("/")
        if self.guard is not None:
            ok, why = self.guard.check_write(request.remote or "",
                                             request.query,
                                             request.headers, fid)
            if not ok:
                return json_response({"error": why}, status=401)
        vid, key, cookie = parse_file_id(fid)
        is_replicate = request.query.get("type") == "replicate"
        ttl = TTL.parse(request.query.get("ttl"))
        # ?fsync=true (reference UploadOption.Fsync, fed by a filer path
        # rule's fsync flag): this ack stands on a real fsync
        fsync = request.query.get("fsync") in ("true", "1")

        # body parse + needle serialization + the store write run
        # OFF-LOOP in one executor hop (contextvars carried): a multi-MB
        # chunk PUT is milliseconds of memcpy/crc (plus an fsync wait
        # when durable), and the filer's windowed upload fan-out sends
        # several at once — on-loop they serialized behind each other
        # and every other request
        def parse_and_write():
            data, name, mime, gzipped = self._read_body(request)
            n = Needle(id=key, cookie=cookie, data=data, name=name,
                       mime=mime, is_gzipped=gzipped, ttl=ttl)
            self.store.write_needle(vid, n, sync=fsync)
            return data, name, mime, gzipped, n

        import asyncio
        import contextvars
        ctx = contextvars.copy_context()
        loop = asyncio.get_running_loop()
        data, name, mime, gzipped, n = await loop.run_in_executor(
            None, ctx.run, parse_and_write)
        if not is_replicate:
            await self._replicate(fid, data, name, mime, gzipped,
                                  fsync=fsync)
        return json_response({"name": name.decode(errors="replace"),
                              "size": len(data),
                              "eTag": f"{n.checksum:x}"}, status=201)

    async def _replicate(self, fid: str, data: bytes, name: bytes,
                         mime: bytes, gzipped: bool,
                         fsync: bool = False) -> None:
        """Synchronous fan-out to replica peers (store_replicate.go:25),
        preserving the needle attributes (name/mime/gzip flag) and the
        durability mode (a ?fsync=true write is fsync'd on EVERY
        replica, or the ack overstates what a crash can keep)."""
        vid = int(fid.split(",")[0])
        # single-copy volumes need no peer lookup at all: the superblock
        # carries the xyz placement, and '000' means this write is final
        # (reference checks ReplicaPlacement.GetCopyCount() == 1 the same way)
        v = self.store.find_volume(vid)
        if v is not None and v.super_block.replica_placement.copy_count == 1:
            return
        peers = [u for u in self._lookup_replicas_cached(vid) if u != self.url]
        if not peers:
            return
        from .. import tracing

        headers = {"Content-Type": mime.decode() or "application/octet-stream"}
        if gzipped:
            headers["Content-Encoding"] = "gzip"

        async def send_one(sess, peer):
            url = f"http://{peer}/{fid}?type=replicate"
            if fsync:
                url += "&fsync=true"
            if name:
                url += "&" + urllib.parse.urlencode(
                    {"name": name.decode(errors="replace")})
            url += self._peer_jwt_param(fid)
            from .. import qos as qos_mod
            async with sess.post(
                    url, data=data,
                    headers=qos_mod.inject(tracing.inject(headers))) as r:
                return r.status

        await self._fan_out_to_peers(
            peers,
            lambda peer: {"peer": peer, "fid": fid, "bytes": len(data)},
            "replicate", send_one)

    async def _fan_out_to_peers(self, peers, span_attrs, desc,
                                send_one) -> None:
        """Shared synchronous replica fan-out envelope (reference
        store_replicate.go:25): EVERY peer must land or the write fails,
        so a transiently-flaky peer gets the retry envelope (jittered
        backoff, per-attempt timeout, one overall deadline bounding the
        whole fan-out) before we give up. Breakers record outcomes for
        observability but never skip a peer here — durability beats
        latency on the replica hop. A 3xx/4xx is a deterministic
        rejection (auth/config mismatch): the peer is alive and the
        identical retry can't succeed, so no breaker charge, no backoff,
        the write fails now. `send_one(sess, peer) -> status` performs
        one attempt; `span_attrs(peer)` labels the per-peer span."""
        import asyncio

        import aiohttp

        from .. import tracing

        pol = retry.WRITE_POLICY
        timeout = aiohttp.ClientTimeout(total=pol.attempt_timeout)
        deadline = time.monotonic() + pol.deadline
        async with aiohttp.ClientSession(auto_decompress=False,
                                         timeout=timeout) as sess:
            for peer in peers:
                br = retry.breaker(peer)
                last_err: Exception | None = None
                # one child span per replica hop: a slow or retried write
                # shows WHICH peer cost it directly in the trace
                with tracing.start_span(
                        "volume.replicate", component="volume",
                        attrs=span_attrs(peer)) as sp:
                    for attempt in range(1, pol.max_attempts + 1):
                        try:
                            # failpoint: a dead replica peer without
                            # killing a real process — drives write-path
                            # failure handling
                            failpoints.check("replicate.peer")
                            status = await send_one(sess, peer)
                            if 300 <= status < 500:
                                last_err = OSError(f"{desc} to {peer}: "
                                                   f"HTTP {status}")
                                break
                            if status >= 500:
                                raise OSError(f"{desc} to {peer}: "
                                              f"HTTP {status}")
                            br.record_success()
                            retry.BUDGET.deposit()
                            last_err = None
                            break
                        except Exception as e:  # noqa: BLE001
                            br.record_failure()
                            last_err = e
                            delay = pol.backoff(attempt)
                            if (attempt >= pol.max_attempts
                                    or time.monotonic() + delay > deadline
                                    or not retry.BUDGET.withdraw()):
                                break
                            try:
                                from ..stats import RETRY_ATTEMPTS
                                RETRY_ATTEMPTS.inc("replicate.peer")
                            except Exception:  # noqa: BLE001  # swtpu-lint: disable=silent-except (metrics must never break IO)
                                pass
                            sp.add_event("retry", op="replicate.peer",
                                         attempt=attempt,
                                         breaker=br.state,
                                         delay_ms=round(delay * 1e3, 2),
                                         error=str(e)[:200])
                            await asyncio.sleep(delay)
                    if last_err is not None:
                        sp.set_error(last_err)
                if last_err is not None:
                    raise OSError(f"{desc} to {peer} failed after "
                                  f"retries: {last_err}")

    def _peer_jwt_param(self, fid: str) -> str:
        """Replica fan-out re-mints a write token with the shared signing key
        (reference store_replicate.go forwards the request's jwt; peers share
        the key, so minting locally is equivalent and survives expiry)."""
        if self.guard is None or not self.guard.signing_key:
            return ""
        from ..security import gen_jwt_for_volume_server
        tok = gen_jwt_for_volume_server(self.guard.signing_key,
                                        self.guard.expires_after_sec, fid)
        return "&jwt=" + urllib.parse.quote(tok)

    # -- bulk ingest data plane (batched control plane, ISSUE 7) -----------
    async def _handle_bulk(self, request, sp):
        """One framed bulk-PUT: N needles land under a single volume-lock
        acquisition with one batched needle-map update and ONE fsync
        (storage/volume.py write_needles), the range JWT is validated
        once for the whole frame, and replicas receive the frame in one
        fan-out hop instead of N. This is where the per-needle ~115 us
        of PUT protocol amortizes to ~115/N us."""
        from ..utils.fastweb import json_response

        if request.method not in ("POST", "PUT"):
            return json_response({"error": "method not allowed"}, status=405)
        # chaos arm: the volume server dying mid-bulk-PUT — nothing
        # written, no ack; the client must re-lease and burn the fids
        failpoints.check("volume.bulk.put")
        from ..storage import bulk as bulk_frame

        # frame parse + per-needle crc32c is real CPU at 8 MB frames —
        # run it off-loop like the write below, or concurrent bulk
        # clients head-of-line-block every read on this server
        import asyncio
        import contextvars
        loop = asyncio.get_running_loop()
        ctx = contextvars.copy_context()
        try:
            vid, entries = await loop.run_in_executor(
                None, ctx.run, bulk_frame.unpack_frame,
                request.body or b"")
        except bulk_frame.FrameError as e:
            return json_response({"error": str(e)}, status=400)
        q_vid = request.query.get("vid", "")
        try:
            if q_vid and int(q_vid) != vid:
                return json_response(
                    {"error": f"query vid {q_vid} != frame vid {vid}"},
                    status=400)
        except ValueError:
            return json_response({"error": f"bad vid {q_vid!r}"},
                                 status=400)
        cookies = {e.cookie for e in entries}
        if len(cookies) != 1:
            # a lease shares ONE cookie across its range; mixed cookies
            # means a stitched frame — reject before the auth check
            return json_response({"error": "mixed cookies in frame"},
                                 status=400)
        keys = [e.key for e in entries]
        cookie = entries[0].cookie
        sp.set_attr("vid", vid)
        sp.set_attr("needles", len(entries))
        if self.guard is not None:
            # ONE token validation covers the whole frame (range JWT)
            ok, why = self.guard.check_bulk(request.remote or "",
                                            request.query, request.headers,
                                            vid, keys, cookie)
            if not ok:
                return json_response({"error": why}, status=401)
        ttl_str = request.query.get("ttl") or ""
        ttl = TTL.parse(ttl_str)
        is_replicate = request.query.get("type") == "replicate"

        # needle construction + the batched append + frame fsync run
        # off-loop in ONE executor hop (contextvars carried so the
        # storage failpoints/trace stay under this span)
        def build_and_write():
            needles = [Needle(id=e.key, cookie=e.cookie,
                              data=bytes(e.data),
                              is_gzipped=bool(e.flags & 0x01), ttl=ttl)
                       for e in entries]
            return self.store.write_needles_bulk(vid, needles)

        await loop.run_in_executor(None, ctx.run, build_and_write)
        if not is_replicate:
            await self._replicate_bulk(vid, request.body, keys, cookie,
                                       ttl_str)
        # chaos arm: ack lost AFTER the frame is durable everywhere —
        # the client burns the fids; the needles stay readable orphans
        failpoints.check("volume.bulk.ack")
        from ..stats import BULK_PUT_NEEDLES
        BULK_PUT_NEEDLES.observe(value=len(entries))
        from ..ops import events
        events.emit("bulk.put", vid=vid, needles=len(entries),
                    bytes=len(request.body), node=self.url,
                    replicate=is_replicate)
        return json_response(
            {"count": len(entries),
             "eTags": [f"{e.crc:x}" for e in entries]}, status=201)

    async def _replicate_bulk(self, vid: int, body: bytes,
                              keys: "list[int]", cookie: int,
                              ttl_str: str = "") -> None:
        """Synchronous replica fan-out of a WHOLE bulk frame: one hop
        per peer instead of one per needle, under the same retry
        envelope + all-replicas-or-fail semantics as _replicate."""
        v = self.store.find_volume(vid)
        if v is not None and v.super_block.replica_placement.copy_count == 1:
            return
        peers = [u for u in self._lookup_replicas_cached(vid)
                 if u != self.url]
        if not peers:
            return
        from .. import tracing

        url_tail = f"&type=replicate{self._peer_range_jwt_param(vid, keys, cookie)}"
        if ttl_str:
            # replicas must store the SAME ttl or the copies diverge
            # in expiry semantics
            url_tail += "&ttl=" + urllib.parse.quote(ttl_str)

        async def send_one(sess, peer):
            from .. import qos as qos_mod
            async with sess.put(f"http://{peer}/bulk?vid={vid}{url_tail}",
                                data=body,
                                headers=qos_mod.inject(
                                    tracing.inject({}))) as r:
                return r.status

        await self._fan_out_to_peers(
            peers,
            lambda peer: {"peer": peer, "vid": vid,
                          "bulk_needles": len(keys), "bytes": len(body)},
            "bulk replicate", send_one)

    def _peer_range_jwt_param(self, vid: int, keys: "list[int]",
                              cookie: int) -> str:
        """Range token for the bulk replica hop, minted locally with the
        shared signing key over the frame's [min, max] key span."""
        if self.guard is None or not self.guard.signing_key:
            return ""
        from ..security import gen_jwt_for_fid_range
        lo = min(keys)
        tok = gen_jwt_for_fid_range(
            self.guard.signing_key,
            max(30, self.guard.expires_after_sec),
            vid, lo, max(keys) - lo + 1, cookie)
        return "&jwt=" + urllib.parse.quote(tok)

    # -- bulk read data plane (read-side mirror of /bulk, ISSUE 9) ----------
    async def _handle_bulk_read(self, request, sp):
        """One framed bulk GET: the client names a vid + (key, cookie)
        list ("SWBR"), the server resolves the whole batch in one index
        pass over the lock-free read path and streams every found
        needle back in a single length-prefixed frame ("SWBG") with a
        per-needle status for misses/deleted — the read-side mirror of
        the /bulk ingest plane, amortizing the per-GET HTTP protocol
        N-fold. Hot needles come out of the read cache without touching
        the volume file at all."""
        from ..utils.fastweb import Response, json_response

        if request.method not in ("POST", "PUT"):
            return json_response({"error": "method not allowed"}, status=405)
        # chaos arm: the volume server dying mid-bulk-read — the client
        # fails over to a replica holder
        failpoints.check("volume.bulk.read")
        from ..storage import bulk as bulk_frame
        try:
            vid, pairs = bulk_frame.unpack_read_request(request.body or b"")
        except bulk_frame.FrameError as e:
            return json_response({"error": str(e)}, status=400)
        q_vid = request.query.get("vid", "")
        try:
            if q_vid and int(q_vid) != vid:
                return json_response(
                    {"error": f"query vid {q_vid} != frame vid {vid}"},
                    status=400)
        except ValueError:
            return json_response({"error": f"bad vid {q_vid!r}"},
                                 status=400)
        sp.set_attr("vid", vid)
        sp.set_attr("needles", len(pairs))
        if self.guard is not None:
            # read tokens are per-fid: the frame is admitted only if the
            # caller is whitelisted or its token covers EVERY fid in the
            # frame — the exact scoping the per-needle GET enforces, so
            # /bulk-read can never widen one fid's token into a
            # read-everything pass (check_read short-circuits before any
            # decode when read security is off)
            from ..storage.types import file_id as _file_id
            for key, cookie in pairs:
                ok, why = self.guard.check_read(
                    request.remote or "", request.query, request.headers,
                    _file_id(vid, key, cookie))
                if not ok:
                    return json_response({"error": why}, status=401)
        if (self.store.find_volume(vid) is None
                and self.store.find_ec_volume(vid) is None):
            # no proxy hop for frames: the client fans out by vid and
            # fails over to replica holders itself
            return json_response({"error": f"volume {vid} not local"},
                                 status=404)
        import asyncio
        import contextvars
        loop = asyncio.get_running_loop()
        ctx = contextvars.copy_context()
        body, hits = await loop.run_in_executor(
            self._read_pool, ctx.run, self._bulk_read_frame, vid, pairs)
        sp.set_attr("cache_hits", hits)
        from ..stats import BULK_READ_NEEDLES
        BULK_READ_NEEDLES.observe(value=len(pairs))
        return Response(body, content_type="application/octet-stream")

    def _bulk_read_frame(self, vid: int,
                         pairs: "list[tuple[int, int]]",
                         ) -> "tuple[bytes, int]":
        """Resolve one bulk-read frame (runs on the read pool): cache
        hits first, then ONE batched storage pass for the misses, cache
        fills on the way out. A per-frame byte budget
        (SWTPU_BULK_READ_FRAME_BYTES, 32 MB) bounds what one frame can
        materialize — found needles past it come back READ_OVERFLOW
        unread and the client re-fetches them per-needle, so a frame of
        large objects can't OOM the server across read-pool threads.
        Returns (response_frame, cache_hits)."""
        from ..storage import bulk as bulk_frame
        from ..storage.needle import FLAG_GZIP
        from ..utils.env import env_int

        budget = env_int("SWTPU_BULK_READ_FRAME_BYTES", 32 << 20)
        cache = (self.read_cache
                 if self.store.find_volume(vid) is not None else None)
        results: "list[tuple[int, int, int, int, bytes] | None]" = \
            [None] * len(pairs)
        misses: "list[int]" = []
        hits = 0
        used = 0
        epoch = cache.epoch(vid) if cache is not None else None
        for i, (key, cookie) in enumerate(pairs):
            n = cache.get(vid, key, cookie) if cache is not None else None
            if n is not None:
                # hits consume the frame budget too: the response join
                # is the allocation the budget bounds, and a frame
                # naming hot keys (or one key repeatedly) must not
                # assemble more than the cap
                if used >= budget:
                    results[i] = (key, cookie, bulk_frame.READ_OVERFLOW,
                                  0, b"")
                    continue
                hits += 1
                used += len(n.data)
                results[i] = (key, cookie, bulk_frame.READ_OK,
                              FLAG_GZIP if n.is_gzipped else 0, n.data)
            else:
                misses.append(i)
        if hits:
            # cache hits never reach the store: feed the lifecycle heat
            # counters (misses are counted inside read_needles_bulk)
            self.store.note_read(vid, n=hits)
        if misses:
            got = self.store.read_needles_bulk(
                vid, [pairs[i] for i in misses],
                shard_reader=self._make_shard_reader(vid),
                byte_budget=max(0, budget - used))
            for i, (st, n) in zip(misses, got):
                key, cookie = pairs[i]
                if st == bulk_frame.READ_OK:
                    results[i] = (key, cookie, st,
                                  FLAG_GZIP if n.is_gzipped else 0, n.data)
                    if cache is not None:
                        cache.put(vid, key, n, epoch=epoch)
                else:
                    results[i] = (key, cookie, st, 0, b"")
        return bulk_frame.pack_read_response(vid, results), hits

    def _lookup_replicas_cached(self, vid: int) -> list[str]:
        """Replica sets move only on evacuate/rebalance; a short-TTL cache
        keeps the per-write master round-trip off the hot path."""
        now = time.monotonic()
        hit = self._replica_cache.get(vid)
        if hit is not None and now - hit[0] < 5.0:
            return hit[1]
        urls = self._lookup_replicas(vid)
        self._replica_cache[vid] = (now, urls)
        return urls

    def _lookup_replicas(self, vid: int) -> list[str]:
        try:
            stub = Stub(self.current_leader, MASTER_SERVICE)
            resp = stub.call("LookupVolume",
                             mpb.LookupVolumeRequest(volume_or_file_ids=[str(vid)]),
                             mpb.LookupVolumeResponse, timeout=5)
            for e in resp.volume_id_locations:
                return [loc.url for loc in e.locations]
        except Exception as e:  # noqa: BLE001
            log.warning("replica lookup vid=%d failed: %s", vid, e)
        return []

    @staticmethod
    def _parse_range(value: "str | None"):
        """One single-range `bytes=` spec, or None for absent / invalid /
        multi-range (those serve the full body, per RFC 7233's allowance
        to ignore unsupported Range headers). Returns ("suffix", n) |
        ("from", start) | ("range", start, last)."""
        if not value or not value.startswith("bytes="):
            return None
        spec = value[len("bytes="):].strip()
        if "," in spec:
            return None
        first, sep, last = spec.partition("-")
        if not sep:
            return None
        first, last = first.strip(), last.strip()
        try:
            if not first:
                n = int(last)
                return ("suffix", n) if n > 0 else None
            start = int(first)
            if start < 0:
                return None
            if not last:
                return ("from", start)
            stop = int(last)
            return ("range", start, stop) if stop >= start else None
        except ValueError:
            return None

    @staticmethod
    def _resolve_range(spec, size: int) -> "tuple[int, int] | None":
        """[start, stop) byte window of `spec` over a `size`-byte body,
        or None when unsatisfiable (RFC 7233: start past the end)."""
        if spec[0] == "suffix":
            if size == 0:
                return None
            return max(0, size - spec[1]), size
        start = spec[1]
        if start >= size:
            return None
        if spec[0] == "from":
            return start, size
        return start, min(spec[2] + 1, size)

    async def _handle_read(self, request):
        import asyncio
        import contextvars

        from .. import tracing
        from ..utils.fastweb import Response, json_response

        fid = request.path.lstrip("/")
        if self.guard is not None:
            ok, why = self.guard.check_read(request.remote or "",
                                            request.query,
                                            request.headers, fid)
            if not ok:
                return json_response({"error": why}, status=401)
        vid, key, cookie = parse_file_id(fid)
        # hot-needle cache sits in front of the storage read for LOCAL
        # plain volumes only: EC/degraded and proxied reads stream
        # uncached (their bytes still flow through the identical
        # serve/Range logic below, so the response is path-invariant)
        cache = self.read_cache
        cacheable = (cache is not None
                     and self.store.find_volume(vid) is not None)
        n = None
        epoch = None
        if cacheable:
            n = cache.get(vid, key, cookie)
            if n is not None:
                # cache hits never reach the store: feed the lifecycle
                # heat counters here or hot volumes would read as cold
                self.store.note_read(vid)
            sp = tracing.current_span()
            if sp is not None:
                sp.set_attr("cache", "hit" if n is not None else "miss")
        try:
            if n is None:
                if cacheable:
                    # epoch BEFORE the storage read: a mutation landing
                    # in between invalidates this fill (read_cache.put)
                    epoch = cache.epoch(vid)
                loop = asyncio.get_running_loop()
                ctx = contextvars.copy_context()
                # storage read off-loop on the parallel read pool: the
                # seqlock read path never touches the volume lock, so
                # concurrent GETs proceed while a writer fsyncs
                n = await loop.run_in_executor(
                    self._read_pool, ctx.run, self._store_read,
                    vid, key, cookie)
                if epoch is not None:
                    cache.put(vid, key, n, epoch=epoch)
        except KeyError:
            if (self.store.find_volume(vid) is not None
                    or self.store.find_ec_volume(vid) is not None):
                # the VOLUME is local, so this server is an authoritative
                # replica: a missing/deleted needle is a definitive 404.
                # Proxying here would ping-pong between replicas that
                # each re-proxy — a livelock on read-after-delete (write
                # fan-out fails the whole write on any replica failure,
                # so replicas can't silently diverge on live needles).
                raise
            if request.query.get("proxied"):
                raise  # one forwarding hop max: never proxy a proxy
            # volume not local: proxy or redirect by master lookup (ReadMode)
            return await self._read_remote(request, fid, vid)
        except OSError as e:
            # degraded EC read that couldn't gather d shards from HERE —
            # another holder may reach a different shard subset, so fail
            # over unless this request is already a forwarded hop. When
            # no failover exists (local read mode, sole holder, already
            # proxied) answer 503, NOT 404: the object is recoverable,
            # and a 404 would read as "deleted" to clients and filers.
            if (self.store.find_ec_volume(vid) is not None
                    and not request.query.get("proxied")
                    and self.read_mode != "local"
                    and [u for u in self._lookup_replicas(vid)
                         if u != self.url]):
                return await self._read_remote(request, fid, vid)
            return json_response({"error": str(e)}, status=503)
        body = n.data
        headers = {}
        if n.name:
            headers["Content-Disposition"] = f'inline; filename="{n.name.decode(errors="replace")}"'
        # on-the-fly image ops need uncompressed bytes (reference
        # conditionallyResizeImages, volume_server_handlers_read.go:321);
        # a resize request therefore forces decompression of gzip needles.
        name = n.name.decode(errors="replace") if n.name else ""
        ext = os.path.splitext(name)[1].lower()
        w = h = 0
        mode, do_resize = "", False
        if ext:
            from ..images import should_resize
            w, h, mode, do_resize = should_resize(ext, request.query)
        # Range semantics are computed on the FINAL identity bytes this
        # handler assembled, after the gzip/resize decisions — so the
        # answer is byte-identical whether the needle came from the
        # cache, a lock-free volume pread, or a degraded EC reconstruct.
        # A ranged read of a gzip needle serves identity (sliced
        # compressed bytes would be useless to a client).
        rng_spec = None if do_resize else self._parse_range(
            request.headers.get("Range"))
        gzip_ok = "gzip" in (request.headers.get("Accept-Encoding") or "")
        if n.is_gzipped and (do_resize or rng_spec is not None
                             or not gzip_ok):
            import gzip as _gz
            body = _gz.decompress(body)
        elif n.is_gzipped:
            headers["Content-Encoding"] = "gzip"
        if do_resize:
            from ..images import fix_jpeg_orientation, resized
            if ext in (".jpg", ".jpeg"):
                # bake EXIF rotation only when we re-encode anyway — the
                # plain read path serves stored bytes untouched
                body = fix_jpeg_orientation(body)
            body = resized(ext, body, w, h, mode)
        status = 200
        if rng_spec is not None:
            window = self._resolve_range(rng_spec, len(body))
            if window is None:
                return Response(
                    b"", status=416,
                    headers={"Content-Range": f"bytes */{len(body)}"},
                    content_type="application/octet-stream")
            start, stop = window
            headers["Content-Range"] = \
                f"bytes {start}-{stop - 1}/{len(body)}"
            body = body[start:stop]
            status = 206
        return Response(body, status=status, headers=headers or None,
                        content_type=(n.mime.decode() if n.mime else
                                      "application/octet-stream"))

    def _store_read(self, vid: int, key: int, cookie: "int | None"):
        """Blocking storage read (runs on the read pool)."""
        return self.store.read_needle(
            vid, key, cookie=cookie,
            shard_reader=self._make_shard_reader(vid))

    async def _read_remote(self, request, fid: str, vid: int):
        from ..utils.fastweb import Redirect, Response, json_response

        if self.read_mode == "local":
            return json_response({"error": f"volume {vid} not local"},
                                 status=404)
        # known-dead holders go last on the proxy/redirect hop too
        peers = retry.order_by_breaker(
            [u for u in self._lookup_replicas(vid) if u != self.url])
        if not peers:
            return json_response({"error": f"volume {vid} not found"},
                                 status=404)
        # preserve the caller's query (jwt, resize params, …) on
        # proxy/redirect, marking the hop so the receiver never forwards
        # again (bounds the proxy chain at one hop — no ping-pong)
        qs = request.query_string
        qs = (f"{qs}&" if qs else "") + "proxied=1"
        suffix = f"?{qs}"
        if self.read_mode == "redirect":
            raise Redirect(f"http://{peers[0]}/{fid}{suffix}", status=301)
        import aiohttp

        timeout = aiohttp.ClientTimeout(
            total=retry.READ_POLICY.attempt_timeout)
        from .. import tracing
        # the Range header must survive the proxy hop (and its
        # Content-Range/-Encoding must survive the way back) or ranged
        # reads would silently widen to full bodies on proxied volumes
        fwd = {}
        for h in ("Range", "Accept-Encoding"):
            val = request.headers.get(h)
            if val:
                fwd[h] = val
        # skip aiohttp's default Accept-Encoding — only the CLIENT's own
        # header may reach the origin, or a gzip-stored needle comes back
        # compressed to a caller that never advertised gzip (with
        # auto_decompress off, nobody would decompress it)
        async with aiohttp.ClientSession(
                timeout=timeout, auto_decompress=False,
                skip_auto_headers=("Accept-Encoding",)) as sess:
            last_err: Exception | None = None
            for peer in peers:
                br = retry.breaker(peer)
                try:
                    async with sess.get(f"http://{peer}/{fid}{suffix}",
                                        headers=tracing.inject(fwd)) as r:
                        body = await r.read()
                        br.record_success()
                        back = {}
                        for h in ("Content-Range", "Content-Encoding",
                                  "Content-Disposition"):
                            if h in r.headers:
                                back[h] = r.headers[h]
                        return Response(
                            body, status=r.status, headers=back or None,
                            content_type=(r.content_type
                                          or "application/octet-stream"))
                except Exception as e:  # noqa: BLE001
                    br.record_failure()
                    last_err = e
            return json_response(
                {"error": f"proxy read vid {vid} failed: {last_err}"},
                status=502)

    async def _handle_delete(self, request):
        from ..utils.fastweb import json_response

        fid = request.path.lstrip("/")
        if self.guard is not None:
            ok, why = self.guard.check_write(request.remote or "",
                                             request.query,
                                             request.headers, fid)
            if not ok:
                return json_response({"error": why}, status=401)
        vid, key, _ = parse_file_id(fid)
        is_replicate = request.query.get("type") == "replicate"
        v = self.store.find_volume(vid)
        if v is not None:
            ok = self.store.delete_needle(vid, key)
        else:
            ev = self.store.find_ec_volume(vid)
            if ev is None:
                raise KeyError(f"volume {vid} not local")
            ok = ev.delete_needle(key)
        # fan out even when the needle wasn't found locally (reference
        # ReplicatedDelete): a replica that missed an earlier delete's
        # best-effort fan-out still holds the needle, and re-deleting
        # through any holder must converge the set, not just this copy
        if not is_replicate:
            peers = [u for u in self._lookup_replicas(vid) if u != self.url]
            if peers:
                import aiohttp

                timeout = aiohttp.ClientTimeout(
                    total=retry.WRITE_POLICY.attempt_timeout)
                async with aiohttp.ClientSession(timeout=timeout) as sess:
                    for peer in peers:
                        try:
                            # failpoint: a replica missing the delete
                            # fan-out (the tombstone heals on the next
                            # write/vacuum) — per-peer best effort, the
                            # local delete already succeeded
                            failpoints.check("replicate.delete.peer")
                            await sess.delete(
                                f"http://{peer}/{fid}?type=replicate"
                                + self._peer_jwt_param(fid))
                        except Exception as e:  # noqa: BLE001
                            log.warning("delete fan-out to %s: %s", peer, e)
        return json_response({"size": 1 if ok else 0}, status=202)

    # -- EC shard reader: remote fetch + degraded reconstruct ---------------
    def _fetch_remote_shard(self, vid: int, sid: int, offset: int,
                            length: int, holders: "list[str]",
                            include_open: bool = False) -> bytes | None:
        # one span per shard fetch: a degraded read's trace shows every
        # attempted shard as a child, INCLUDING the failed/missing ones
        # (status=error with the per-holder failures as events)
        from .. import tracing
        with tracing.start_span(
                "ec.shard.fetch", component="volume",
                attrs={"vid": vid, "shard": sid, "offset": offset,
                       "length": length, "holders": len(holders)}) as sp:
            data = self._fetch_remote_shard_inner(vid, sid, offset, length,
                                                  holders, include_open, sp)
            if data is None:
                sp.set_error("no holder served shard"
                             if holders else "shard has no holders")
            return data

    def _fetch_remote_shard_inner(self, vid: int, sid: int, offset: int,
                                  length: int, holders: "list[str]",
                                  include_open: bool,
                                  sp) -> bytes | None:
        try:
            # failpoint: shard fetch failure -> the caller's degraded
            # reconstruct-from-d-others path, without destroying a shard
            failpoints.check("ec.shard.read")
        except failpoints.FailpointError as e:
            log.warning("ec shard %d.%d read failpoint: %s", vid, sid, e)
            sp.add_event("failpoint", error=str(e)[:200])
            return None
        # circuit-open holders are SKIPPED entirely (returning None sends
        # the caller down the reconstruct path — that's the graceful
        # degradation: a known-dead shard peer must not cost a connect
        # timeout per read). `include_open=True` is the reconstruct
        # path's last resort when the healthy shards alone can't reach d.
        ordered = retry.order_by_breaker(holders)
        if not include_open:
            allowed = []
            for addr in ordered:
                br = retry.breaker(addr)
                if br.would_allow():
                    allowed.append(addr)
                else:
                    sp.add_event("breaker_open", peer=addr,
                                 state=br.state)
            ordered = allowed
        for addr in ordered:
            br = retry.breaker(addr)
            try:
                stub = Stub(addr, VOLUME_SERVICE)
                parts = [r.data for r in stub.call_stream(
                    "VolumeEcShardRead",
                    vpb.VolumeEcShardReadRequest(
                        volume_id=vid, shard_id=sid,
                        offset=offset, size=length),
                    vpb.VolumeEcShardReadResponse)]
                br.record_success()
                sp.set_attr("holder", addr)
                # corrupt site: bit-flips on the shard wire — the needle
                # CRC downstream must catch what reconstruction produces
                return failpoints.corrupt("ec.shard.read.data",
                                          b"".join(parts))
            except Exception as e:  # noqa: BLE001
                br.record_failure()
                sp.add_event("holder_failed", peer=addr,
                             error=str(e)[:200])
                log.warning("remote shard %d.%d read from %s: %s",
                            vid, sid, addr, e)
        return None

    def _make_shard_reader(self, vid: int):
        from .. import tracing

        def reader(shard_id: int, offset: int, length: int) -> bytes:
            locs = self._lookup_ec_shards(vid)
            data = self._fetch_remote_shard(vid, shard_id, offset, length,
                                            locs.get(shard_id, []))
            if data is None and locs.get(shard_id):
                # holders listed but unreachable: locations may be stale
                # (11 s tier, store_ec.go:263) — refresh once and retry
                fresh = self._lookup_ec_shards(vid, failed=True)
                if fresh.get(shard_id, []) != locs.get(shard_id, []):
                    tracing.add_event("stale_locations_refreshed", vid=vid,
                                      shard=shard_id)
                    data = self._fetch_remote_shard(
                        vid, shard_id, offset, length,
                        fresh.get(shard_id, []))
                locs = fresh
            if data is not None:
                return data
            # degraded read: reconstruct this interval from >= d other
            # shards fetched CONCURRENTLY (store_ec.go:357-400 fans out
            # one goroutine per shard; sequential fetches would stack one
            # RTT per shard onto the degraded p99)
            with tracing.start_span(
                    "ec.reconstruct", component="volume",
                    attrs={"vid": vid, "shard": shard_id, "offset": offset,
                           "length": length}) as sp:
                return _reconstruct(shard_id, offset, length, locs, sp)

        def _reconstruct(shard_id: int, offset: int, length: int,
                         locs: dict, sp) -> bytes:
            ev = self.store.find_ec_volume(vid)
            if ev is None:
                raise KeyError(f"shard {shard_id} unreachable")
            geo = ev.geo
            if ev.codec == "msr":
                # the coupled code is not positional: degraded reads
                # fetch the interval plan's layer slices (repair planes
                # when all n-1 helpers answer, a closure-restricted
                # general decode otherwise)
                return _reconstruct_msr(ev, shard_id, offset, length,
                                        locs, sp)
            piggybacked = ev.codec == "piggyback"
            gathered: dict[int, bytes] = {}
            remote_sids = []
            for sid in range(geo.n):
                if sid == shard_id:
                    continue
                local = ev.shards.get(sid)
                if local is not None and len(gathered) < geo.d:
                    gathered[sid] = local.read_at(offset, length)
                elif local is None:
                    remote_sids.append(sid)
            sp.set_attr("local_shards", len(gathered))
            # piggybacked volumes: shards 0..d (data + the unpiggybacked
            # parity) decode positionally anywhere, so fetch those first
            # and touch piggybacked parities only when the plain set
            # cannot reach d (they need a paired a-range fetch to strip)
            if piggybacked:
                waves = [[s for s in remote_sids if s <= geo.d],
                         [s for s in remote_sids if s > geo.d]]
            else:
                waves = [remote_sids]
            for wave in waves:
                if len(gathered) >= geo.d or not wave:
                    continue
                import concurrent.futures as cf
                import contextvars
                # copy_context per submit: the pool threads' fetch spans
                # must land under THIS reconstruct span, not as orphan
                # roots (ThreadPoolExecutor does not propagate contextvars)
                futs = {}
                for sid in wave:
                    ctx = contextvars.copy_context()
                    futs[self._ec_read_pool.submit(
                        ctx.run, self._fetch_remote_shard, vid, sid,
                        offset, length, locs.get(sid, []))] = sid
                for fut in cf.as_completed(futs):
                    data = fut.result()
                    if data is not None:
                        gathered[futs[fut]] = data
                    if len(gathered) >= geo.d:
                        for f in futs:  # stop burning pool workers on
                            f.cancel()  # fetches nobody will use
                        break
            if len(gathered) < geo.d:
                # healthy shards alone can't reach d: as a last resort
                # probe the circuit-open holders too — an open breaker
                # should cost latency, never turn a recoverable read
                # into an error
                for sid in remote_sids:
                    if sid in gathered or len(gathered) >= geo.d:
                        continue
                    data = self._fetch_remote_shard(
                        vid, sid, offset, length, locs.get(sid, []),
                        include_open=True)
                    if data is not None:
                        gathered[sid] = data
            sp.set_attr("gathered", len(gathered))
            sp.set_attr("needed", geo.d)
            if len(gathered) < geo.d:
                # availability failure, NOT a lookup miss: OSError so the
                # read handler fails over to another holder instead of
                # reporting a recoverable object as 404/deleted
                raise OSError(
                    f"cannot reconstruct shard {shard_id}: only "
                    f"{len(gathered)} shards reachable")
            import numpy as np

            present = tuple(sorted(gathered))[:geo.d]
            coder = self.store.coder(geo.d, geo.p, codec=ev.codec)
            from ..stats import DEGRADED_EC_READS
            if piggybacked and any(s > geo.d for s in present):
                # a piggybacked parity is load-bearing: strip its
                # piggyback with the paired a-range (ec/repair.py)
                from ..ec import repair as ec_repair

                def fetch_pair(sid: int, off: int, ln: int) -> bytes:
                    local = ev.shards.get(sid)
                    if local is not None:
                        return local.read_at(off, ln)
                    return self._fetch_range_or_raise(vid, sid, off, ln,
                                                      locs.get(sid, []))
                def fetch_map(fn, reqs):
                    # same fan-out discipline as the gather waves above:
                    # one serial RTT per paired range would stack onto
                    # the degraded p99 (copy_context keeps fetch spans
                    # under this reconstruct span)
                    import contextvars
                    futs = [self._ec_read_pool.submit(
                        contextvars.copy_context().run, fn, *r)
                        for r in reqs]
                    return [f.result() for f in futs]
                sp.set_attr("piggyback_strip", True)
                out_b = ec_repair.reconstruct_interval(
                    coder, {s: gathered[s] for s in present}, shard_id,
                    offset, length, ev.shard_size, fetch_pair,
                    fetch_map=fetch_map)
                DEGRADED_EC_READS.inc()
                return out_b
            inner = coder.inner if piggybacked else coder
            sl = np.stack([np.frombuffer(gathered[s], dtype=np.uint8)
                           for s in present])
            out = np.asarray(inner.reconstruct(sl, present, (shard_id,)))
            DEGRADED_EC_READS.inc()
            return out[0].tobytes()

        def _fetch_plan(ev, plan, locs) -> "dict[int, bytes | None]":
            """Gather one IntervalPlan's per-survivor fragments — local
            shards by pread, remote by ranged-compute fetch — fanned out
            on the EC read pool. None entries mark unreachable helpers."""
            import concurrent.futures as cf
            import contextvars

            def one(sid: int) -> "bytes | None":
                ranges = plan.byte_ranges(sid)
                local = ev.shards.get(sid)
                try:
                    if local is not None:
                        return b"".join(local.read_at(o, ln)
                                        for o, ln in ranges)
                    return self._fetch_fragment_or_raise(
                        vid, sid, ranges, locs.get(sid, []))
                except Exception as e:  # noqa: BLE001
                    log.warning("msr fragment %d.%d: %s", vid, sid, e)
                    return None

            futs = {self._ec_read_pool.submit(
                contextvars.copy_context().run, one, sid): sid
                for sid in plan.fetch}
            return {futs[f]: f.result() for f in cf.as_completed(futs)}

        def _reconstruct_msr(ev, shard_id: int, offset: int, length: int,
                             locs: dict, sp) -> bytes:
            from ..stats import DEGRADED_EC_READS
            geo = ev.geo
            coder = self.store.coder(geo.d, geo.p, codec="msr")
            sub = ev.shard_size // coder.alpha
            ragged = sub and (offset % sub or (offset + length) % sub)
            sp.set_attr("msr", True)
            if ragged and offset // sub != (offset + length - 1) // sub:
                # a span crossing sub-symbol boundaries would widen the
                # shared inner window to the full sub-symbol width.
                # Split into at most THREE pieces — partial head,
                # layer-aligned middle (one combined plan: every interior
                # byte is wanted, so the full-width window wastes
                # nothing), partial tail — so a ragged edge fetches only
                # its exact inner span without serializing one
                # plan+fan-out round per interior layer.
                end = offset + length
                cuts = [offset]
                head_end = -(-offset // sub) * sub   # round up
                mid_end = (end // sub) * sub         # round down
                if offset < head_end:
                    cuts.append(head_end)
                if head_end < mid_end:
                    cuts.append(mid_end)
                if cuts[-1] != end:
                    cuts.append(end)
                pieces = [_msr_piece(ev, coder, shard_id, a, b - a,
                                     locs, sp)
                          for a, b in zip(cuts, cuts[1:])]
                sp.set_attr("msr_mode", "+".join(m for _, m, _ in pieces))
                sp.set_attr("msr_fetch_bytes",
                            sum(fb for _, _, fb in pieces))
            else:
                pieces = [_msr_piece(ev, coder, shard_id, offset, length,
                                     locs, sp)]
                sp.set_attr("msr_mode", pieces[0][1])
                sp.set_attr("msr_fetch_bytes", pieces[0][2])
            DEGRADED_EC_READS.inc()  # one logical degraded read
            return b"".join(buf for buf, _, _ in pieces)

        def _msr_piece(ev, coder, shard_id: int, offset: int, length: int,
                       locs: dict, sp) -> "tuple[bytes, str, int]":
            """(bytes, plan mode, fetch bytes) for one boundary-aligned
            (or single-layer) span of the lost shard."""
            geo = ev.geo
            helpers = tuple(s for s in range(geo.n) if s != shard_id)
            plan = coder.interval_plan(helpers, shard_id, offset,
                                       length, ev.shard_size)
            got = _fetch_plan(ev, plan, locs)
            if any(v is None for v in got.values()):
                # a helper is down: closure-restricted decode over d
                # survivors that DID answer (one retry; a second wave of
                # failures means the stripe is genuinely unreadable)
                present = tuple(s for s, v in got.items() if v is not None)
                if len(present) < geo.d:
                    raise OSError(
                        f"cannot reconstruct shard {shard_id}: only "
                        f"{len(present)} msr helpers reachable")
                sp.add_event("msr_repair_degraded",
                             reachable=len(present))
                plan = coder.interval_plan(present, shard_id, offset,
                                           length, ev.shard_size)
                got = _fetch_plan(ev, plan, locs)
                if any(v is None for v in got.values()):
                    raise OSError(
                        f"cannot reconstruct shard {shard_id}: msr "
                        "survivors unreachable")
            return (coder.interval_decode(plan, got), plan.mode,
                    plan.bytes_total())
        return reader

    def _make_repair_reader(self, vid: int, codec: "str | None" = None):
        """(shard_reader, fragment_reader, remote_sids, fold_planner)
        for a rebuild on THIS server: survivors that live elsewhere are
        fetched by RANGE through VolumeEcShardRead — or, for repair-
        efficient codecs whose plans name many scattered ranges (msr
        repair planes), by its ranged-COMPUTE mode, which packs them
        into one wire fragment per survivor per window. `fold_planner`
        (geo plane) additionally groups far-DC msr helpers behind a
        same-DC relay that folds their plane rows into ONE alpha-row
        partial before crossing the expensive link.

        Every off-node fetch books SeaweedFS_repair_bytes_by_link_total
        by the holder's DC vs this server's (the master's answers carry
        DC, not rack, so same-DC hops book as cross_rack).

        The read-path location cache is BYPASSED: its freshest tier is
        still 11 s, and a rebuild planned against a pre-failure holder
        set would count the lost shard among its survivors. Admin
        rebuilds are rare; a master round-trip is the right price."""
        locs = self._lookup_ec_shards_master(vid)
        if locs is None:
            # master unreachable: serve the stale cache entry directly
            # (going through _lookup_ec_shards would re-ask the master we
            # just saw fail — a second full lookup timeout per rebuild)
            with self._ec_loc_lock:
                ent = self._ec_loc_cache.get(vid)
            locs = ent[0] if ent is not None else {}
        else:
            now = time.monotonic()
            with self._ec_loc_lock:
                self._ec_loc_cache[vid] = (locs, now, False)
        me = f"{self.ip}:{self.grpc_port}"
        peers = {sid: [a for a in addrs if a != me]
                 for sid, addrs in locs.items()}
        remote = sorted(sid for sid, addrs in peers.items() if addrs)
        if codec is None:
            ev = self.store.find_ec_volume(vid)
            codec = ev.codec if ev is not None else "rs"

        def _book(link: str, n: int) -> None:
            try:
                from ..stats import REPAIR_BYTES_BY_LINK
                REPAIR_BYTES_BY_LINK.inc(codec, link, amount=n)
            except Exception:  # noqa: BLE001  # swtpu-lint: disable=silent-except (metrics must never break repair)
                pass

        def _link_of(sid: int) -> "str | None":
            # attribution by primary holder: the fallback discipline may
            # serve from a later holder, but the first healthy one is
            # the overwhelmingly common server and the only defensible
            # single answer without per-fetch plumbing
            holders = peers.get(sid)
            if not holders or not self.data_center:
                return None
            dc = self._ec_addr_dc.get(holders[0], "")
            if not dc:
                return None
            return "cross_rack" if dc == self.data_center else "cross_dc"

        def reader(sid: int, offset: int, length: int) -> bytes:
            data = self._fetch_range_or_raise(vid, sid, offset, length,
                                              peers.get(sid, []))
            link = _link_of(sid)
            if link:
                _book(link, len(data))
            return data

        def fragment_reader(sid: int, ranges) -> bytes:
            buf = self._fetch_fragment_or_raise(vid, sid, ranges,
                                                peers.get(sid, []))
            link = _link_of(sid)
            if link:
                _book(link, len(buf))
            return buf

        def _fold_fetch(f, sids, srcs, mat, alpha):
            """One relay group's fetch(ranges) -> folded partial of
            alpha rows. sids[0]/srcs[0] is the relay; it gathers the
            rest of the group's plane rows DC-locally (gather_* request
            fields) and applies the stacked combine matrix, so only
            alpha rows cross the thin link instead of |group|*beta."""
            import numpy as np
            relay_sid, relay = sids[0], srcs[0]

            def fetch(ranges) -> "np.ndarray":
                want = alpha * ranges[0][1]
                try:
                    stub = Stub(relay, VOLUME_SERVICE)
                    parts = [r.data for r in stub.call_stream(
                        "VolumeEcShardRead",
                        vpb.VolumeEcShardReadRequest(
                            volume_id=vid, shard_id=relay_sid,
                            fragment_offsets=[o for o, _ in ranges],
                            fragment_lengths=[ln for _, ln in ranges],
                            combine_rows=alpha,
                            combine_matrix=mat.tobytes(),
                            gather_shard_ids=list(sids[1:]),
                            gather_sources=list(srcs[1:])),
                        vpb.VolumeEcShardReadResponse)]
                    buf = b"".join(parts)
                    if len(buf) != want:
                        raise OSError(f"folded partial {len(buf)} bytes "
                                      f"!= {want}")
                    _book("cross_dc", want)
                    return np.frombuffer(buf, dtype=np.uint8)
                except Exception as e:  # noqa: BLE001
                    log.warning("folded fetch vid=%d f=%d relay=%s: %s; "
                                "shipping raw fragments", vid, f, relay, e)
                # relay down or legacy: ship the raw rows (no geo
                # saving) and fold locally — repair still converges
                from ..ops import gf8
                w = ranges[0][1]
                rows = []
                for s in sids:
                    buf = fragment_reader(s, list(ranges))
                    arr = np.frombuffer(buf, dtype=np.uint8)
                    rows.extend(arr.reshape(len(ranges), w))
                return gf8.np_gf_apply(mat, np.stack(rows))
            return fetch

        def fold_planner(coder, f: int):
            """[(sids, fetch)] relay groups for rebuild_msr_single: one
            per far DC holding > q helpers (geo/repair_fold.py). Empty
            when geo folding is off (SWTPU_GEO_FOLD=0), topology is
            unknown, or no far group is big enough to pay for a relay
            hop."""
            if os.environ.get("SWTPU_GEO_FOLD", "1") == "0" or \
                    not self.data_center or coder.codec != "msr":
                return []
            g = coder.grid
            if g.q < 2:
                return []
            from ..geo import repair_fold
            helper_dcs = {}
            for sid, addrs in peers.items():
                if sid == f or not addrs:
                    continue
                dc = self._ec_addr_dc.get(addrs[0], "")
                if dc:
                    helper_dcs[sid] = dc
            folds = []
            for dc, sids in repair_fold.fold_groups(
                    helper_dcs, self.data_center, g.q):
                srcs = []
                for s in sids:
                    cands = [a for a in peers.get(s, ())
                             if self._ec_addr_dc.get(a) == dc]
                    if not cands:
                        break
                    srcs.append(cands[0])
                if len(srcs) != len(sids):
                    continue  # a member lost its in-DC holder
                mat = repair_fold.stacked_matrix(g.d, g.p, f, sids)
                folds.append((sids, _fold_fetch(f, sids, srcs, mat,
                                                g.alpha)))
            return folds
        return reader, fragment_reader, remote, fold_planner

    def _fetch_range_or_raise(self, vid: int, sid: int, offset: int,
                              length: int, holders: "list[str]") -> bytes:
        """One ranged fetch with the shared fallback discipline: healthy
        holders first, then circuit-open ones as a last resort (latency
        beats failing a repair or a recoverable read), else OSError."""
        data = self._fetch_remote_shard(vid, sid, offset, length, holders)
        if data is None:
            data = self._fetch_remote_shard(vid, sid, offset, length,
                                            holders, include_open=True)
        if data is None:
            raise OSError(f"shard {vid}.{sid} range [{offset}, +{length}) "
                          "unreachable")
        return data

    def _fetch_fragment_or_raise(self, vid: int, sid: int, ranges,
                                 holders: "list[str]") -> bytes:
        """Fetch one computed fragment (scattered ranges packed holder-
        side). A holder predating the ranged-compute fields answers the
        legacy zero-size read with an empty stream — detected and
        degraded to per-range fetches so mixed-version repairs still
        converge."""
        from .. import tracing
        want = sum(ln for _, ln in ranges)
        if want == 0:
            return b""
        ordered = retry.order_by_breaker([a for a in holders
                                          if retry.breaker(a).would_allow()]) \
            or list(holders)
        for addr in ordered:
            try:
                # same fault-injection site as the ranged path; a firing
                # failpoint degrades to per-range fetches (no breaker
                # penalty — the peer did nothing wrong)
                failpoints.check("ec.shard.read")
            except failpoints.FailpointError as e:
                log.warning("ec fragment read failpoint: %s", e)
                break
            br = retry.breaker(addr)
            try:
                stub = Stub(addr, VOLUME_SERVICE)
                parts = [r.data for r in stub.call_stream(
                    "VolumeEcShardRead",
                    vpb.VolumeEcShardReadRequest(
                        volume_id=vid, shard_id=sid,
                        fragment_offsets=[o for o, _ in ranges],
                        fragment_lengths=[ln for _, ln in ranges]),
                    vpb.VolumeEcShardReadResponse)]
                buf = b"".join(parts)
                if len(buf) == want:
                    br.record_success()
                    return failpoints.corrupt("ec.shard.read.data", buf)
                if not buf:
                    tracing.add_event("fragment_unsupported", peer=addr,
                                      vid=vid, shard=sid)
                    break  # legacy holder: per-range fallback below
                raise OSError(f"fragment length {len(buf)} != {want}")
            except Exception as e:  # noqa: BLE001
                br.record_failure()
                log.warning("fragment read %d.%d from %s: %s",
                            vid, sid, addr, e)
        out = bytearray()
        for off, ln in ranges:
            out += self._fetch_range_or_raise(vid, sid, off, ln, holders)
        return bytes(out)

    # shard-location cache staleness tiers (store_ec.go:256-267): complete
    # location sets refresh every 37 min, incomplete every 7 min, and a
    # failed read may force a refresh after 11 s — the master is OFF the
    # EC read hot path.
    _EC_LOC_TTL_COMPLETE = 37 * 60
    _EC_LOC_TTL_INCOMPLETE = 7 * 60
    _EC_LOC_TTL_FAILED = 11

    def _lookup_ec_shards(self, vid: int, failed: bool = False,
                          ) -> dict[int, list[str]]:
        """shard id -> gRPC addresses of holders, via the tiered cache."""
        now = time.monotonic()
        with self._ec_loc_lock:
            ent = self._ec_loc_cache.get(vid)
            if ent is not None:
                locs, fetched, complete = ent
                ttl = (self._EC_LOC_TTL_FAILED if failed else
                       self._EC_LOC_TTL_COMPLETE if complete else
                       self._EC_LOC_TTL_INCOMPLETE)
                if now - fetched < ttl:
                    return locs
        locs = self._lookup_ec_shards_master(vid)
        if locs is not None:
            ev = self.store.find_ec_volume(vid)
            n = ev.geo.n if ev is not None else 0
            complete = n > 0 and all(locs.get(s) for s in range(n))
            with self._ec_loc_lock:
                self._ec_loc_cache[vid] = (locs, now, complete)
            return locs
        # master unreachable: serve stale rather than fail the read, and
        # re-stamp the entry (complete=False) so the next probe waits a full
        # incomplete tier (11 s via failed=True) instead of paying the 5 s
        # lookup timeout on EVERY read for the whole outage
        with self._ec_loc_lock:
            ent = self._ec_loc_cache.get(vid)
            if ent is not None:
                self._ec_loc_cache[vid] = (ent[0], now, False)
        return ent[0] if ent is not None else {}

    def _lookup_ec_shards_master(self, vid: int) -> "dict | None":
        try:
            stub = Stub(self.current_leader, MASTER_SERVICE)
            resp = stub.call("LookupEcVolume",
                             mpb.LookupEcVolumeRequest(volume_id=vid),
                             mpb.LookupEcVolumeResponse, timeout=5)
            locs: dict[int, list[str]] = {}
            for e in resp.shard_id_locations:
                addrs = []
                for l in e.locations:
                    addr = f"{l.url.rsplit(':', 1)[0]}:{l.grpc_port}"
                    addrs.append(addr)
                    if l.data_center:
                        self._ec_addr_dc[addr] = l.data_center
                locs[e.shard_id] = addrs
            return locs
        except Exception as e:  # noqa: BLE001
            log.warning("ec lookup vid=%d: %s", vid, e)
            return None

    # -- gRPC admin service ---------------------------------------------------
    def _build_service(self) -> RpcService:
        svc = RpcService(VOLUME_SERVICE)
        vs = self
        store = self.store

        @svc.unary("AllocateVolume", vpb.AllocateVolumeRequest,
                   vpb.AllocateVolumeResponse)
        def allocate(req, context):
            store.add_volume(req.volume_id, req.collection, req.replication,
                             req.ttl, req.disk_type or None)
            vs.flush_heartbeat()
            return vpb.AllocateVolumeResponse()

        @svc.unary("VolumeDelete", vpb.VolumeDeleteRequest, vpb.VolumeDeleteResponse)
        def vol_delete(req, context):
            store.delete_volume(req.volume_id, req.only_empty)
            vs.flush_heartbeat()
            return vpb.VolumeDeleteResponse()

        @svc.unary("VolumeScrub", vpb.VolumeScrubRequest,
                   vpb.VolumeScrubResponse)
        def volume_scrub(req, context):
            """Stream live needles through the batched CRC kernel
            (storage/scrub.py); device='auto' uses the accelerator when
            jax initializes, else the host loop. One failing volume never
            loses the other volumes' results; a time budget + rotating
            cursor lets the admin cron cover large servers across sweeps."""
            from ..storage.scrub import scrub_volume
            if req.volume_id:
                v = store.find_volume(req.volume_id)
                if v is None:
                    context.abort(5, f"volume {req.volume_id} not found")
                vols = [v]
            else:
                vols = []
                for loc in store.locations:
                    with loc.lock:
                        vols.extend(loc.volumes.values())
                vols.sort(key=lambda v: v.id)
                # rotate: start after the last volume a budgeted sweep
                # finished with, so coverage advances sweep over sweep
                cursor = getattr(vs, "_scrub_cursor", 0)
                vols = ([v for v in vols if v.id > cursor]
                        + [v for v in vols if v.id <= cursor])
            resp = vpb.VolumeScrubResponse()
            deadline = (time.monotonic() + req.time_budget_s
                        if req.time_budget_s else None)
            for v in vols:
                try:
                    r = scrub_volume(v, device=req.device or "auto")
                    resp.results.add(volume_id=r.volume_id,
                                     scanned=r.scanned,
                                     corrupt_needle_ids=r.corrupt,
                                     bytes_checked=r.bytes_checked,
                                     elapsed_s=r.elapsed_s, mode=r.mode,
                                     error=r.error)
                except Exception as e:  # noqa: BLE001 — isolate per volume
                    resp.results.add(volume_id=v.id, mode="error",
                                     error=str(e))
                if not req.volume_id:
                    vs._scrub_cursor = v.id
                if deadline is not None and time.monotonic() > deadline:
                    break
            return resp

        @svc.unary("VolumeMarkReadonly", vpb.VolumeMarkReadonlyRequest,
                   vpb.VolumeMarkReadonlyResponse)
        def mark_ro(req, context):
            store.mark_readonly(req.volume_id, True)
            vs.flush_heartbeat()
            return vpb.VolumeMarkReadonlyResponse()

        @svc.unary("VolumeMarkWritable", vpb.VolumeMarkWritableRequest,
                   vpb.VolumeMarkWritableResponse)
        def mark_rw(req, context):
            store.mark_readonly(req.volume_id, False)
            vs.flush_heartbeat()
            return vpb.VolumeMarkWritableResponse()

        @svc.unary("VolumeConfigure", vpb.VolumeConfigureRequest,
                   vpb.VolumeConfigureResponse)
        def vol_configure(req, context):
            """Rewrite the super block's replica placement (reference
            volume_grpc_admin.go VolumeConfigure)."""
            from ..storage.types import ReplicaPlacement
            v = store.find_volume(req.volume_id)
            if v is None:
                return vpb.VolumeConfigureResponse(
                    error=f"volume {req.volume_id} not found")
            try:
                rp = ReplicaPlacement.parse(req.replication)
            except Exception as e:  # noqa: BLE001
                return vpb.VolumeConfigureResponse(error=str(e))
            with v._lock:
                v.super_block.replica_placement = rp
                if v.remote_spec is None:
                    v._dat.seek(0)
                    v._dat.write(v.super_block.to_bytes())
                    v._dat.flush()
            vs.flush_heartbeat()
            return vpb.VolumeConfigureResponse()

        @svc.unary("VolumeStatus", vpb.VolumeStatusRequest, vpb.VolumeStatusResponse)
        def vol_status(req, context):
            v = store.find_volume(req.volume_id)
            if v is None:
                context.abort(5, f"volume {req.volume_id} not found")
            return vpb.VolumeStatusResponse(
                is_read_only=v.read_only, volume_size=v.content_size,
                file_count=v.file_count, file_deleted_count=v.deleted_count)

        # vacuum phases (reference volume_grpc_vacuum.go)
        @svc.unary("VolumeMount", vpb.VolumeMountRequest,
                   vpb.VolumeMountResponse)
        def volume_mount(req, context):
            store.mount_volume(req.volume_id, req.collection)
            vs.flush_heartbeat()
            return vpb.VolumeMountResponse()

        @svc.unary("VolumeUnmount", vpb.VolumeUnmountRequest,
                   vpb.VolumeUnmountResponse)
        def volume_unmount(req, context):
            if not store.unmount_volume(req.volume_id):
                context.abort(5, f"volume {req.volume_id} not found")
            vs.flush_heartbeat()
            return vpb.VolumeUnmountResponse()

        @svc.unary("VolumeServerLeave", vpb.VolumeServerLeaveRequest,
                   vpb.VolumeServerLeaveResponse)
        def volume_server_leave(req, context):
            """Stop heartbeating so the master forgets this node; data
            service keeps running for direct reads (reference
            volume_grpc_admin.go VolumeServerLeave)."""
            vs._leave.set()
            vs._hb_wake.set()
            return vpb.VolumeServerLeaveResponse()

        # ---- tail / incremental sync (reference volume_grpc_tail.go,
        # volume_grpc_copy_incremental.go) ----
        @svc.unary("VolumeSyncStatus", vpb.VolumeSyncStatusRequest,
                   vpb.VolumeSyncStatusResponse)
        def volume_sync_status(req, context):
            v = store.find_volume(req.volume_id)
            if v is None:
                context.abort(5, f"volume {req.volume_id} not found")
            v.sync()
            return vpb.VolumeSyncStatusResponse(
                volume_id=v.id, collection=v.collection,
                tail_offset=v._append_offset,
                compact_revision=v.super_block.compaction_revision,
                last_append_at_ns=v.last_append_at_ns)

        @svc.unary_stream("VolumeIncrementalCopy",
                          vpb.VolumeIncrementalCopyRequest,
                          vpb.VolumeIncrementalCopyResponse)
        def volume_incremental_copy(req, context):
            v = store.find_volume(req.volume_id)
            if v is None:
                context.abort(5, f"volume {req.volume_id} not found")
            start = v.offset_by_append_ns(req.since_ns)
            with v._lock:
                end = v._append_offset
            buf = 2 << 20
            for off in range(start, end, buf):
                yield vpb.VolumeIncrementalCopyResponse(
                    file_content=v.read_raw(off, min(buf, end - off)))

        @svc.unary_stream("VolumeTailSender", vpb.VolumeTailSenderRequest,
                          vpb.VolumeTailSenderResponse)
        def volume_tail_sender(req, context):
            v = store.find_volume(req.volume_id)
            if v is None:
                context.abort(5, f"volume {req.volume_id} not found")
            last_ns = req.since_ns
            draining = req.idle_timeout_seconds or 0
            while context.is_active():  # dead client must free the worker
                progressed = False
                for rec, ts, _nsize in v.read_records_since(last_ns):
                    yield vpb.VolumeTailSenderResponse(needle_record=rec,
                                                      append_at_ns=ts)
                    last_ns = max(last_ns, ts)
                    progressed = True
                if req.idle_timeout_seconds == 0:
                    time.sleep(1.0)  # follow forever (while client lives)
                    continue
                if progressed:
                    draining = req.idle_timeout_seconds
                else:
                    draining -= 1
                    if draining <= 0:
                        return
                time.sleep(1.0)

        @svc.unary("VolumeTailReceiver", vpb.VolumeTailReceiverRequest,
                   vpb.VolumeTailReceiverResponse)
        def volume_tail_receiver(req, context):
            """Pull records from a peer's tail into the local volume
            (reference volume_grpc_tail.go:VolumeTailReceiver)."""
            v = store.find_volume(req.volume_id)
            if v is None:
                context.abort(5, f"volume {req.volume_id} not found")
            src = Stub(req.source_volume_server, VOLUME_SERVICE)
            received = 0
            for resp in src.call_stream(
                    "VolumeTailSender",
                    vpb.VolumeTailSenderRequest(
                        volume_id=req.volume_id, since_ns=req.since_ns,
                        idle_timeout_seconds=req.idle_timeout_seconds or 2),
                    vpb.VolumeTailSenderResponse):
                v.append_records(resp.needle_record)
                received += 1
            return vpb.VolumeTailReceiverResponse(received=received)

        @svc.unary("VacuumVolumeCheck", vpb.VacuumVolumeCheckRequest,
                   vpb.VacuumVolumeCheckResponse)
        def vacuum_check(req, context):
            v = store.find_volume(req.volume_id)
            if v is None:
                context.abort(5, f"volume {req.volume_id} not found")
            # tiered volumes never report garbage: compacting one would
            # silently un-tier it and orphan the remote copy
            if v.remote_spec is not None:
                return vpb.VacuumVolumeCheckResponse(garbage_ratio=0.0)
            return vpb.VacuumVolumeCheckResponse(garbage_ratio=v.garbage_ratio())

        @svc.unary("VacuumVolumeCompact", vpb.VacuumVolumeCompactRequest,
                   vpb.VacuumVolumeCompactResponse)
        def vacuum_compact(req, context):
            v = store.find_volume(req.volume_id)
            if v is None:
                context.abort(5, f"volume {req.volume_id} not found")
            if v.remote_spec is not None:
                context.abort(9, f"volume {req.volume_id} is tiered; "
                              "download it before compacting")
            _, reclaimed = compact(v)
            return vpb.VacuumVolumeCompactResponse(processed_bytes=reclaimed)

        @svc.unary("VacuumVolumeCommit", vpb.VacuumVolumeCommitRequest,
                   vpb.VacuumVolumeCommitResponse)
        def vacuum_commit(req, context):
            v = store.find_volume(req.volume_id)
            if v is None:
                context.abort(5, f"volume {req.volume_id} not found")
            newv = commit_compact(v)
            for loc in store.locations:
                if loc.volumes.get(req.volume_id) is v:
                    loc.volumes[req.volume_id] = newv
            vs.flush_heartbeat()
            return vpb.VacuumVolumeCommitResponse(volume_size=newv.content_size)

        @svc.unary("VacuumVolumeCleanup", vpb.VacuumVolumeCleanupRequest,
                   vpb.VacuumVolumeCleanupResponse)
        def vacuum_cleanup(req, context):
            v = store.find_volume(req.volume_id)
            if v is not None:
                base = v.file_name()
                for ext in (".cpd", ".cpx"):
                    if os.path.exists(base + ext):
                        os.remove(base + ext)
            return vpb.VacuumVolumeCleanupResponse()

        @svc.unary("BatchDelete", vpb.BatchDeleteRequest, vpb.BatchDeleteResponse)
        def batch_delete(req, context):
            resp = vpb.BatchDeleteResponse()
            for fid in req.file_ids:
                r = resp.results.add(file_id=fid)
                try:
                    vid, key, cookie = parse_file_id(fid)
                    if store.delete_needle(vid, key):
                        r.status = 202
                    else:
                        r.status, r.error = 404, "not found"
                except Exception as e:  # noqa: BLE001
                    r.status, r.error = 500, str(e)
            return resp

        # ---- EC RPC set ----
        def _ensure_vif(vid: int, collection: str,
                        base: "str | None" = None) -> "str | None":
            """A rebuild decodes with the codec/geometry sealed in the
            .vif — make sure one exists at `base`, pulling the tiny
            sidecar from any peer holder when this server's copy is
            gone (e.g. bases written before source-volume deletes
            learned to spare it)."""
            if base is None:
                ev = store.find_ec_volume(vid)
                if ev is not None:
                    base = ev.base
                else:
                    for loc in store.locations:
                        cand = loc.base_name(collection, vid)
                        if os.path.exists(cand + ".ecx"):
                            base = cand
                            break
            if base is None or os.path.exists(base + ".vif"):
                return base
            me = f"{vs.ip}:{vs.grpc_port}"
            locs = vs._lookup_ec_shards(vid, failed=True)
            for addr in sorted({a for addrs in locs.values()
                                for a in addrs if a != me}):
                try:
                    src = Stub(addr, VOLUME_SERVICE)
                    parts = [r.file_content for r in src.call_stream(
                        "CopyFile",
                        vpb.CopyFileRequest(volume_id=vid,
                                            collection=collection,
                                            ext=".vif", is_ec_volume=True),
                        vpb.CopyFileResponse)]
                except Exception:  # noqa: BLE001 — peer may lack it too
                    continue
                if any(parts):
                    # parse before installing, and install through the
                    # one sanctioned .vif writer: a torn peer copy must
                    # never land as a valid-looking sidecar
                    try:
                        info = json.loads(b"".join(parts))
                    except ValueError:
                        continue  # peer's copy is torn; try the next
                    ec_files.write_vif(base + ".vif", **info)
                    return base
            return base

        @svc.unary("VolumeEcShardsGenerate", vpb.VolumeEcShardsGenerateRequest,
                   vpb.VolumeEcShardsGenerateResponse)
        def ec_generate(req, context):
            from ..ops import events
            events.emit("ec.encode.start", vid=req.volume_id,
                        collection=req.collection, node=vs.url)
            t0 = time.perf_counter()
            stats: dict = {}
            try:
                store.generate_ec_shards(req.volume_id, req.collection,
                                         req.data_shards or None,
                                         req.parity_shards or None,
                                         stats=stats,
                                         codec=req.codec or None)
            except Exception as e:  # noqa: BLE001
                events.emit("ec.encode.finish", severity=events.ERROR,
                            vid=req.volume_id, node=vs.url, ok=False,
                            error=str(e)[:200])
                raise
            events.emit("ec.encode.finish", vid=req.volume_id, node=vs.url,
                        ok=True,
                        duration_ms=round((time.perf_counter() - t0) * 1e3, 1),
                        **_ec_stage_fields(stats))
            return vpb.VolumeEcShardsGenerateResponse()

        @svc.unary("VolumeEcShardsGenerateBatch",
                   vpb.VolumeEcShardsGenerateBatchRequest,
                   vpb.VolumeEcShardsGenerateBatchResponse)
        def ec_generate_batch(req, context):
            from ..ops import events
            t0 = time.perf_counter()
            stats: dict = {}
            try:
                done = store.generate_ec_shards_batch(
                    list(req.volume_ids), req.collection,
                    req.data_shards or None, req.parity_shards or None,
                    stats=stats, codec=req.codec or None)
            except Exception as e:  # noqa: BLE001
                events.emit("ec.encode.finish", severity=events.ERROR,
                            node=vs.url, ok=False,
                            vids=list(req.volume_ids), error=str(e))
                raise
            events.emit("ec.encode.finish", node=vs.url, ok=True,
                        vids=list(done),
                        duration_ms=round((time.perf_counter() - t0) * 1e3, 1),
                        **_ec_stage_fields(stats))
            return vpb.VolumeEcShardsGenerateBatchResponse(
                encoded_volume_ids=done,
                data_shards=req.data_shards or store.ec_geometry.d,
                parity_shards=req.parity_shards or store.ec_geometry.p,
                codec=req.codec or store.ec_codec)

        @svc.unary("VolumeEcShardsInfo", vpb.VolumeEcShardsInfoRequest,
                   vpb.VolumeEcShardsInfoResponse)
        def ec_info(req, context):
            """Geometry probe from the .vif (TPU extension; the reference
            hardcodes RS(14,2) so it never needs this). local_shard_ids
            reports every shard file ON DISK — mounted or not — which is
            what the repair planner's remount probe needs: a shard
            unmounted by a crashed move while its server stayed up is a
            zero-copy repair (mount it back) instead of a rebuild."""
            from ..ec import files as ec_files

            def on_disk(base):
                return sorted(sid for sid in range(32)
                              if os.path.exists(base
                                                + ec_files.shard_ext(sid)))
            ev = store.find_ec_volume(req.volume_id)
            if ev is not None:
                return vpb.VolumeEcShardsInfoResponse(
                    data_shards=ev.geo.d, parity_shards=ev.geo.p,
                    dat_size=ev.dat_size or 0,
                    codec=ev.codec, shard_size=ev.shard_size,
                    local_shard_ids=sorted(set(ev.shards)
                                           | set(on_disk(ev.base))),
                    remote_shard_ids=ev.remote_shard_ids())
            for loc in store.locations:
                base = loc.base_name(req.collection, req.volume_id)
                if os.path.exists(base + ".vif"):
                    info = ec_files.read_vif(base + ".vif")
                    geo = EcGeometry.from_vif(info, store.ec_geometry)
                    rem = info.get("remote_shards") or {}
                    return vpb.VolumeEcShardsInfoResponse(
                        data_shards=info.get("d", 0),
                        parity_shards=info.get("p", 0),
                        dat_size=info.get("dat_size", 0),
                        codec=info.get("codec", "rs"),
                        shard_size=geo.shard_file_size(
                            info.get("dat_size", 0)),
                        local_shard_ids=on_disk(base),
                        remote_shard_ids=sorted(
                            int(k) for k in rem.get("keys", {})))
            raise KeyError(f"ec volume {req.volume_id} not found")

        @svc.unary("VolumeEcShardsRebuild", vpb.VolumeEcShardsRebuildRequest,
                   vpb.VolumeEcShardsRebuildResponse)
        @_maintenance_tagged
        def ec_rebuild(req, context):
            from ..ops import events
            failpoints.check("ec.rebuild")
            events.emit("ec.rebuild.start", vid=req.volume_id,
                        collection=req.collection, node=vs.url)
            t0 = time.perf_counter()
            stats: dict = {}
            try:
                reader, frag, remote, fold = \
                    vs._make_repair_reader(req.volume_id)
                _ensure_vif(req.volume_id, req.collection)
                rebuilt = store.rebuild_ec_shards(req.volume_id,
                                                  req.collection,
                                                  shard_reader=reader,
                                                  remote_shards=remote,
                                                  stats=stats,
                                                  fragment_reader=frag,
                                                  fold_planner=fold)
            except Exception as e:  # noqa: BLE001
                events.emit("ec.rebuild.finish", severity=events.ERROR,
                            vid=req.volume_id, node=vs.url, ok=False,
                            error=str(e)[:200])
                raise
            events.emit("ec.rebuild.finish", vid=req.volume_id, node=vs.url,
                        ok=True, rebuilt_shard_ids=list(rebuilt),
                        codec=stats.get("codec", "rs"),
                        repair_path=stats.get("path"),
                        bytes_read=stats.get("bytes_read", 0),
                        bytes_written=stats.get("bytes_written", 0),
                        duration_ms=round((time.perf_counter() - t0) * 1e3, 1))
            vs.flush_heartbeat()
            return vpb.VolumeEcShardsRebuildResponse(
                rebuilt_shard_ids=rebuilt,
                bytes_read=stats.get("bytes_read", 0),
                bytes_written=stats.get("bytes_written", 0))

        @svc.unary("VolumeEcShardsCopy", vpb.VolumeEcShardsCopyRequest,
                   vpb.VolumeEcShardsCopyResponse)
        @_maintenance_tagged
        def ec_copy(req, context):
            """Pull shard files FROM source_data_node to this server.
            All of a volume's shard files stay in ONE location: prefer
            the location already holding its .ecx."""
            failpoints.check("ec.shard.copy")
            src = Stub(req.source_data_node, VOLUME_SERVICE)
            loc = next((l for l in store.locations
                        if os.path.exists(
                            l.base_name(req.collection,
                                        req.volume_id) + ".ecx")),
                       None) or store._location_for(None)
            base = loc.base_name(req.collection, req.volume_id)
            exts = [ec_files.shard_ext(s) for s in req.shard_ids]
            if req.copy_ecx_file:
                exts.append(".ecx")
            if req.copy_ecj_file:
                exts.append(".ecj")
            if req.copy_vif_file:
                exts.append(".vif")
            for ext in exts:
                parts = []
                try:
                    for r in src.call_stream(
                            "CopyFile",
                            vpb.CopyFileRequest(volume_id=req.volume_id,
                                                collection=req.collection,
                                                ext=ext, is_ec_volume=True),
                            vpb.CopyFileResponse):
                        parts.append(r.file_content)
                except Exception:  # noqa: BLE001
                    if ext in (".ecj", ".ecx", ".vif"):
                        continue  # optional sidecars may not exist at source
                    raise
                with open(base + ext, "wb") as f:
                    for pc in parts:
                        f.write(pc)
            return vpb.VolumeEcShardsCopyResponse()

        # fork RPC: rebuild shards directly onto this server from peers
        @svc.unary("VolumeEcShardsCopyByRebuild",
                   vpb.VolumeEcShardsCopyByRebuildRequest,
                   vpb.VolumeEcShardsCopyByRebuildResponse)
        @_maintenance_tagged
        def ec_copy_by_rebuild(req, context):
            loc = store._location_for(None)
            base = loc.base_name(req.collection, req.volume_id)
            # the tiny .vif sidecar still copies whole (it carries the
            # codec + geometry the rebuild must decode with); survivor
            # DATA moves only as the ranged fetches the plan asks for
            _ensure_vif(req.volume_id, req.collection, base)
            info = ec_files.read_vif(base + ".vif")
            geo = EcGeometry.from_vif(info, store.ec_geometry)
            reader, frag, remote, fold = vs._make_repair_reader(
                req.volume_id, codec=info.get("codec", "rs"))
            stats: dict = {}
            rebuilt = rebuild_shards(
                base, geo,
                store.coder(geo.d, geo.p, codec=info.get("codec", "rs")),
                wanted=list(req.shard_ids), shard_reader=reader,
                remote_shards=remote, stats=stats, fragment_reader=frag,
                fold_planner=fold)
            return vpb.VolumeEcShardsCopyByRebuildResponse(
                rebuilt_shard_ids=rebuilt,
                bytes_read=stats.get("bytes_read", 0),
                bytes_written=stats.get("bytes_written", 0))

        @svc.unary("VolumeEcShardsMount", vpb.VolumeEcShardsMountRequest,
                   vpb.VolumeEcShardsMountResponse)
        def ec_mount(req, context):
            store.mount_ec_shards(req.volume_id, req.collection)
            vs.flush_heartbeat()
            return vpb.VolumeEcShardsMountResponse()

        @svc.unary("VolumeEcShardsUnmount", vpb.VolumeEcShardsUnmountRequest,
                   vpb.VolumeEcShardsUnmountResponse)
        def ec_unmount(req, context):
            store.unmount_ec_shards(req.volume_id,
                                    list(req.shard_ids) or None)
            vs.flush_heartbeat()
            return vpb.VolumeEcShardsUnmountResponse()

        @svc.unary("VolumeEcShardsDelete", vpb.VolumeEcShardsDeleteRequest,
                   vpb.VolumeEcShardsDeleteResponse)
        def ec_delete(req, context):
            ev = store.find_ec_volume(req.volume_id)
            base = None
            if ev is not None:
                base = ev.base
                store.unmount_ec_shards(req.volume_id, list(req.shard_ids))
            else:
                for loc in store.locations:
                    cand = loc.base_name(req.collection, req.volume_id)
                    if any(os.path.exists(cand + ec_files.shard_ext(s))
                           for s in req.shard_ids):
                        base = cand
                        break
            if base:
                for s in req.shard_ids:
                    p = base + ec_files.shard_ext(s)
                    if os.path.exists(p):
                        os.remove(p)
                # a remote-backed shard has no payload file here:
                # release its .vif claim instead. The remote OBJECT is
                # untouched — a move's target has already merged the
                # claim, and a plain delete leaves cleanup to the
                # lifecycle reaper that owns the remote tier.
                if os.path.exists(base + ".vif"):
                    ec_files.drop_remote_claims(base + ".vif",
                                                list(req.shard_ids))
            vs.flush_heartbeat()
            return vpb.VolumeEcShardsDeleteResponse()

        # fork RPC: move = copy + source delete, driven from the target
        @svc.unary("VolumeEcShardsMove", vpb.VolumeEcShardsMoveRequest,
                   vpb.VolumeEcShardsMoveResponse)
        def ec_move(req, context):
            # first shards of this volume on this server need the index
            # sidecars too (reference copies .ecx/.vif on first placement,
            # command_ec_encode.go parallelCopyEcShardsFromSource);
            # look in EVERY location — existing shards may live on a
            # different disk than the emptiest one
            need_sidecars = not any(
                os.path.exists(loc.base_name(req.collection,
                                             req.volume_id) + ".ecx")
                for loc in store.locations)
            src = Stub(req.source_data_node, VOLUME_SERVICE)
            # a shard whose payload lives on the remote tier moves its
            # .vif CLAIM, not bytes: probe which of the requested sids
            # the source holds only as offloaded claims
            try:
                sinfo = src.call("VolumeEcShardsInfo",
                                 vpb.VolumeEcShardsInfoRequest(
                                     volume_id=req.volume_id,
                                     collection=req.collection),
                                 vpb.VolumeEcShardsInfoResponse)
                claim_sids = [s for s in req.shard_ids
                              if s in set(sinfo.remote_shard_ids)]
            except Exception:  # noqa: BLE001 — legacy peer: payload-only
                claim_sids = []
            payload_sids = [s for s in req.shard_ids
                            if s not in set(claim_sids)]
            ec_copy(vpb.VolumeEcShardsCopyRequest(
                volume_id=req.volume_id, collection=req.collection,
                shard_ids=payload_sids,
                copy_ecx_file=need_sidecars, copy_ecj_file=need_sidecars,
                copy_vif_file=need_sidecars,
                source_data_node=req.source_data_node), context)
            if claim_sids or need_sidecars:
                loc = next((l for l in store.locations
                            if os.path.exists(
                                l.base_name(req.collection,
                                            req.volume_id) + ".ecx")),
                           None) or store._location_for(None)
                base = loc.base_name(req.collection, req.volume_id)
            if claim_sids:
                parts = [r.file_content for r in src.call_stream(
                    "CopyFile",
                    vpb.CopyFileRequest(volume_id=req.volume_id,
                                        collection=req.collection,
                                        ext=".vif", is_ec_volume=True),
                    vpb.CopyFileResponse)]
                claims = ec_files.remote_claims(
                    json.loads(b"".join(parts)), claim_sids)
                if claims is None:
                    context.abort(9, f"source holds no remote claim "
                                     f"for shards {list(claim_sids)}")
            if need_sidecars and os.path.exists(base + ".vif"):
                # the whole-sidecar copy brought claims for shards NOT
                # moving here; exactly one server may hold each claim
                here = ec_files.read_vif(base + ".vif")
                stray = [int(k) for k in (here.get("remote_shards")
                                          or {}).get("keys", {})
                         if int(k) not in set(req.shard_ids)]
                ec_files.drop_remote_claims(base + ".vif", stray)
            if claim_sids:
                ec_files.merge_remote_claims(base + ".vif", claims)
            src.call("VolumeEcShardsDelete",
                     vpb.VolumeEcShardsDeleteRequest(
                         volume_id=req.volume_id, collection=req.collection,
                         shard_ids=req.shard_ids),
                     vpb.VolumeEcShardsDeleteResponse)
            store.mount_ec_shards(req.volume_id, req.collection)
            vs.flush_heartbeat()
            return vpb.VolumeEcShardsMoveResponse()

        @svc.unary_stream("VolumeEcShardRead", vpb.VolumeEcShardReadRequest,
                          vpb.VolumeEcShardReadResponse)
        def ec_shard_read(req, context):
            ev = store.find_ec_volume(req.volume_id)
            if ev is None:
                context.abort(5, f"ec volume {req.volume_id} not found")
            sh = ev.shards.get(req.shard_id)
            if sh is None:
                context.abort(5, f"shard {req.shard_id} not on this server")
            frag_ranges = list(zip(req.fragment_offsets,
                                   req.fragment_lengths))
            if len(req.fragment_offsets) != len(req.fragment_lengths):
                context.abort(3, "fragment_offsets/lengths length mismatch")
            cost = (sum(ln for _, ln in frag_ranges) if frag_ranges
                    else req.size)
            # a maintenance-tagged survivor read (repair plans pulling
            # ranged fetches) admits through the QoS plane and YIELDS
            # to queued foreground work; untagged shard reads are the
            # degraded-read data path and stay admission-free
            from .. import qos as qos_mod
            grant = None
            if vs.qos.enabled and \
                    qos_mod.current_class() == qos_mod.CLASS_MAINTENANCE:
                grant = vs.qos.admit_sync(
                    ev.collection or "default",
                    qos_mod.CLASS_MAINTENANCE, cost=cost)
            try:
                if frag_ranges:
                    # ranged-COMPUTE mode: gather the scattered ranges
                    # (an MSR repair plane is alpha/p layer slices) and
                    # ship ONE packed — optionally GF-combined — wire
                    # fragment instead of one RPC per range
                    yield from _serve_fragment(sh, req, frag_ranges,
                                               context)
                    return
                remaining = req.size
                offset = req.offset
                while remaining > 0:
                    chunk = min(remaining, 1 << 20)
                    data = sh.read_at(offset, chunk)
                    if not data:
                        break
                    yield vpb.VolumeEcShardReadResponse(data=data)
                    offset += len(data)
                    remaining -= len(data)
            finally:
                if grant is not None:
                    grant.release()

        def _serve_fragment(sh, req, frag_ranges, context):
            import numpy as np
            if not req.combine_rows:
                if req.gather_shard_ids:
                    # a relay gather without a combine matrix would ship
                    # MORE bytes than the callers fetching directly
                    context.abort(3, "gather requires combine_rows")
                # pack-only: stream straight from disk, range by range
                # in 1 MB chunks — a request-controlled fragment size
                # must never materialize whole in the holder's RSS
                for off, ln in frag_ranges:
                    rem, pos = ln, off
                    while rem > 0:
                        buf = sh.read_at(pos, min(rem, 1 << 20))
                        if not buf:
                            context.abort(3, f"fragment range [{off}, "
                                             f"+{ln}) beyond shard")
                        yield vpb.VolumeEcShardReadResponse(data=buf)
                        pos += len(buf)
                        rem -= len(buf)
                return
            # helper-side GF fold: rows_out = M (x) range_rows, the
            # hook for codecs whose helpers ship inner products. The
            # fold must hold all rows at once, so unlike the streamed
            # pack path its request-controlled size is CAPPED — repair
            # executors window fragments to ~window/q (ec/repair.py),
            # far below this
            from ..ops import gf8
            gather = list(zip(req.gather_shard_ids, req.gather_sources))
            if len(req.gather_shard_ids) != len(req.gather_sources):
                context.abort(3, "gather ids/sources length mismatch")
            if sum(ln for _, ln in frag_ranges) * (1 + len(gather)) \
                    > (64 << 20):
                context.abort(3, "combine fragment exceeds 64 MB; "
                                 "window the request")
            lens = {ln for _, ln in frag_ranges}
            if len(lens) != 1:
                context.abort(3, "combine needs equal-length ranges")
            total_rows = len(frag_ranges) * (1 + len(gather))
            if len(req.combine_matrix) != req.combine_rows * total_rows:
                context.abort(3, "combine_matrix shape mismatch")
            rows = []
            for off, ln in frag_ranges:
                buf = sh.read_at(off, ln)
                if len(buf) != ln:
                    context.abort(3, f"fragment range [{off}, +{ln}) "
                                     "beyond shard")
                rows.append(np.frombuffer(buf, dtype=np.uint8))
            # geo relay: gather the SAME ranges from DC-local peers so
            # the fold below covers the whole far-side group — matrix
            # columns run sid-major (own rows first, then each gathered
            # shard's) matching geo/repair_fold.stacked_matrix
            for gsid, gsrc in gather:
                try:
                    buf = vs._fetch_fragment_or_raise(
                        req.volume_id, gsid, frag_ranges, [gsrc])
                except OSError as e:
                    context.abort(14, f"gather shard {gsid} from "
                                      f"{gsrc}: {e}")
                arr = np.frombuffer(buf, dtype=np.uint8)
                rows.extend(arr.reshape(len(frag_ranges), frag_ranges[0][1]))
            mat = np.frombuffer(req.combine_matrix, dtype=np.uint8)
            mat = mat.reshape(req.combine_rows, total_rows)
            data = gf8.np_gf_apply(mat, np.stack(rows)).tobytes()
            for i in range(0, len(data), 1 << 20):
                yield vpb.VolumeEcShardReadResponse(
                    data=data[i:i + (1 << 20)])

        @svc.unary("VolumeEcBlobDelete", vpb.VolumeEcBlobDeleteRequest,
                   vpb.VolumeEcBlobDeleteResponse)
        def ec_blob_delete(req, context):
            ev = store.find_ec_volume(req.volume_id)
            if ev is None:
                context.abort(5, f"ec volume {req.volume_id} not found")
            ev.delete_needle(req.file_key)
            return vpb.VolumeEcBlobDeleteResponse()

        @svc.unary("VolumeEcShardsToVolume", vpb.VolumeEcShardsToVolumeRequest,
                   vpb.VolumeEcShardsToVolumeResponse)
        def ec_to_volume(req, context):
            store.ec_shards_to_volume(req.volume_id, req.collection)
            vs.flush_heartbeat()
            return vpb.VolumeEcShardsToVolumeResponse()

        @svc.unary("VolumeCopy", vpb.VolumeCopyRequest, vpb.VolumeCopyResponse)
        @_maintenance_tagged
        def volume_copy(req, context):
            """Pull a whole volume (.dat + .idx) from source_data_node
            (reference volume_grpc_copy.go doCopyFile flow).

            Same-server special case: when the volume is ALREADY here
            and the request names a different disk_type, this is a
            cross-tier move on one machine (volume.tier.move without a
            second server) — a local disk-to-disk copy + retire, not a
            network pull. A same-server request WITHOUT a differing
            disk_type keeps the historical 'already here' rejection."""
            v_here = store.find_volume(req.volume_id)
            if v_here is not None:
                if req.disk_type and not any(
                        loc.volumes.get(req.volume_id) is v_here
                        and loc.disk_type == req.disk_type
                        for loc in store.locations):
                    try:
                        store.move_volume_local(req.volume_id,
                                                req.disk_type)
                    except (KeyError, OSError) as e:
                        context.abort(9, f"local tier move: {e}")
                    vs.flush_heartbeat()
                    nv = store.find_volume(req.volume_id)
                    return vpb.VolumeCopyResponse(
                        last_append_at_ns=nv.last_append_at_ns)
                context.abort(6, f"volume {req.volume_id} already here")
            src = Stub(req.source_data_node, VOLUME_SERVICE)
            loc = store._location_for(req.disk_type or None)
            base = loc.base_name(req.collection, req.volume_id)
            try:
                for ext in (".dat", ".idx"):
                    with open(base + ext, "wb") as f:
                        for r in src.call_stream(
                                "CopyFile",
                                vpb.CopyFileRequest(volume_id=req.volume_id,
                                                    collection=req.collection,
                                                    ext=ext),
                                vpb.CopyFileResponse):
                            f.write(r.file_content)
            except Exception:
                # remove the partial clone: left on disk it would be
                # mounted as a live truncated volume on restart and block
                # every retry with "volume already here"
                for ext in (".dat", ".idx"):
                    try:
                        os.remove(base + ext)
                    except OSError:
                        pass
                raise
            from ..storage.volume import Volume as _Volume
            v = _Volume(loc.directory, req.collection, req.volume_id,
                        create_if_missing=False)
            with loc.lock:
                loc.volumes[req.volume_id] = v
            vs.flush_heartbeat()
            return vpb.VolumeCopyResponse(last_append_at_ns=v.last_append_at_ns)

        @svc.unary_stream("CopyFile", vpb.CopyFileRequest, vpb.CopyFileResponse)
        def copy_file(req, context):
            # a maintenance-tagged pull (VolumeCopy / shard copy from a
            # repairing peer) admits before streaming file bytes off
            # this node's disks — repair storms must not out-read the
            # tenants this node serves
            from .. import qos as qos_mod
            grant = None
            if vs.qos.enabled and \
                    qos_mod.current_class() == qos_mod.CLASS_MAINTENANCE:
                grant = vs.qos.admit_sync(req.collection or "default",
                                          qos_mod.CLASS_MAINTENANCE)
            try:
                yield from _copy_file_stream(req, context)
            finally:
                if grant is not None:
                    grant.release()

        def _copy_file_stream(req, context):
            # flush the live volume's buffered appends first — the stream
            # below reads through a fresh handle and would otherwise miss
            # them (reference syncs via the readonly flip in doCopyFile)
            v = store.find_volume(req.volume_id)
            if v is not None and req.ext in (".dat", ".idx"):
                v.sync()
            path = None
            for loc in store.locations:
                cand = loc.base_name(req.collection, req.volume_id) + req.ext
                if os.path.exists(cand):
                    path = cand
                    break
            if path is None:
                context.abort(5, f"file vol={req.volume_id}{req.ext} not found")
            with open(path, "rb") as f:
                while True:
                    chunk = f.read(1 << 20)
                    if not chunk:
                        break
                    yield vpb.CopyFileResponse(file_content=chunk)

        @svc.unary("ReadVolumeFileStatus", vpb.ReadVolumeFileStatusRequest,
                   vpb.ReadVolumeFileStatusResponse)
        def file_status(req, context):
            v = store.find_volume(req.volume_id)
            if v is None:
                context.abort(5, f"volume {req.volume_id} not found")
            dat_size = (os.path.getsize(v.dat_path)
                        if os.path.exists(v.dat_path)
                        else v.remote_spec.get("size", 0)
                        if v.remote_spec else 0)
            return vpb.ReadVolumeFileStatusResponse(
                volume_id=req.volume_id,
                dat_file_size=dat_size,
                idx_file_size=os.path.getsize(v.idx_path),
                file_count=v.file_count,
                compaction_revision=v.super_block.compaction_revision,
                collection=v.collection)

        @svc.unary("VolumeNeedleStatus", vpb.VolumeNeedleStatusRequest,
                   vpb.VolumeNeedleStatusResponse)
        def needle_status(req, context):
            try:
                n = store.read_needle(req.volume_id, req.needle_id)
            except KeyError as e:
                context.abort(5, str(e))
            return vpb.VolumeNeedleStatusResponse(
                needle_id=n.id, cookie=n.cookie, size=len(n.data),
                last_modified=n.last_modified, crc=n.checksum,
                ttl=str(n.ttl))

        @svc.unary("Ping", vpb.PingRequest, vpb.PingResponse)
        def ping(req, context):
            now = time.time_ns()
            return vpb.PingResponse(start_time_ns=now, remote_time_ns=now,
                                    stop_time_ns=time.time_ns())

        @svc.unary("VolumeTierMoveDatToRemote",
                   vpb.VolumeTierMoveDatToRemoteRequest,
                   vpb.VolumeTierMoveDatToRemoteResponse)
        def tier_upload(req, context):
            """Seal + upload the .dat to a remote backend; the volume
            stays readable through ranged reads (reference
            volume_grpc_tier_upload.go)."""
            from ..ec import files as ec_files
            from ..storage.backend import open_remote

            v = store.find_volume(req.volume_id)
            if v is None:
                context.abort(5, f"volume {req.volume_id} not local")
            if v.remote_spec is not None:
                context.abort(9, f"volume {req.volume_id} already tiered")
            try:
                client = open_remote(req.destination_backend_name)
            except ValueError as e:
                context.abort(3, str(e))
            was_read_only = v.read_only
            v.read_only = True
            try:
                v.sync()
                key = os.path.basename(v.dat_path)
                size = client.write_object(key, v.dat_path)
            except Exception as e:  # noqa: BLE001
                v.read_only = was_read_only  # roll back: no remote copy
                context.abort(13, f"tier upload: {e}")
            remote = {"spec": req.destination_backend_name,
                      "key": key, "size": size}
            ec_files.update_vif(v.vif_path, {"remote": remote})
            if req.keep_local_dat_file:
                # local .dat keeps serving reads; volume stays read-only
                # and marked tiered so the guards above hold
                v.remote_spec = remote
            else:
                v.close()
                os.unlink(v.dat_path)
                store.reload_volume(req.volume_id)
            return vpb.VolumeTierMoveDatToRemoteResponse(
                processed=size, processedPercentage=100.0)

        @svc.unary("VolumeTierMoveDatFromRemote",
                   vpb.VolumeTierMoveDatFromRemoteRequest,
                   vpb.VolumeTierMoveDatFromRemoteResponse)
        def tier_download(req, context):
            """Pull a tiered .dat back to local disk (reference
            volume_grpc_tier_download.go)."""
            from ..ec import files as ec_files
            from ..storage.backend import open_remote

            v = store.find_volume(req.volume_id)
            if v is None:
                context.abort(5, f"volume {req.volume_id} not local")
            if v.remote_spec is None:
                context.abort(9, f"volume {req.volume_id} not tiered")
            remote = v.remote_spec
            client = open_remote(remote["spec"])
            # download to a temp file and verify the size BEFORE touching
            # the .vif or the remote copy — a torn download must never
            # cost the only good copy
            tmp = v.dat_path + ".tiertmp"
            try:
                client.read_object_to(remote["key"], tmp)
                got = os.path.getsize(tmp)
                want = remote.get("size") or client.object_size(remote["key"])
                if got != want:
                    raise OSError(f"short download: {got} != {want}")
            except Exception as e:  # noqa: BLE001
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                context.abort(13, f"tier download: {e}")
            v.close()
            # the remote object may be deleted below: the downloaded .dat
            # and its rename must be durable before the last other copy
            # of the volume's data goes away
            fsutil.fsync_path(tmp)
            os.replace(tmp, v.dat_path)
            fsutil.fsync_dir(v.dat_path)
            ec_files.update_vif(v.vif_path, remove=("remote",))
            nv = store.reload_volume(req.volume_id)
            if not req.keep_remote_dat_file and nv is not None:
                client.delete_object(remote["key"])
            return vpb.VolumeTierMoveDatFromRemoteResponse(
                processed=remote.get("size", 0),
                processedPercentage=100.0)

        @svc.unary("VolumeEcShardsTierMoveToRemote",
                   vpb.VolumeTierMoveDatToRemoteRequest,
                   vpb.VolumeTierMoveDatToRemoteResponse)
        @_maintenance_tagged
        def ec_tier_offload(req, context):
            """Lifecycle EC→remote: offload this holder's local shard
            payloads of an EC volume to the remote tier named by
            `destination_backend_name` (the .dat tier-upload message is
            reused — same field meanings at shard granularity; see the
            volume_server.proto tiering note). The volume keeps serving
            through lazy ranged reads; sidecars stay local. Offload
            bytes admit maintenance-class so a lifecycle sweep can't
            out-read the tenants this node serves."""
            from ..ops import events
            from .. import qos as qos_mod
            grant = None
            if vs.qos.enabled:
                grant = vs.qos.admit_sync(req.collection or "default",
                                          qos_mod.CLASS_MAINTENANCE)
            moved = 0
            try:
                moved = store.offload_ec_shards(
                    req.volume_id, req.destination_backend_name,
                    collection=req.collection)
            except KeyError as e:
                context.abort(5, str(e))
            except ValueError as e:
                context.abort(3, str(e))
            except Exception as e:  # noqa: BLE001
                context.abort(13, f"ec tier offload: {e}")
            finally:
                if grant is not None:
                    if moved:
                        grant.charge(moved)
                    grant.release()
            if moved:
                events.emit("lifecycle.transition", kind="offload",
                            vid=req.volume_id, node=vs.url,
                            collection=req.collection,
                            **{"from": "ec", "to": "remote"},
                            bytes_moved=moved)
            return vpb.VolumeTierMoveDatToRemoteResponse(
                processed=moved, processedPercentage=100.0)

        @svc.unary("VolumeEcShardsTierMoveFromRemote",
                   vpb.VolumeTierMoveDatFromRemoteRequest,
                   vpb.VolumeTierMoveDatFromRemoteResponse)
        @_maintenance_tagged
        def ec_tier_promote(req, context):
            """Lifecycle remote→ec (promote-on-heat): pull this
            holder's offloaded shard payloads back to local disk."""
            from ..ops import events
            from .. import qos as qos_mod
            grant = None
            if vs.qos.enabled:
                grant = vs.qos.admit_sync(req.collection or "default",
                                          qos_mod.CLASS_MAINTENANCE)
            moved = 0
            try:
                moved = store.promote_ec_shards(
                    req.volume_id, collection=req.collection,
                    keep_remote=req.keep_remote_dat_file)
            except KeyError as e:
                context.abort(5, str(e))
            except Exception as e:  # noqa: BLE001
                context.abort(13, f"ec tier promote: {e}")
            finally:
                if grant is not None:
                    if moved:
                        grant.charge(moved)
                    grant.release()
            if moved:
                events.emit("lifecycle.transition", kind="promote",
                            vid=req.volume_id, node=vs.url,
                            collection=req.collection,
                            **{"from": "remote", "to": "ec"},
                            bytes_moved=moved)
            return vpb.VolumeTierMoveDatFromRemoteResponse(
                processed=moved, processedPercentage=100.0)

        @svc.unary("VolumeEcShardsSetDestroyTime",
                   vpb.VolumeTailReceiverRequest,
                   vpb.VolumeTailReceiverResponse)
        def ec_set_destroy_time(req, context):
            """Stamp a DestroyTime onto a local EC volume's .vif — the
            lifecycle executor's TTL verb, on the AUTHENTICATED gRPC
            plane (the cluster token gates it on guarded clusters,
            unlike a bare HTTP POST). Message reuse (no protoc in
            image): since_ns = the DestroyTime instant in NANOSECONDS,
            source_volume_server = collection; see volume_server.proto."""
            if not self._set_destroy_time(req.volume_id,
                                          req.since_ns / 1e9):
                context.abort(5, f"no ec volume {req.volume_id}")
            return vpb.VolumeTailReceiverResponse(received=1)

        @svc.unary_stream("Query", vpb.QueryRequest, vpb.QueriedStripe)
        def query(req, context):
            """S3-Select-lite scan over needles (reference
            volume_grpc_query.go:12; JSON via weed/query/json, CSV is a
            stub there — supported here)."""
            import json as _json

            from ..query import Query, query_csv_lines, query_json_lines

            q = Query(field=req.filter.field, op=req.filter.operand,
                      value=req.filter.value)
            in_fmt = req.input_serialization.format or "json"
            out_fmt = req.output_serialization.format or "json"
            out_delim = req.output_serialization.csv_delimiter or ","
            for fid in req.from_file_ids:
                try:
                    vid, key, cookie = parse_file_id(fid)
                    n = store.read_needle(
                        vid, key, cookie=cookie,
                        shard_reader=self._make_shard_reader(vid))
                except (KeyError, ValueError) as e:
                    context.abort(5, f"query {fid}: {e}")
                data = n.data
                if n.is_gzipped:
                    import gzip as _gz
                    data = _gz.decompress(data)
                if in_fmt == "csv":
                    rows = query_csv_lines(
                        data, list(req.projections), q,
                        delimiter=req.input_serialization.csv_delimiter or ",",
                        has_header=req.input_serialization.csv_has_header)
                else:
                    rows = query_json_lines(data, list(req.projections), q)
                if out_fmt == "csv":
                    import csv as _csv
                    import io as _io
                    sio = _io.StringIO()
                    wr = _csv.writer(sio, delimiter=out_delim,
                                     lineterminator="\n")
                    for row in rows:
                        wr.writerow(["" if v is None else v for v in row])
                    if rows:
                        yield vpb.QueriedStripe(
                            records=sio.getvalue().encode())
                    continue
                buf = []
                for row in rows:
                    if (in_fmt != "csv" and not req.projections
                            and len(row) == 1):
                        buf.append(_json.dumps(row[0]))  # whole document
                    else:
                        buf.append(_json.dumps(row))
                if buf:
                    yield vpb.QueriedStripe(
                        records=("\n".join(buf) + "\n").encode())

        return svc

