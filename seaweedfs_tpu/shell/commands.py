"""Admin shell: command registry + CommandEnv (reference weed/shell).

`CommandEnv` wraps a MasterClient plus the exclusive cluster lock
(command_lock_unlock.go; `confirmIsLocked` gates mutating commands, e.g.
command_ec_encode.go:76). Commands are registered in a table like
shell/commands.go and exposed through the CLI REPL (weed shell).
"""

from __future__ import annotations

import shlex
import time
from dataclasses import dataclass, field
from typing import Callable, TextIO

from ..client.master_client import MasterClient
from ..pb import master_pb2 as mpb
from ..utils.rpc import MASTER_SERVICE, Stub

COMMANDS: dict[str, "Command"] = {}


@dataclass
class Command:
    name: str
    help: str
    fn: Callable
    needs_lock: bool = False


def command(name: str, help: str, needs_lock: bool = False,
            aliases: tuple = ()):
    """`aliases` carries the reference's exact Name() spellings (e.g.
    volumeServer.evacuate) so migrating operators find them; registered
    at import time alongside the canonical name."""
    def deco(fn):
        COMMANDS[name] = Command(name, help, fn, needs_lock)
        for a in aliases:
            COMMANDS[a] = Command(a, f"alias of {name}", fn, needs_lock)
        return fn
    return deco


@dataclass
class CommandEnv:
    master_address: str
    mc: MasterClient = None
    lock_token: int = 0
    lock_time: int = 0
    out: TextIO = None
    option: dict = field(default_factory=dict)

    def __post_init__(self):
        if self.mc is None:
            self.mc = MasterClient(self.master_address, client_type="shell")
        if self.out is None:
            import sys
            self.out = sys.stdout

    def println(self, *args) -> None:
        print(*args, file=self.out)

    # -- exclusive lock (reference command_lock_unlock.go) ------------------
    def acquire_lock(self) -> None:
        stub = Stub(self.mc.leader, MASTER_SERVICE)
        resp = stub.call("LeaseAdminToken", mpb.LeaseAdminTokenRequest(
            previous_token=self.lock_token, previous_lock_time=self.lock_time,
            lock_name="admin", client_name="shell"),
            mpb.LeaseAdminTokenResponse)
        self.lock_token, self.lock_time = resp.token, resp.lock_ts_ns

    def release_lock(self) -> None:
        if not self.lock_token:
            return
        stub = Stub(self.mc.leader, MASTER_SERVICE)
        stub.call("ReleaseAdminToken", mpb.ReleaseAdminTokenRequest(
            previous_token=self.lock_token, previous_lock_time=self.lock_time,
            lock_name="admin"), mpb.ReleaseAdminTokenResponse)
        self.lock_token = 0

    def confirm_is_locked(self) -> None:
        if not self.lock_token:
            raise RuntimeError(
                "this command requires the exclusive cluster lock; run 'lock' first")

    # -- helpers shared by commands -----------------------------------------
    def topology(self) -> mpb.TopologyInfo:
        return self.mc.volume_list().topology_info

    def collect_volume_servers(self) -> list[dict]:
        out = []
        for dc in self.topology().data_center_infos:
            for rack in dc.rack_infos:
                for node in rack.data_node_infos:
                    out.append({"id": node.id, "grpc_port": node.grpc_port,
                                "dc": dc.id, "rack": rack.id,
                                "disks": node.disk_infos})
        return out

    def grpc_addr(self, node_id: str, grpc_port: int) -> str:
        return f"{node_id.rsplit(':', 1)[0]}:{grpc_port}"


def run_command(env: CommandEnv, line: str) -> bool:
    """Parse and run one shell line. Returns False on 'exit'."""
    parts = shlex.split(line.strip())
    if not parts:
        return True
    name, args = parts[0], parts[1:]
    if name in ("exit", "quit"):
        return False
    if name == "help":
        for c in sorted(COMMANDS.values(), key=lambda c: c.name):
            env.println(f"  {c.name:32s} {c.help}")
        return True
    cmd = COMMANDS.get(name)
    if cmd is None:
        env.println(f"unknown command {name!r}; try 'help'")
        return True
    if cmd.needs_lock:
        env.confirm_is_locked()
    t0 = time.monotonic()
    cmd.fn(env, args)
    if env.option.get("timing"):
        env.println(f"({time.monotonic() - t0:.2f}s)")
    return True


def repl(env: CommandEnv) -> None:
    env.println(f"swtpu shell connected to {env.master_address}; 'help' lists commands")
    while True:
        try:
            line = input("> ")
        except EOFError:
            break
        try:
            if not run_command(env, line):
                break
        except Exception as e:  # noqa: BLE001
            env.println(f"error: {e}")
    env.release_lock()



def list_cluster_nodes(env: "CommandEnv", client_type: str) -> list:
    """Live nodes of a type from the master cluster list (cluster.go:104),
    oldest first; [] on any error. THE single ListClusterNodes call site
    for shell helpers so fixes (grpc ports, retries) land once."""
    from ..pb import master_pb2 as mpb
    from ..utils.rpc import MASTER_SERVICE
    try:
        resp = Stub(env.mc.leader, MASTER_SERVICE).call(
            "ListClusterNodes",
            mpb.ListClusterNodesRequest(client_type=client_type),
            mpb.ListClusterNodesResponse)
        return sorted(resp.cluster_nodes, key=lambda n: n.created_at_ns)
    except Exception:  # noqa: BLE001
        return []


def discover_cluster_node(env: "CommandEnv", client_type: str
                          ) -> "tuple[str, int]":
    """Oldest live node of a type: ('', 0) if none."""
    try:
        nodes = list_cluster_nodes(env, client_type)
        if nodes:
            return nodes[0].address, nodes[0].grpc_port
    except Exception:  # noqa: BLE001  # swtpu-lint: disable=silent-except (no such node type yet; caller reports)
        pass
    return "", 0
