"""ec.* commands — the north-star admin pipeline.

Reference: weed/shell/command_ec_encode.go:61 (Do), :187 (spreadEcShards),
:333 (balancedEcDistribution), command_ec_rebuild.go:100,
command_ec_balance.go, command_ec_decode.go. Fork semantics honored: source
volumes can be filtered to SSD (-sourceDiskType), shards move with
VolumeEcShardsMove, rebuilds can use CopyByRebuild.
"""

from __future__ import annotations

import argparse
import time

from ..pb import volume_server_pb2 as vpb
from ..utils.rpc import Stub, VOLUME_SERVICE
from .commands import CommandEnv, command


def _stub(env: CommandEnv, srv: dict) -> Stub:
    return Stub(env.grpc_addr(srv["id"], srv["grpc_port"]), VOLUME_SERVICE)


def parse_ec_shards(spec: str) -> tuple[int, int]:
    """'d,p' -> (d, p); the one grammar every -ecShards flag shares."""
    try:
        d_s, p_s = spec.split(",")
        d, p = int(d_s), int(p_s)
    except ValueError:
        raise ValueError(f"-ecShards wants 'd,p' (e.g. 10,4), got {spec!r}"
                         ) from None
    if d <= 0 or p <= 0 or d + p > 256:
        raise ValueError(f"invalid RS geometry ({d},{p})")
    return d, p


def _ec_holders(env: CommandEnv, vid: int) -> dict[int, list[dict]]:
    """shard id -> servers holding it."""
    out: dict[int, list[dict]] = {}
    for srv in env.collect_volume_servers():
        for disk in srv["disks"].values():
            for s in disk.ec_shard_infos:
                if s.id == vid:
                    for sid in range(32):
                        if s.ec_index_bits >> sid & 1:
                            out.setdefault(sid, []).append(srv)
    return out


def _settled_ec_holders(env: CommandEnv, vid: int,
                        tries: int = 20, interval: float = 0.2
                        ) -> dict[int, list[dict]]:
    """Master topology is heartbeat-propagated (eventually consistent); after
    mount/unmount RPCs the view lags by up to a pulse. Poll until two
    consecutive reads agree before acting on it."""
    prev = None
    holders = _ec_holders(env, vid)
    for _ in range(tries):
        cur = {sid: sorted(h["id"] for h in hs) for sid, hs in holders.items()}
        if prev is not None and cur == prev:
            break
        prev = cur
        time.sleep(interval)
        holders = _ec_holders(env, vid)
    return holders


def _free_slots(srv: dict) -> int:
    return sum(d.free_volume_count for d in srv["disks"].values())


def balanced_ec_distribution(servers: list[dict], n_shards: int,
                             parity: int = 0, vid: int = 0) -> list[dict]:
    """Shard -> server assignment through the placement engine
    (placement/engine.py spread_ec_shards): scored by free slots, byte
    load and breaker state, and RACK-CAPPED — no rack holds more than
    `parity` shards of the stripe, so a rack loss stays reconstructable
    (degrades gracefully to the most-even spread when the fleet has too
    few racks). parity=0 keeps the legacy free-slot ranking semantics
    with no rack cap (the reference command_ec_encode.go:333 shape)."""
    if not servers:
        raise RuntimeError("no volume servers")
    from ..placement import snapshot_from_servers, spread_ec_shards
    snap = snapshot_from_servers(servers)
    by_id = {s["id"]: s for s in servers}
    views = spread_ec_shards(snap, n_shards,
                             parity if parity > 0 else n_shards, vid=vid)
    return [by_id[v.id] for v in views]


def _codec_names() -> "list[str]":
    """Registered erasure codecs — any codec behind the ErasureCoder
    seam shows up in help/validation without editing this file. Called
    at parse time, never at import (the lazy codec registry exists so a
    help string doesn't eagerly import every codec module)."""
    from ..ops.coder import registered_codecs
    return registered_codecs()


@command("ec.encode",
         "-volumeId N | -collection C|'*' [-fullPercent 95] "
         "[-sourceDiskType ssd] [-ecShards d,p] [-codec NAME]: "
         "erasure-code volumes and spread shards (geometry defaults to the "
         "server's -ecShards; fork 14+2 and upstream 10+4 both just work; "
         "-codec takes any registered erasure codec — ec.encode -h "
         "enumerates them; piggyback and msr are repair-efficient)",
         needs_lock=True)
def cmd_ec_encode(env: CommandEnv, args):
    p = argparse.ArgumentParser(prog="ec.encode")
    p.add_argument("-volumeId", type=int, default=0)
    p.add_argument("-collection", default=None)
    p.add_argument("-fullPercent", type=float, default=95.0)
    p.add_argument("-sourceDiskType", default="")
    p.add_argument("-dataShards", type=int, default=0)
    p.add_argument("-parityShards", type=int, default=0)
    p.add_argument("-ecShards", default="",
                   help="geometry as 'd,p' (e.g. 14,2 or 10,4); shorthand "
                        "for -dataShards/-parityShards")
    p.add_argument("-codec", default="",
                   help=f"erasure codec: {' | '.join(_codec_names())} "
                        "(blank = server default; piggyback and msr are "
                        "repair-efficient)")
    opt = p.parse_args(args)
    if opt.codec and opt.codec not in _codec_names():
        raise ValueError(f"unknown codec {opt.codec!r}; registered: "
                         f"{', '.join(_codec_names())}")
    if opt.ecShards:
        opt.dataShards, opt.parityShards = parse_ec_shards(opt.ecShards)

    limit = env.mc.volume_list().volume_size_limit_mb * (1 << 20)
    targets = []  # (vid, collection, srv)
    for srv in env.collect_volume_servers():
        for dtype, disk in srv["disks"].items():
            if opt.sourceDiskType and dtype != opt.sourceDiskType:
                continue  # fork: EC source restricted by disk type
            for v in disk.volume_infos:
                if opt.volumeId and v.id != opt.volumeId:
                    continue
                if not opt.volumeId:
                    if opt.collection is None or (
                            opt.collection != "*"
                            and v.collection != opt.collection):
                        continue
                    if limit and v.size < limit * opt.fullPercent / 100:
                        continue
                targets.append((v.id, v.collection, srv))
    seen = set()
    targets = [t for t in targets
               if t[0] not in seen and not seen.add(t[0])]
    if not targets:
        env.println("no volumes eligible for ec encoding")
        return
    # group by source server so each server encodes ALL its volumes through
    # one shared device stream (VolumeEcShardsGenerateBatch; ec/stream.py) —
    # the reference loops per volume instead (command_ec_encode.go:113-126)
    by_src: dict[tuple[str, str], tuple[dict, list[tuple[int, str]]]] = {}
    for vid, collection, srv in targets:
        by_src.setdefault((srv["id"], collection),
                          (srv, []))[1].append((vid, collection))
    encoded = 0
    for srv, vols in by_src.values():
        encoded += _encode_on_server(env, srv, vols, opt)
    env.println(f"ec encoded {encoded} volumes")


def _encode_on_server(env: CommandEnv, srv: dict,
                      vols: "list[tuple[int, str]]", opt) -> int:
    """Freeze + batch-generate + spread one server's volumes. A failure
    rolls the un-encoded volumes back to writable and never aborts other
    servers' batches (caller loops on)."""
    stub = _stub(env, srv)
    collection = vols[0][1]
    vids = [v for v, _ in vols]
    env.println(f"  ec.encode volumes {vids} on {srv['id']} (batched)")
    frozen = []
    for vid, _c in vols:  # freeze writes (command_ec_encode.go:147)
        stub.call("VolumeMarkReadonly",
                  vpb.VolumeMarkReadonlyRequest(volume_id=vid),
                  vpb.VolumeMarkReadonlyResponse)
        frozen.append(vid)
    done: list[int] = []
    d = p = 0
    try:
        gen = stub.call("VolumeEcShardsGenerateBatch",
                        vpb.VolumeEcShardsGenerateBatchRequest(
                            volume_ids=vids, collection=collection,
                            data_shards=opt.dataShards,
                            parity_shards=opt.parityShards,
                            codec=getattr(opt, "codec", "")),
                        vpb.VolumeEcShardsGenerateBatchResponse,
                        timeout=3600 * len(vids))
        done = list(gen.encoded_volume_ids)
        d, p = gen.data_shards, gen.parity_shards
        if gen.codec:
            env.println(f"    codec {gen.codec} RS({d},{p})")
    except Exception as e:  # noqa: BLE001
        env.println(f"    batch generate failed on {srv['id']}: {e}")
    for vid in frozen:
        if vid not in done:  # rollback: un-encoded volumes take writes again
            try:
                stub.call("VolumeMarkWritable",
                          vpb.VolumeMarkWritableRequest(volume_id=vid),
                          vpb.VolumeMarkWritableResponse)
            except Exception:  # noqa: BLE001  # swtpu-lint: disable=silent-except (best-effort rollback of mark-readonly)
                pass
    coll_by_vid = dict(vols)
    for vid in done:
        _spread_and_clean(env, vid, coll_by_vid.get(vid, collection), srv, d, p)
    return len(done)


def _spread_and_clean(env: CommandEnv, vid: int, collection: str, srv: dict,
                      d: int, p: int) -> None:
    """Distribute generated shards and delete the source volume
    (reference command_ec_encode.go:187 spreadEcShards)."""
    stub = _stub(env, srv)
    if not d or not p:
        # the batch response didn't carry the geometry (pre-geometry
        # server): ask the holder for the SEALED (d,p) instead of
        # assuming an RS default — the fork's stale "10.4" bug class,
        # where help text and fallbacks hardcode one geometry while the
        # .vif is the source of truth
        info = stub.call("VolumeEcShardsInfo",
                         vpb.VolumeEcShardsInfoRequest(
                             volume_id=vid, collection=collection),
                         vpb.VolumeEcShardsInfoResponse, timeout=30)
        d = d or info.data_shards
        p = p or info.parity_shards
    n_shards = (d or 10) + (p or 4)
    # 3. spread (command_ec_encode.go:187): copy to targets, mount, clean
    # src — rack-capped at p shards per rack so rack loss != data loss
    servers = env.collect_volume_servers()
    placement = balanced_ec_distribution(servers, n_shards,
                                         parity=(p or 4), vid=vid)
    by_server: dict[str, tuple[dict, list[int]]] = {}
    for sid, target in enumerate(placement):
        by_server.setdefault(target["id"], (target, []))[1].append(sid)
    src_grpc = env.grpc_addr(srv["id"], srv["grpc_port"])
    for tid, (target, sids) in by_server.items():
        if tid != srv["id"]:
            _stub(env, target).call(
                "VolumeEcShardsCopy",
                vpb.VolumeEcShardsCopyRequest(
                    volume_id=vid, collection=collection, shard_ids=sids,
                    copy_ecx_file=True, copy_ecj_file=True, copy_vif_file=True,
                    source_data_node=src_grpc),
                vpb.VolumeEcShardsCopyResponse, timeout=3600)
        _stub(env, target).call(
            "VolumeEcShardsMount",
            vpb.VolumeEcShardsMountRequest(volume_id=vid, collection=collection,
                                           shard_ids=sids),
            vpb.VolumeEcShardsMountResponse)
        env.println(f"    shards {sids} -> {tid}")
    # 4. delete shards that moved away from source + the original volume
    keep = by_server.get(srv["id"], (None, []))[1]
    moved = [s for s in range(n_shards) if s not in keep]
    if moved:
        stub.call("VolumeEcShardsUnmount",
                  vpb.VolumeEcShardsUnmountRequest(volume_id=vid, shard_ids=moved),
                  vpb.VolumeEcShardsUnmountResponse)
        stub.call("VolumeEcShardsDelete",
                  vpb.VolumeEcShardsDeleteRequest(volume_id=vid,
                                                  collection=collection,
                                                  shard_ids=moved),
                  vpb.VolumeEcShardsDeleteResponse)
    stub.call("VolumeDelete", vpb.VolumeDeleteRequest(volume_id=vid),
              vpb.VolumeDeleteResponse)


@command("ec.rebuild", "[-volumeId N] [-byRebuild]: restore missing ec "
         "shards (geometry and codec follow each volume's sealed .vif, "
         "never a fixed RS default)", needs_lock=True)
def cmd_ec_rebuild(env: CommandEnv, args):
    """Rebuild runs ON a holder; remote survivors stream in by RANGE —
    or as packed computed fragments through VolumeEcShardRead's
    ranged-compute mode — following the volume's codec repair plan: a
    piggybacked stripe moves ~(d+|group|)/2 half-shards for a single
    data-shard loss, an msr stripe (n-1)/p shard-equivalents for ANY
    single loss, where the old gather-then-rebuild flow copied d full
    shard files before reconstructing anything. Returns
    {rebuilt, bytes_read, bytes_written} so callers (cluster.repair)
    can journal the traffic."""
    p = argparse.ArgumentParser(prog="ec.rebuild")
    p.add_argument("-volumeId", type=int, default=0)
    p.add_argument("-byRebuild", action="store_true",
                   help="use the fork's CopyByRebuild RPC on a fresh server")
    opt = p.parse_args(args)
    # find all ec volumes and their shard coverage
    vols: dict[int, tuple[str, dict[int, list[dict]]]] = {}
    for srv in env.collect_volume_servers():
        for disk in srv["disks"].values():
            for s in disk.ec_shard_infos:
                if opt.volumeId and s.id != opt.volumeId:
                    continue
                vols.setdefault(s.id, (s.collection, {}))
    summary = {"rebuilt": 0, "bytes_read": 0, "bytes_written": 0}
    for vid, (collection, _) in sorted(vols.items()):
        holders = _settled_ec_holders(env, vid)
        if not holders:
            continue
        # geometry: n = max(shard ids)+1 is unreliable; read from a holder
        have = sorted(holders)
        any_srv = holders[have[0]][0]
        n = _probe_n_shards(env, any_srv, vid, collection)
        missing = [s for s in range(n) if s not in holders]
        if not missing:
            continue
        env.println(f"  ec volume {vid}: missing shards {missing}")
        if opt.byRebuild:
            # fork path: rebuild directly onto the least-loaded server
            target = balanced_ec_distribution(
                env.collect_volume_servers(), 1)[0]
            resp = _stub(env, target).call(
                "VolumeEcShardsCopyByRebuild",
                vpb.VolumeEcShardsCopyByRebuildRequest(
                    volume_id=vid, collection=collection, shard_ids=missing),
                vpb.VolumeEcShardsCopyByRebuildResponse, timeout=3600)
            host = target
        else:
            # default: rebuild on the holder with the most local shards
            # (fewest remote ranges to pull); deterministic on ties
            counts: dict[str, list] = {}
            for _sid, hs in holders.items():
                for h in hs:
                    counts.setdefault(h["id"], [0, h])
                    counts[h["id"]][0] += 1
            host = sorted(counts.items(),
                          key=lambda kv: (-kv[1][0], kv[0]))[0][1][1]
            resp = _stub(env, host).call(
                "VolumeEcShardsRebuild",
                vpb.VolumeEcShardsRebuildRequest(volume_id=vid,
                                                 collection=collection),
                vpb.VolumeEcShardsRebuildResponse, timeout=3600)
        if resp.rebuilt_shard_ids:
            _stub(env, host).call(
                "VolumeEcShardsMount",
                vpb.VolumeEcShardsMountRequest(
                    volume_id=vid, collection=collection,
                    shard_ids=list(resp.rebuilt_shard_ids)),
                vpb.VolumeEcShardsMountResponse)
        env.println(f"    rebuilt {sorted(resp.rebuilt_shard_ids)} on "
                    f"{host['id']}: {resp.bytes_read} B read / "
                    f"{resp.bytes_written} B written")
        summary["rebuilt"] += len(resp.rebuilt_shard_ids)
        summary["bytes_read"] += resp.bytes_read
        summary["bytes_written"] += resp.bytes_written
    env.println(f"rebuilt {summary['rebuilt']} shards "
                f"({summary['bytes_read']} survivor bytes read)")
    return summary


def _gather_shards(env: CommandEnv, host_stub: Stub, vid: int, collection: str,
                   fetch: list[int], holders: dict[int, list[dict]]) -> None:
    """Copy each shard in `fetch` onto the host from a server that actually
    holds it (per-shard source), including the index sidecars. Holders come
    from eventually-consistent master state, so try every listed holder and
    refresh the view on failure."""
    first = True
    for sid in fetch:
        hs = list(holders.get(sid) or [])
        last_err: Exception | None = None
        copied = False
        for attempt in range(6):
            for src in hs:
                try:
                    host_stub.call(
                        "VolumeEcShardsCopy",
                        vpb.VolumeEcShardsCopyRequest(
                            volume_id=vid, collection=collection,
                            shard_ids=[sid],
                            copy_ecx_file=first, copy_ecj_file=first,
                            copy_vif_file=first,
                            source_data_node=env.grpc_addr(
                                src["id"], src["grpc_port"])),
                        vpb.VolumeEcShardsCopyResponse, timeout=3600)
                    copied = True
                    break
                except Exception as e:  # noqa: BLE001
                    last_err = e
            if copied or not hs and attempt > 2:
                break
            if not copied:
                time.sleep(0.3)
                hs = list(_ec_holders(env, vid).get(sid) or [])
        if not copied:
            if last_err is None:
                continue  # no holder anywhere: leave it to rebuild
            raise RuntimeError(
                f"gather shard {vid}.{sid} failed from all holders: {last_err}")
        first = False


def _probe_n_shards(env: CommandEnv, srv: dict, vid: int, collection: str) -> int:
    """Ask a holder for the volume's real geometry (VolumeEcShardsInfo reads
    the .vif); fall back to the reference default 14 only if the RPC fails."""
    try:
        resp = _stub(env, srv).call(
            "VolumeEcShardsInfo",
            vpb.VolumeEcShardsInfoRequest(volume_id=vid, collection=collection),
            vpb.VolumeEcShardsInfoResponse)
        if resp.data_shards:
            return resp.data_shards + resp.parity_shards
    except Exception:  # noqa: BLE001  # swtpu-lint: disable=silent-except (pre-geometry-RPC server: fork default)
        pass
    return 14


@command("ec.balance",
         "[-dryRun] [-collection C] [-maxMoves 64]: spread ec shards "
         "evenly across servers, rack-safety-capped")
def cmd_ec_balance(env: CommandEnv, args):
    """Thin shell over the placement plane (seaweedfs_tpu/placement/):
    ONE topology snapshot plans every move (the old loop re-ran the
    settled-holder poll + a full cluster collect per single shard), all
    shards of a stripe moving between one (src, dst) pair ride ONE
    VolumeEcShardsMove RPC, no rack ends up holding more than the
    stripe's parity count, and every hop is maintenance-class through
    the QoS plane with its byte cost journaled. -dryRun prints the
    exact plan and performs zero mutating RPCs."""
    from ..maintenance import make_probes
    from ..placement import (BalanceExecutor, build_ec_balance_plan,
                             snapshot_from_servers)

    p = argparse.ArgumentParser(prog="ec.balance")
    p.add_argument("-dryRun", action="store_true",
                   help="print the plan, mutate nothing")
    p.add_argument("-collection", default=None,
                   help="balance only this collection's stripes")
    p.add_argument("-maxMoves", type=int, default=64)
    p.add_argument("-url", default="",
                   help="master HTTP base URL (fetches its -linkCosts "
                        "policy so plans price moves like the cron)")
    p.add_argument("-linkCosts", default="",
                   help="geo link-cost policy (inline JSON or file); "
                        "overrides the master's")
    opt = p.parse_args(args)

    # stripes can drift for a pulse after encode/rebuild RPCs; settle
    # one stripe's holder view (two consecutive identical reads) before
    # snapshotting so the plan isn't built mid-heartbeat — ONCE, not
    # once per move like the old loop
    any_vid = next((s.id for srv in env.collect_volume_servers()
                    for disk in srv["disks"].values()
                    for s in disk.ec_shard_infos), None)
    if any_vid is None:
        env.println("no ec shards to balance")
        return
    _settled_ec_holders(env, any_vid, tries=5)
    _remount_probe, geometry_probe = make_probes(env)

    def parity_of(vid: int, collection: str) -> "int | None":
        g = geometry_probe(vid, collection)
        return g.get("p") if g else None

    def shard_bytes_of(vid: int, collection: str) -> "int | None":
        g = geometry_probe(vid, collection)
        return g.get("shard_size") if g else None

    limit_mb = env.mc.volume_list().volume_size_limit_mb or 30_000
    snap = snapshot_from_servers(
        env.collect_volume_servers(), shard_bytes_of=shard_bytes_of,
        default_shard_bytes=(limit_mb << 20) // 10)
    from .health_util import fetch_link_costs
    plan = build_ec_balance_plan(snap, collection=opt.collection,
                                 parity_of=parity_of,
                                 max_moves=opt.maxMoves,
                                 costs=fetch_link_costs(opt.url,
                                                        opt.linkCosts))
    plan.render(env.println)
    if opt.dryRun:
        BalanceExecutor(env).execute(plan, dry_run=True)
        env.println("dry run: nothing executed")
        return
    had_lock = bool(env.lock_token)
    env.acquire_lock()
    try:
        res = BalanceExecutor(env, max_moves=opt.maxMoves).execute(plan)
    finally:
        if not had_lock:
            try:
                env.release_lock()
            except Exception:  # noqa: BLE001  # swtpu-lint: disable=silent-except (lease already expired/released)
                pass
    moved = sum(len(m["shard_ids"]) for m in res["done"])
    env.println(f"moved {moved} shards in {len(res['done'])} grouped "
                f"move(s), {len(res['failed'])} failed")
    for f in res["failed"]:
        env.println(f"  FAILED ec {f['vid']} shards {f['shard_ids']} "
                    f"{f['src']} -> {f['dst']}: {f['error']}")


@command("ec.decode", "-volumeId N: convert ec shards back to a normal "
         "volume (decodes with the codec and (data,parity) sealed in the "
         "volume's .vif)", needs_lock=True)
def cmd_ec_decode(env: CommandEnv, args):
    p = argparse.ArgumentParser(prog="ec.decode")
    p.add_argument("-volumeId", type=int, required=True)
    opt = p.parse_args(args)
    vid = opt.volumeId
    holders = _settled_ec_holders(env, vid)
    if not holders:
        env.println(f"no ec shards for volume {vid}")
        return
    # gather all shards onto one holder then ShardsToVolume
    servers = {h["id"]: h for hs in holders.values() for h in hs}
    host = next(iter(servers.values()))
    collection = ""
    for srv in env.collect_volume_servers():
        for disk in srv["disks"].values():
            for s in disk.ec_shard_infos:
                if s.id == vid:
                    collection = s.collection
    host_stub = _stub(env, host)
    host_sids = {s for s, hs in holders.items()
                 if any(h["id"] == host["id"] for h in hs)}
    fetch = sorted(s for s in holders if s not in host_sids)
    if fetch:
        _gather_shards(env, host_stub, vid, collection, fetch, holders)
        host_stub.call("VolumeEcShardsMount",
                       vpb.VolumeEcShardsMountRequest(
                           volume_id=vid, collection=collection,
                           shard_ids=fetch),
                       vpb.VolumeEcShardsMountResponse)
    host_stub.call("VolumeEcShardsToVolume",
                   vpb.VolumeEcShardsToVolumeRequest(volume_id=vid,
                                                     collection=collection),
                   vpb.VolumeEcShardsToVolumeResponse, timeout=3600)
    # drop leftover shards elsewhere
    for sid, hs in holders.items():
        for h in hs:
            if h["id"] == host["id"]:
                continue
            _stub(env, h).call(
                "VolumeEcShardsUnmount",
                vpb.VolumeEcShardsUnmountRequest(volume_id=vid, shard_ids=[sid]),
                vpb.VolumeEcShardsUnmountResponse)
            _stub(env, h).call(
                "VolumeEcShardsDelete",
                vpb.VolumeEcShardsDeleteRequest(volume_id=vid,
                                                collection=collection,
                                                shard_ids=[sid]),
                vpb.VolumeEcShardsDeleteResponse)
    env.println(f"decoded ec volume {vid} back to a normal volume on {host['id']}")


@command("ec.volume.delete", "-volumeId N [-collection C]: delete an ec "
         "volume's shards everywhere", needs_lock=True,
         aliases=("ecVolume.delete",))
def cmd_ec_volume_delete(env: CommandEnv, args):
    """Reference command_ecVolume_delete.go (fork)."""
    p = argparse.ArgumentParser(prog="ec.volume.delete")
    p.add_argument("-volumeId", type=int, required=True)
    p.add_argument("-collection", default="")
    opt = p.parse_args(args)
    removed = 0
    for srv in env.collect_volume_servers():
        sids: list[int] = []
        for disk in srv["disks"].values():
            for s in disk.ec_shard_infos:
                if s.id != opt.volumeId:
                    continue
                sids.extend(i for i in range(32)
                            if s.ec_index_bits & (1 << i) and i not in sids)
        if not sids:
            continue
        stub = _stub(env, srv)
        stub.call("VolumeEcShardsUnmount",
                  vpb.VolumeEcShardsUnmountRequest(volume_id=opt.volumeId,
                                                   shard_ids=sids),
                  vpb.VolumeEcShardsUnmountResponse)
        stub.call("VolumeEcShardsDelete",
                  vpb.VolumeEcShardsDeleteRequest(volume_id=opt.volumeId,
                                                  collection=opt.collection,
                                                  shard_ids=sids),
                  vpb.VolumeEcShardsDeleteResponse)
        removed += len(sids)
        env.println(f"  removed shards {sids} from {srv['id']}")
    env.println(f"deleted ec volume {opt.volumeId} ({removed} shards)")
